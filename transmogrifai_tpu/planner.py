"""Cost-based whole-DAG plan analyzer — the KeystoneML/Flare middle-end.

TransmogrifAI inherits Catalyst's whole-pipeline view but never exploits
it; KeystoneML's pipeline-level cost optimizer and Flare's whole-query
native compilation (PAPERS.md) show what a middle-end buys when the DAG
is analyzed *before* execution. This module is that middle-end for the
TPU runtime: it abstractly interprets the whole feature DAG — reusing
lint.py's synthetic typed store discipline, so **no dataset is read and
no device is dispatched** — and emits an explainable
:class:`ExecutionPlan` that ``Workflow`` fitting and the compiled
scoring engine then follow.

Four analyses run over the abstract DAG:

* **Dead-column pruning** — column-granular liveness propagated from the
  sinks (result features, predictors) backward through the fused
  select/scale/combine chain, extending TMG104's stage-granular orphan
  detection to individual vector slots: vectorizer output columns the
  sanity checker drops before the predictor are dead in the device
  program, and the scoring engine slices them off right after
  ``device_compute`` (gather-of-concat == concat-of-gathers, so results
  are bit-identical by construction).
* **Cross-stage CSE** — structurally identical stages (same class, same
  non-uid params, same input features, and — for fitted models —
  bit-identical fitted state) are deduplicated to ONE computation with
  fan-out in the scoring engine's device program. Merges are only
  emitted after the fitted-state equality check, so aliased outputs are
  bit-identical to the unplanned run by construction; near-misses that
  differ only in uid-sensitive params surface as TMG403 advisories.
* **Per-stage tier assignment** — host vs device vs fused decided per
  stage (and per heavy phase: scoring engine, fused fit-stats pass,
  transform-layer fusion) from a persisted :class:`CostDatabase` of
  measured compile/execute/transfer costs, written atomically alongside
  the compile cache. When no measurement exists, documented static
  fallback estimates from the abstract shapes apply, and the old global
  ``FUSE_MIN_BANDWIDTH_MBPS`` gate degrades to exactly what it should
  be: the cold-start bandwidth *prior*, not a hard per-process switch.
* **Plan explanation** — a stable, diffable report (per-stage tier +
  reason + estimated vs measured cost, pruned columns, CSE merges)
  stamped into every runner metrics doc under ``plan``, surfaced via
  ``python -m transmogrifai_tpu plan params.json [--model DIR]``, and
  mirrored into lint as the TMG4xx advisory rule family so plan
  findings flow through the existing ``failOn``/suppress/telemetry
  machinery.

Static fallback cost model (per 1000 rows, used when the cost database
has no measurement for a stage class):

* ``host``  = bytes/krow ÷ :data:`STATIC_HOST_GBPS` — numpy streaming
  throughput over the stage's input+prepared bytes;
* ``device`` = bytes/krow ÷ link (the db's measured bandwidth, else the
  ``FUSE_MIN_BANDWIDTH_MBPS`` prior) + bytes/krow ÷
  :data:`STATIC_DEVICE_GBPS` — transfer plus HBM-bound compute.

Both are deliberately coarse: they only need to rank tiers sensibly
until a measurement lands in the db, and every plan entry says which
source (``measured``/``static``) produced its decision.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "CostDatabase", "ExecutionPlan", "PlanEntry",
    "plan_model", "plan_workflow", "record_fit_costs",
    "default_cost_db_path", "planner_stats", "reset_planner_stats",
    "COST_DB_FILENAME", "STATIC_HOST_GBPS", "STATIC_DEVICE_GBPS",
]

#: cost database file name, persisted alongside the XLA compile cache
#: (same lifecycle: a warm directory makes the next process smarter)
COST_DB_FILENAME = "tmog_cost_db.json"

#: static prior: host numpy streams a transform at about this rate
STATIC_HOST_GBPS = 1.0

#: static prior: device elementwise transform work is HBM-bound at
#: roughly this rate (per-chip; deliberately conservative)
STATIC_DEVICE_GBPS = 50.0

# ---------------------------------------------------------------------------
# always-on tallies (bench stamps these on every doc, like fitstats)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"plans_built": 0, "cse_merges": 0, "pruned_columns": 0,
          "stages_fused": 0, "stages_host": 0}


def planner_stats() -> Dict[str, int]:
    """Process-wide planner tallies (always on, cheap — the
    ``fitstats_stats`` discipline): plans built, CSE merges found,
    dead columns found, per-tier stage counts."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_planner_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


# ---------------------------------------------------------------------------
# phase-cost observations — how the measured per-phase tiers get fed
# ---------------------------------------------------------------------------

#: pending (phase, tier, seconds, rows) observations reported by the
#: fused stats pass and the transform-layer fusion as they execute;
#: the runner drains them into the persisted cost db after a train.
#: Bounded so a drain-less process cannot grow it without limit.
_OBS_LOCK = threading.Lock()
_PHASE_OBS: List[Tuple[str, str, float, int]] = []
_PHASE_OBS_CAP = 4096


def observe_phase(phase: str, tier: str, seconds: float,
                  rows: int) -> None:
    """Record one measured phase execution (``phase`` in
    ``fitstats``/``transform``, ``tier`` in ``host``/``device``).
    Always on and cheap (a lock + append); callers only report rows
    counts where the tier decision is actually contested (at or above
    the fusion row floor), so the two tiers' s/krow stay comparable."""
    if rows <= 0 or seconds < 0:
        return
    with _OBS_LOCK:
        if len(_PHASE_OBS) < _PHASE_OBS_CAP:
            _PHASE_OBS.append((str(phase), str(tier), float(seconds),
                               int(rows)))


def drain_phase_observations(db: "CostDatabase") -> int:
    """Fold every pending phase observation into ``db`` (as
    ``phase:<name>`` stage entries — what :func:`_phase_tier` reads)
    and clear the buffer; returns the count drained."""
    with _OBS_LOCK:
        obs = list(_PHASE_OBS)
        del _PHASE_OBS[:]
    for phase, tier, s, rows in obs:
        db.record_stage(f"phase:{phase}", tier, s, rows)
    return len(obs)


# ---------------------------------------------------------------------------
# cost database — measured costs persisted next to the compile cache
# ---------------------------------------------------------------------------


def default_cost_db_path(compile_cache_dir: Optional[str]) -> Optional[str]:
    """Where the cost database lives for a given persistent compile
    cache directory (None when no cache is configured — the db is then
    in-memory only and static estimates rule)."""
    if not compile_cache_dir:
        return None
    return os.path.join(str(compile_cache_dir), COST_DB_FILENAME)


class CostDatabase:
    """Measured per-stage-class and whole-chain costs, JSON-persisted.

    Schema (``version`` 1)::

        {"version": 1,
         "bandwidth_mbps": 1234.5 | null,   # SUSTAINED link (pipeline's
                                            # double-buffered path — the
                                            # number tier decisions use)
         "probe_mbps": 23.4 | null,         # cold single-shot round trip
         "chain": {"engine_s_per_krow": ..., "host_s_per_krow": ...},
         "stages": {"<StageClass>": {
             "fit":    {"s_per_krow": ..., "n": k},
             "host":   {"s_per_krow": ..., "n": k},
             "device": {"s_per_krow": ..., "n": k}}}}

    Writes reuse the runner's atomic temp + ``os.replace`` discipline; a
    corrupt/truncated db **never crashes** — it loads as a fresh db with
    ``corrupt=True`` and a TMG404 warning finding, and static estimates
    rule until new measurements land.
    """

    VERSION = 1

    def __init__(self, path: Optional[str] = None,
                 doc: Optional[Dict[str, Any]] = None,
                 corrupt: bool = False):
        self.path = path
        self.corrupt = corrupt
        self.doc: Dict[str, Any] = doc if doc is not None else {
            "version": self.VERSION, "bandwidth_mbps": None,
            "chain": {}, "stages": {}}

    # -- persistence -------------------------------------------------------
    @classmethod
    def load(cls, path: Optional[str]) -> "CostDatabase":
        """Load from ``path``; a missing file is a fresh db, a corrupt or
        truncated one is a fresh db flagged ``corrupt`` (TMG404) — never
        an exception."""
        if not path or not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path) as fh:
                doc = json.load(fh)
            if (not isinstance(doc, dict)
                    or not isinstance(doc.get("stages"), dict)
                    or doc.get("version") != cls.VERSION):
                raise ValueError(f"unexpected cost-db structure in {path}")
        except (OSError, ValueError) as e:
            # json.JSONDecodeError is a ValueError: truncated/corrupt
            # files land here, degrade to static estimates with a finding
            logger.warning("cost database %s unreadable (%s); static "
                           "estimates in force", path, e)
            return cls(path=path, corrupt=True)
        doc.setdefault("bandwidth_mbps", None)
        doc.setdefault("probe_mbps", None)
        doc.setdefault("chain", {})
        return cls(path=path, doc=doc)

    def save(self, path: Optional[str] = None) -> bool:
        """Atomic write (temp + ``os.replace``, the ``_write_metrics``
        discipline): a kill mid-write can never leave a truncated db for
        the next process to trip over. Coordinator-only in multi-host
        runs (every process computes identical costs)."""
        path = path or self.path
        if not path:
            return False
        from .parallel.multihost import is_coordinator
        if not is_coordinator():
            return False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(self.doc, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        self.path = path
        return True

    def finding(self):
        """The TMG404 warning when this db loaded corrupt, else None."""
        if not self.corrupt:
            return None
        from .lint import Finding
        return Finding(
            "TMG404", "cost database is corrupt/truncated — falling back "
            "to static estimates (delete or regenerate it to clear this)",
            location=self.path)

    # -- recording ---------------------------------------------------------

    #: running-mean window: new observations always keep at least
    #: 1/WINDOW weight, so a changed backend/link re-converges instead
    #: of being frozen under an unbounded historical mean
    MERGE_WINDOW = 32

    @classmethod
    def _merge(cls, slot: Dict[str, Any], s_per_krow: float) -> None:
        n = min(int(slot.get("n", 0)), cls.MERGE_WINDOW - 1)
        old = float(slot.get("s_per_krow", 0.0))
        slot["s_per_krow"] = round((old * n + s_per_krow) / (n + 1), 6)
        slot["n"] = int(slot.get("n", 0)) + 1

    def record_stage(self, class_name: str, tier: str, seconds: float,
                     rows: int) -> None:
        """Fold one measured (class, tier) observation in: ``tier`` is
        ``fit`` / ``host`` / ``device``."""
        if rows <= 0 or seconds < 0:
            return
        slot = self.doc["stages"].setdefault(str(class_name), {}) \
            .setdefault(tier, {})
        self._merge(slot, seconds / (rows / 1000.0))

    def record_bandwidth(self, mbps: float,
                         probe_mbps: Optional[float] = None) -> None:
        """``mbps`` is the SUSTAINED measurement (the pipeline's
        pinned-buffer double-buffered path — what tier decisions use);
        ``probe_mbps`` the cold single-shot round trip, recorded beside
        it so a tier flip between processes is explainable."""
        self.doc["bandwidth_mbps"] = round(float(mbps), 1)
        if probe_mbps is not None:
            self.doc["probe_mbps"] = round(float(probe_mbps), 1)

    def record_chain(self, host_rows_per_s: Optional[float] = None,
                     engine_rows_per_s: Optional[float] = None) -> None:
        """Whole-chain scoring measurements (per-layer host path vs the
        compiled engine) — the strongest tier evidence there is."""
        ch = self.doc["chain"]
        if host_rows_per_s and host_rows_per_s > 0:
            ch["host_s_per_krow"] = round(1000.0 / host_rows_per_s, 6)
        if engine_rows_per_s and engine_rows_per_s > 0:
            ch["engine_s_per_krow"] = round(1000.0 / engine_rows_per_s, 6)

    # -- lookup ------------------------------------------------------------
    def stage_cost(self, class_name: str, tier: str) -> Optional[float]:
        slot = self.doc["stages"].get(class_name, {}).get(tier)
        return float(slot["s_per_krow"]) if slot else None

    def chain_cost(self, which: str) -> Optional[float]:
        v = self.doc["chain"].get(f"{which}_s_per_krow")
        return float(v) if v is not None else None

    def bandwidth_mbps(self) -> Optional[float]:
        v = self.doc.get("bandwidth_mbps")
        return float(v) if v else None


def record_fit_costs(model, db: CostDatabase) -> int:
    """Harvest a freshly trained model's per-stage fit timings (the
    telemetry/stage_metrics evidence) into the cost database; returns
    the number of observations recorded. Warm-started stages carry no
    measurement and are skipped."""
    rows = int(getattr(model, "train_rows", 0) or 0)
    if rows <= 0:
        return 0
    n = 0
    for _uid, m in sorted(model.stage_metrics.items()):
        if m.get("warmStarted") or "fitSeconds" not in m:
            continue
        execute = m.get("executeSeconds", m["fitSeconds"])
        db.record_stage(m.get("stageName", "?"), "fit", float(execute),
                        rows)
        n += 1
    return n


# ---------------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------------


@dataclass
class PlanEntry:
    """One stage's row in the execution plan."""

    uid: str
    stage: str                      # stage class/display name
    kind: str                       # vec|combine|select|scale|predict|host
    tier: str                       # host|fused
    reason: str
    est_host_s_per_krow: Optional[float] = None
    est_device_s_per_krow: Optional[float] = None
    measured_s_per_krow: Optional[float] = None
    source: str = "static"          # static|measured

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "uid": self.uid, "stage": self.stage, "kind": self.kind,
            "tier": self.tier, "reason": self.reason,
            "source": self.source}
        for k, v in (("estHostSPerKrow", self.est_host_s_per_krow),
                     ("estDeviceSPerKrow", self.est_device_s_per_krow),
                     ("measuredSPerKrow", self.measured_s_per_krow)):
            if v is not None:
                out[k] = v
        return out


class ExecutionPlan:
    """The planner's output: explainable, stable, and executable.

    ``Workflow`` fitting consults ``fitstats_tier``/``transform_tier``;
    the scoring engine consults ``engine_tier``, ``prune`` (per-vec live
    column indices) and ``cse`` (verified merge groups). ``report()`` is
    byte-stable for a given (DAG, cost db) pair — the determinism tests
    diff it directly."""

    def __init__(self, entries: List[PlanEntry],
                 prune: Optional[Dict[str, "np.ndarray"]] = None,
                 widths: Optional[Dict[str, int]] = None,
                 cse: Optional[List[Dict[str, Any]]] = None,
                 cse_suppressed: Optional[List[Dict[str, Any]]] = None,
                 engine_tier: Optional[str] = None,
                 fitstats_tier: Optional[str] = None,
                 transform_tier: Optional[str] = None,
                 aggregate_tier: Optional[str] = None,
                 link_mbps: float = 0.0, link_source: str = "prior",
                 tier_findings: Optional[List[Any]] = None,
                 db_finding: Optional[Any] = None):
        self.entries = entries
        #: {vec stage uid: sorted live column indices} — only stages
        #: with at least one dead column appear
        self.prune = prune or {}
        #: {vec stage uid: declared output width} for pruned stages
        self.widths = widths or {}
        #: verified merges: [{"kept": uid, "dropped": [uid...],
        #: "stage": class}] — bit-identical state asserted by the planner
        self.cse = cse or []
        self.cse_suppressed = cse_suppressed or []
        self.engine_tier = engine_tier
        self.fitstats_tier = fitstats_tier
        self.transform_tier = transform_tier
        #: measured columnar-vs-rowwise route for temporal aggregation
        #: (the readers consult it via temporal.set_aggregate_tier_hint)
        self.aggregate_tier = aggregate_tier
        self.link_mbps = link_mbps
        self.link_source = link_source
        self._tier_findings = tier_findings or []
        self._db_finding = db_finding

    # -- summaries ---------------------------------------------------------
    def counts(self) -> Dict[str, Any]:
        tiers: Dict[str, int] = {}
        for e in self.entries:
            tiers[e.tier] = tiers.get(e.tier, 0) + 1
        return {
            "stages": len(self.entries),
            "tiers": {k: tiers[k] for k in sorted(tiers)},
            "prunedColumns": int(sum(
                self.widths[uid] - len(idx)
                for uid, idx in self.prune.items())),
            "cseMerges": len(self.cse),
            "engineTier": self.engine_tier,
        }

    def to_json(self) -> Dict[str, Any]:
        """Stable JSON form (the ``plan`` block of metrics docs)."""
        pruned = {}
        for uid, idx in sorted(self.prune.items()):
            live = {int(i) for i in idx}
            pruned[uid] = {"width": int(self.widths[uid]),
                           "dead": [j for j in range(self.widths[uid])
                                    if j not in live]}
        return {
            "version": 1,
            "link": {"mbps": round(self.link_mbps, 1),
                     "source": self.link_source},
            "tiers": {"engine": self.engine_tier,
                      "fitstats": self.fitstats_tier,
                      "transform": self.transform_tier,
                      "aggregate": self.aggregate_tier},
            "stages": [e.to_json() for e in self.entries],
            "prunedColumns": pruned,
            "cse": self.cse,
            "cseSuppressed": self.cse_suppressed,
            "counts": self.counts(),
        }

    def report(self) -> str:
        """The human-facing plan explanation: one deterministic text
        document (tier table + prune/CSE sections) suitable for diffing
        across planner or cost-db changes."""
        from .utils.table import Table
        c = self.counts()
        head = (f"ExecutionPlan: {c['stages']} stage(s) "
                + " ".join(f"{k}={v}" for k, v in c["tiers"].items())
                + f" | engine tier: {self.engine_tier or 'gate'}"
                + f" | link {self.link_mbps:.1f} MB/s ({self.link_source})")
        rows = []
        for e in self.entries:
            rows.append([
                e.stage, e.uid, e.kind, e.tier,
                "" if e.est_host_s_per_krow is None
                else f"{e.est_host_s_per_krow:.6f}",
                "" if e.est_device_s_per_krow is None
                else f"{e.est_device_s_per_krow:.6f}",
                "" if e.measured_s_per_krow is None
                else f"{e.measured_s_per_krow:.6f}",
                e.source, e.reason])
        parts = [head, Table(
            ["stage", "uid", "kind", "tier", "est host s/krow",
             "est device s/krow", "measured s/krow", "source", "reason"],
            rows, name="Stage tiers").render()]
        if self.prune:
            lines = ["Pruned dead columns "
                     f"({c['prunedColumns']} total):"]
            for uid, idx in sorted(self.prune.items()):
                dead = self.widths[uid] - len(idx)
                lines.append(f"  {uid}: {dead} of {self.widths[uid]} "
                             "column(s) never reach a sink")
            parts.append("\n".join(lines))
        if self.cse:
            lines = [f"CSE merges ({len(self.cse)}):"]
            for m in self.cse:
                lines.append(f"  {m['stage']}: keep {m['kept']}, alias "
                             + ", ".join(m["dropped"]))
            parts.append("\n".join(lines))
        if self.cse_suppressed:
            lines = [f"CSE suppressed ({len(self.cse_suppressed)}):"]
            for m in self.cse_suppressed:
                lines.append(f"  {m['stage']}: {m['reason']}")
            parts.append("\n".join(lines))
        return "\n\n".join(parts) + "\n"

    def findings(self) -> List[Any]:
        """TMG4xx advisory findings: tier contradictions (TMG401), dead
        columns (TMG402), suppressed CSE (TMG403), corrupt db (TMG404)
        — routed through the same ``failOn``/suppress machinery as the
        pre-flight rules."""
        from .lint import Finding
        out: List[Finding] = list(self._tier_findings)
        for uid, idx in sorted(self.prune.items()):
            dead = self.widths[uid] - len(idx)
            out.append(Finding(
                "TMG402", f"{dead} of {self.widths[uid]} output "
                "column(s) are dead: dropped by a downstream selector "
                "before any sink — the planner prunes them from the "
                "device program", stage=uid))
        for m in self.cse_suppressed:
            out.append(Finding(
                "TMG403", f"{m['stage']}: structurally identical stages "
                f"({', '.join(m['uids'])}) cannot merge — {m['reason']}",
                stage=m["uids"][0]))
        if self._db_finding is not None:
            out.append(self._db_finding)
        return out


# ---------------------------------------------------------------------------
# liveness (dead-column pruning) over the fused plan
# ---------------------------------------------------------------------------

#: liveness sentinel: every column live (distinct from a missing entry,
#: which means "no fused consumer needs this output at all")
_ALL = object()


def _union(a, b):
    if a is _ALL or b is _ALL:
        return _ALL
    return a | b


def _device_liveness(plan_items, result_names: Sequence[str]
                     ) -> Tuple[Dict[str, Any], Dict[str, int]]:
    """Column liveness per fused output name, propagated sinks-backward.

    ``plan_items`` are the scoring engine's ``_FusedStage`` records in
    topological (producers-first) order. Returns ``(live, widths)``
    where ``live[name]`` is a set of live column indices or the ``_ALL``
    sentinel, and ``widths[name]`` the known column count."""
    widths: Dict[str, int] = {}
    for it in plan_items:
        if it.kind == "vec":
            widths[it.out] = it.model.vector_metadata().size
        elif it.kind == "combine":
            ins = [widths.get(nm) for nm in it.ins]
            widths[it.out] = (sum(ins)            # type: ignore[arg-type]
                              if all(w is not None for w in ins) else None)
        elif it.kind == "select":
            widths[it.out] = len(it.model.keep_indices)
        elif it.kind == "scale":
            widths[it.out] = widths.get(it.ins[0])

    live: Dict[str, Any] = {nm: _ALL for nm in result_names}
    # consumers-first: the plan list is producers-first and acyclic
    for it in reversed(plan_items):
        ol = live.get(it.out, _ALL if it.out in result_names else None)
        if ol is None:
            # no fused consumer and not a result: nothing downstream
            # needs it — contribute no liveness to the inputs
            continue
        if it.kind == "select":
            keep = list(it.model.keep_indices)
            contrib = (set(int(k) for k in keep) if ol is _ALL
                       else {int(keep[i]) for i in ol})
            live[it.ins[0]] = _union(live.get(it.ins[0], set()), contrib)
        elif it.kind == "scale":
            live[it.ins[0]] = _union(live.get(it.ins[0], set()),
                                     ol if ol is not _ALL else _ALL)
        elif it.kind == "combine":
            if any(widths.get(nm) is None for nm in it.ins):
                # an input of unknown width poisons every offset after
                # it — column math through this combine is unsound, so
                # every input stays fully live (no pruning through it)
                for nm in it.ins:
                    live[nm] = _ALL
                continue
            off = 0
            for nm in it.ins:
                w = widths[nm]
                if ol is _ALL:
                    contrib: Any = _ALL
                else:
                    contrib = {j - off for j in ol if off <= j < off + w}
                live[nm] = _union(live.get(nm, set()), contrib)
                off += w
        elif it.kind == "predict":
            for nm in it.ins:
                live[nm] = _ALL
        # vec: no fused inputs to propagate into
    return live, {k: v for k, v in widths.items() if v is not None}


# ---------------------------------------------------------------------------
# CSE over stages
# ---------------------------------------------------------------------------


def _params_signature(stage) -> Tuple:
    """Stable, uid-free signature of a stage's constructor params."""
    try:
        params = dict(stage.get_params())
    except Exception:  # lint: broad-except — unparamable stage: signature falls back to identity
        return ("<unparamable>", id(stage))
    params.pop("uid", None)
    return tuple(sorted((k, repr(v)) for k, v in params.items()))


def _uid_sensitive_keys(a_params: Dict[str, Any],
                        b_params: Dict[str, Any]) -> List[str]:
    """Keys whose values differ between two otherwise identical stages
    and look uid-like (the TMG403 evidence)."""
    from .utils import uid as uid_mod
    keys = []
    for k in sorted(set(a_params) | set(b_params)):
        if k == "uid":
            continue
        va, vb = a_params.get(k), b_params.get(k)
        if va == vb:
            continue
        for v in (va, vb):
            try:
                uid_mod.parse_uid(str(v))
                keys.append(k)
                break
            except Exception:  # lint: broad-except — non-uid param value: not uid-sensitive
                continue
    return keys


def _state_equal(a, b) -> bool:
    """Bit-identical fitted state (numpy-aware deep compare)."""
    try:
        sa, sb = a.get_model_state(), b.get_model_state()
    except Exception:  # lint: broad-except — unstateable model: never merge it
        return False

    def eq(x, y) -> bool:
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            x, y = np.asarray(x), np.asarray(y)
            return (x.shape == y.shape and x.dtype == y.dtype
                    and bool(np.array_equal(x, y)))
        if isinstance(x, dict) and isinstance(y, dict):
            return (sorted(x) == sorted(y)
                    and all(eq(x[k], y[k]) for k in x))
        if isinstance(x, (list, tuple)) and isinstance(y, (list, tuple)):
            return (len(x) == len(y)
                    and all(eq(p, q) for p, q in zip(x, y)))
        return bool(x == y)

    return eq(sa, sb)


def _cse_pass(vec_items) -> Tuple[List[Dict[str, Any]],
                                  List[Dict[str, Any]]]:
    """Group structurally identical fused vectorizers.

    Returns ``(merges, suppressed)``: merges are verified (class, input
    features, params AND fitted state all identical — aliasing is
    bit-identical by construction); suppressed records structural twins
    whose merge a uid-sensitive param or state mismatch blocks."""
    groups: Dict[Tuple, List[Any]] = {}
    for it in vec_items:
        m = it.model
        key = (type(m).__name__,
               tuple(f.name for f in m.input_features),
               _params_signature(m))
        groups.setdefault(key, []).append(it)

    merges: List[Dict[str, Any]] = []
    suppressed: List[Dict[str, Any]] = []
    for key, items in sorted(groups.items(),
                             key=lambda kv: kv[1][0].model.uid):
        if len(items) < 2:
            continue
        kept = items[0]
        ok, bad = [kept], []
        for it in items[1:]:
            (ok if _state_equal(kept.model, it.model) else bad).append(it)
        if len(ok) > 1:
            merges.append({"stage": key[0], "kept": kept.model.uid,
                           "dropped": [it.model.uid for it in ok[1:]]})
        if bad:
            suppressed.append({
                "stage": key[0],
                "uids": [kept.model.uid] + [it.model.uid for it in bad],
                "reason": "fitted state differs despite identical "
                          "params/inputs (uid-seeded or data-order-"
                          "sensitive fit)"})

    # near-misses: same class+inputs, params differing only in uid-like
    # values — the classic generated-pipeline pattern TMG403 names
    by_shape: Dict[Tuple, List[Any]] = {}
    for it in vec_items:
        m = it.model
        by_shape.setdefault(
            (type(m).__name__, tuple(f.name for f in m.input_features)),
            []).append(it)
    for (cls, _ins), items in sorted(by_shape.items()):
        if len(items) < 2:
            continue
        # one representative per distinct signature: the comparison
        # must cross the signature boundary, or a uid-sensitive twin
        # hiding behind two identical-param stages is never seen
        by_sig: Dict[Tuple, Any] = {}
        for it in items:
            by_sig.setdefault(_params_signature(it.model), it)
        if len(by_sig) < 2:
            continue            # identical params: handled above
        reps = list(by_sig.values())
        a, b = reps[0].model, reps[1].model
        try:
            a_params, b_params = a.get_params(), b.get_params()
        except Exception:  # lint: broad-except — unparamable near-miss: skip it, don't kill the plan
            continue
        keys = _uid_sensitive_keys(a_params, b_params)
        if keys:
            suppressed.append({
                "stage": cls, "uids": sorted(m.model.uid for m in items),
                "reason": f"params {keys} carry uid-like values — make "
                "them uid-independent to unlock the merge"})
    return merges, suppressed


# ---------------------------------------------------------------------------
# tier assignment
# ---------------------------------------------------------------------------


def _resolve_link(db: Optional[CostDatabase]) -> Tuple[float, str]:
    """The link bandwidth the plan reasons with. NEVER probes a device
    (planning is static): a db measurement wins, else the old global
    gate value serves as the documented cold-start prior."""
    from .workflow import FUSE_MIN_BANDWIDTH_MBPS
    if db is not None:
        mbps = db.bandwidth_mbps()
        if mbps:
            return mbps, "measured"
    return FUSE_MIN_BANDWIDTH_MBPS, "prior"


def _stage_bytes_per_row(model, kind: str, store, widths: Dict[str, int]
                         ) -> float:
    """Abstract per-row byte volume of a stage — the static cost model's
    input. Vectorizers: canonicalized prepared blocks measured on the
    synthetic store; structural kinds: f32 width."""
    if kind == "vec":
        from .ops.vectorizer_base import canonicalize_prepared
        n = store.n_rows
        try:
            prep = canonicalize_prepared(model.host_prepare(store))
        except Exception:  # lint: broad-except — unpreparable stage: width-based fallback estimate
            return 4.0 * model.vector_metadata().size
        total = 0.0
        for v in prep.values():
            a = np.asarray(v)
            if a.ndim and a.shape[0] == n:
                total += a.nbytes / n
        return total + 4.0 * model.vector_metadata().size
    w = widths.get(getattr(model, "output_name", ""), 0) or 0
    return 4.0 * float(w)


def _entry_for(model, kind: Optional[str], fused: bool, store,
               widths: Dict[str, int], db: Optional[CostDatabase],
               link_mbps: float):
    """One stage's PlanEntry + its (host, device) cost pair."""
    from .lint import Finding, _stage_label
    name = type(model).__name__
    label = _stage_label(model)
    if kind is None:
        measured = db.stage_cost(name, "host") if db else None
        return PlanEntry(
            uid=model.uid, stage=name, kind="host", tier="host",
            reason="no device form (host-only stage)",
            measured_s_per_krow=measured,
            source="measured" if measured is not None else "static",
        ), None, None
    bpr = _stage_bytes_per_row(model, kind, store, widths)
    est_host = round(1000.0 * bpr / (STATIC_HOST_GBPS * 1e9), 6)
    est_dev = round(1000.0 * bpr * (1.0 / (link_mbps * 1e6)
                                    + 1.0 / (STATIC_DEVICE_GBPS * 1e9)), 6)
    # per-class host/device transform costs are the db's injectable
    # interface (bench/operator-fed): a fused program's per-stage
    # device time is not separable from outside, so nothing records
    # them automatically — absent entries fall back to the estimates
    m_host = db.stage_cost(name, "host") if db else None
    m_dev = db.stage_cost(name, "device") if db else None
    if not fused:
        return PlanEntry(
            uid=model.uid, stage=name, kind=kind, tier="host",
            reason="demoted: a host-only stage consumes its output",
            est_host_s_per_krow=est_host, est_device_s_per_krow=est_dev,
            measured_s_per_krow=m_host,
            source="measured" if m_host is not None else "static",
        ), None, None
    measured = m_dev if m_dev is not None else None
    src = "measured" if (m_host is not None and m_dev is not None) \
        else "static"
    finding = None
    if m_host is not None and m_dev is not None and m_dev > m_host:
        finding = Finding(
            "TMG401", f"{label} measured slower on device "
            f"({m_dev:.6f} s/krow) than host ({m_host:.6f} s/krow) but "
            "is pinned to the fused device program by its consumers — "
            "consider demoting the chain", stage=model.uid)
    return PlanEntry(
        uid=model.uid, stage=name, kind=kind, tier="fused",
        reason=("measured costs favor the fused device program"
                if src == "measured" and (m_dev or 0) <= (m_host or 0)
                else "consumer-closed device-capable chain"),
        est_host_s_per_krow=est_host, est_device_s_per_krow=est_dev,
        measured_s_per_krow=measured, source=src,
    ), (m_host if m_host is not None else est_host,
        m_dev if m_dev is not None else est_dev), finding


def _engine_tier(host_total: float, dev_total: float,
                 db: Optional[CostDatabase], link_mbps: float,
                 link_source: str) -> Tuple[Optional[str], str]:
    """Whole-chain tier: measured chain costs rule when present, else
    the per-stage totals; with nothing but priors the decision degrades
    to the classic bandwidth gate (the prior's whole remaining job)."""
    if db is not None:
        ch_h, ch_e = db.chain_cost("host"), db.chain_cost("engine")
        if ch_h is not None and ch_e is not None:
            return (("device" if ch_e <= ch_h else "host"),
                    "measured whole-chain scoring costs")
    if link_source == "measured":
        return (("device" if dev_total <= host_total else "host"),
                "static per-stage estimates over the measured link")
    # pure priors: keep the legacy gate semantics (the prior IS the
    # gate) — None leaves the engine's own bandwidth gate in charge
    return None, "cold-start prior (bandwidth gate rules)"


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def plan_model(model, cost_db: Optional[CostDatabase] = None,
               n_rows: int = 8) -> ExecutionPlan:
    """Build the execution plan for a fitted :class:`WorkflowModel`.

    Purely static: host stages and ``host_prepare`` run on lint.py's
    tiny synthetic typed store (defaults only, no dataset read), device
    computes are never dispatched, and the link bandwidth comes from the
    cost database or the cold-start prior — never a live probe."""
    from . import telemetry
    from .lint import _synthetic_store
    from .scoring import build_fused_plan

    plan_items, host_layers = build_fused_plan(model._resolved_dag())
    result_names = [f.name for f in model.result_features]

    # the synthetic store, advanced through the host stages so
    # host_prepare sees realistic (typed, empty-default) inputs
    store = _synthetic_store(model.result_features, n_rows)
    for layer in host_layers:
        for m in layer:
            try:
                store = m.transform(store)
            except Exception:  # lint: broad-except — a host stage without a static form only degrades its own byte estimate
                logger.debug("planner: host stage %s has no static form",
                             m.uid)

    live, widths_by_name = _device_liveness(plan_items, result_names)
    prune: Dict[str, np.ndarray] = {}
    prune_widths: Dict[str, int] = {}
    for it in plan_items:
        if it.kind != "vec":
            continue
        lv = live.get(it.out)
        w = widths_by_name.get(it.out)
        if lv is _ALL or lv is None or w is None:
            continue
        if len(lv) < w:
            prune[it.model.uid] = np.asarray(sorted(int(j) for j in lv),
                                             dtype=np.int64)
            prune_widths[it.model.uid] = int(w)

    vec_items = [it for it in plan_items if it.kind == "vec"]
    merges, suppressed = _cse_pass(vec_items)

    link_mbps, link_source = _resolve_link(cost_db)
    fused_uids = {it.model.uid for it in plan_items}
    entries: List[PlanEntry] = []
    tier_findings: List[Any] = []
    host_total = dev_total = 0.0
    from .scoring import _classify
    for layer in model._resolved_dag():
        for m in layer:
            kind = _classify(m)
            entry, costs, finding = _entry_for(
                m, kind, m.uid in fused_uids, store, widths_by_name,
                cost_db, link_mbps)
            entries.append(entry)
            if costs is not None:
                host_total += costs[0]
                dev_total += costs[1]
            if finding is not None:
                tier_findings.append(finding)
    engine_tier, tier_reason = _engine_tier(
        host_total, dev_total, cost_db, link_mbps, link_source)

    plan = ExecutionPlan(
        entries, prune=prune, widths=prune_widths, cse=merges,
        cse_suppressed=suppressed, engine_tier=engine_tier,
        fitstats_tier=_phase_tier(cost_db, "fitstats"),
        transform_tier=_phase_tier(cost_db, "transform"),
        aggregate_tier=aggregate_route_tier(cost_db),
        link_mbps=link_mbps, link_source=link_source,
        tier_findings=tier_findings,
        db_finding=cost_db.finding() if cost_db is not None else None)
    logger.info("planner: %d stage(s), engine tier %s (%s), %d pruned "
                "column(s), %d CSE merge(s)", len(entries),
                engine_tier or "gate", tier_reason,
                plan.counts()["prunedColumns"], len(merges))
    _record_tallies(plan)
    telemetry.emit("plan", stages=len(entries),
                   engine_tier=engine_tier,
                   pruned_columns=plan.counts()["prunedColumns"],
                   cse_merges=len(merges))
    return plan


def plan_workflow(workflow, cost_db: Optional[CostDatabase] = None
                  ) -> ExecutionPlan:
    """Plan an untrained :class:`Workflow` (graph-only: fitted state —
    sanity keep-indices, model weights — does not exist yet, so dead-
    column pruning and verified CSE wait for the model plan; tier
    estimates and the fit-phase tiers are available now and
    ``Workflow.train`` follows them)."""
    from . import telemetry
    from .graph import compute_dag
    link_mbps, link_source = _resolve_link(cost_db)
    entries: List[PlanEntry] = []
    for layer in compute_dag(workflow.result_features):
        for st in layer:
            # fit costs are recorded under stage_name() (class + op,
            # the stage_metrics key) — look them up the same way
            name = st.stage_name()
            measured = (cost_db.stage_cost(name, "fit")
                        if cost_db is not None else None)
            entries.append(PlanEntry(
                uid=st.uid, stage=name, kind="estimator"
                if hasattr(st, "fit_columns") else "host", tier="host",
                reason="fit-path stage (tier decided per phase)",
                measured_s_per_krow=measured,
                source="measured" if measured is not None else "static"))
    plan = ExecutionPlan(
        entries, engine_tier=None,
        fitstats_tier=_phase_tier(cost_db, "fitstats"),
        transform_tier=_phase_tier(cost_db, "transform"),
        aggregate_tier=aggregate_route_tier(cost_db),
        link_mbps=link_mbps, link_source=link_source,
        db_finding=cost_db.finding() if cost_db is not None else None)
    _record_tallies(plan)
    telemetry.emit("plan", stages=len(entries), engine_tier=None,
                   pruned_columns=0, cse_merges=0)
    return plan


def _phase_tier(db: Optional[CostDatabase],
                phase: str) -> Optional[str]:
    """Measured tier for a whole fit phase (``fitstats`` stats pass /
    ``transform`` layer fusion): both tiers must have been measured to
    override the gate; otherwise None keeps the legacy gate in charge
    (and the bit-exact host tier stays the default on slow links)."""
    if db is None:
        return None
    h = db.stage_cost(f"phase:{phase}", "host")
    d = db.stage_cost(f"phase:{phase}", "device")
    if h is None or d is None:
        return None
    return "device" if d <= h else "host"


def aggregate_route_tier(db: Optional[CostDatabase]) -> Optional[str]:
    """Measured columnar-vs-rowwise tier for the temporal aggregation
    route (ROADMAP item 4 leftover): the readers report
    ``phase:temporal.route_aggregate`` observations with tiers
    ``columnar`` / ``rowwise`` (``temporal.route_aggregate`` /
    ``tally_rowwise`` → :func:`observe_phase` → the drained cost db).
    Both tiers must have been measured to emit a hint — the runner
    installs it via ``temporal.set_aggregate_tier_hint`` so the
    ``"auto"`` route defers to evidence; None keeps the structural
    auto-route (columnar when the source is columnar) in charge."""
    if db is None:
        return None
    c = db.stage_cost("phase:temporal.route_aggregate", "columnar")
    r = db.stage_cost("phase:temporal.route_aggregate", "rowwise")
    if c is None or r is None:
        return None
    return "columnar" if c <= r else "rowwise"


def ingest_route_tier(db: Optional[CostDatabase]) -> Optional[str]:
    """Measured stream-vs-materialize tier for the workflow's raw-store
    ingest (the out-of-core seam): ``Workflow.train`` reports
    ``phase:workflow.ingest`` observations with tiers ``stream`` /
    ``materialize`` whenever a directory reader feeds a train at
    contested row counts. Both tiers must have been measured to emit a
    hint — the runner installs it via ``workflow.set_stream_fit`` so
    the ``streamFit=null`` auto mode defers to evidence; None keeps the
    structural auto-engage (stream when the source is a directory
    reader) in charge."""
    if db is None:
        return None
    s = db.stage_cost("phase:workflow.ingest", "stream")
    m = db.stage_cost("phase:workflow.ingest", "materialize")
    if s is None or m is None:
        return None
    return "stream" if s <= m else "materialize"


def _record_tallies(plan: ExecutionPlan) -> None:
    c = plan.counts()
    _tally("plans_built")
    _tally("cse_merges", c["cseMerges"])
    _tally("pruned_columns", c["prunedColumns"])
    _tally("stages_fused", c["tiers"].get("fused", 0))
    _tally("stages_host", c["tiers"].get("host", 0))
