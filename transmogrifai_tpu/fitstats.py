"""Fused fit-statistics engine — the ``SequenceAggregators`` analog.

The reference computes every estimator's sufficient statistics for a
stage layer in ONE Spark pass over the data
(``utils/.../spark/SequenceAggregators.scala:41``: a single
``Dataset.select(aggregates...)`` job feeds all vectorizers' fill
values, modes and top-K counts). Our reproduction used to loop
``for stage in layer: stage.fit(train)`` — every estimator re-scanning
the full train store on host numpy.

This module restores the one-pass-per-layer discipline for the TPU
runtime:

* Estimators declare what they need through a small **StatRequest
  protocol** (``Estimator.stat_requests(store)`` — count / masked mean /
  variance / std / min / max / quantile sketch / mode / top-K category
  counts / histogram / the sanity checker's per-column label co-moments).
* ``Workflow._fit_layer`` collects every request in the layer,
  deduplicates them (two stages needing the mean of the same column
  share one reduction), and runs them as **one pass** over the train
  store (``LayerStatsPlan.run``).
* Each opted-in stage then fits from the finalized stats
  (``Estimator.fit(store, stats=...)`` → ``fit_columns_from_stats``) —
  a cheap host-side finalize, no data scan.

Execution has the same two-tier structure as the transform-side layer
fusion (``workflow.apply_layer_vectorized``):

* **Host execution** (default below the fusion gate) computes each
  requested stat with *exactly the numpy expressions the sequential
  ``fit_columns`` implementations use* on the identical compressed
  arrays — fused and per-stage fits are **bit-identical** on this path.
* **Device execution** (rows ≥ ``workflow.FUSE_MIN_ROWS`` and measured
  link bandwidth ≥ ``workflow.FUSE_MIN_BANDWIDTH_MBPS``) streams the
  scalar-moment columns through the device in fixed-shape chunks — one
  jitted fold program per (chunk, width, dtype) shape (bounded cache, a
  compile-count guard test mirrors the scoring engine's budget test),
  uploads via the content-keyed ``device_put_f32`` cache, and combines
  per-chunk partials on host in f64 (Chan's parallel-variance merge —
  the same count/mean/M2 merge Spark's aggregators run per partition).
  With >1 device the chunk rows shard over the ``data`` axis of a
  ``parallel/mesh.py`` mesh and XLA inserts the psum. Counts, minima
  and maxima are exact on both tiers; f-moment low bits can differ from
  numpy's pairwise summation, which is why the bit-exactness guarantee
  is stated for the host tier (the one the gate picks on slow links —
  and the one CI exercises for the parity suite).

String statistics (top-K counts, modes) and exact order statistics
(quantiles) are host work by design — strings never reach the device
(the one-hot vectorizer discipline) and ``np.quantile`` is the
sequential path's exact sketch. They still ride the same single pass:
each column is materialized once, whatever mix of stages needs it.

Pass-count math: a layer with k opted-in estimators used to cost k full
scans of the train store; fused it costs exactly one (asserted via the
``fitstats.bytes_scanned`` counter in tests/test_fitstats.py). The
module keeps an always-on tally (``fitstats_stats()``) that bench.py
stamps on every emitted doc, and mirrors it into telemetry counters
(``fitstats.bytes_scanned``, ``fitstats.passes_saved``, ...) when
telemetry is enabled.
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "StatRequest", "StatResults", "LayerStatsPlan", "SufficientStats",
    "StreamingMomentFold",
    "FITSTATS_ENABLED", "FITSTATS_MIN_STAGES", "FITSTATS_CHUNK_ROWS",
    "fitstats_stats", "reset_fitstats_stats", "program_cache_stats",
    "collect_column_state", "sufficient_stats_to_json",
    "sufficient_stats_from_json", "load_sufficient_stats",
]

#: master switch (``TMOG_FITSTATS=0`` disables; tests/bench toggle the
#: module attribute directly)
FITSTATS_ENABLED = os.environ.get("TMOG_FITSTATS", "1") != "0"

#: fuse a layer only when at least this many of its estimators opt in —
#: below it there is no pass to save
FITSTATS_MIN_STAGES = 2

#: row chunk of the device fold (bounds device memory for stores larger
#: than HBM; the last chunk zero-mask-pads to the full chunk shape so a
#: layer compiles ONE program regardless of row count)
FITSTATS_CHUNK_ROWS = 262_144

#: stat kinds computed together from one per-column moment bundle —
#: the device-foldable family
_MOMENT_KINDS = frozenset(
    {"count", "mean", "variance", "std", "min", "max"})

# ---------------------------------------------------------------------------
# always-on tallies (bench stamps these on every doc, telemetry mirrors)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"layers_fused": 0, "passes_saved": 0, "bytes_scanned": 0,
          "host_passes": 0, "device_passes": 0, "programs_compiled": 0,
          "warm_state_merges": 0, "stream_chunks": 0}


def fitstats_stats() -> Dict[str, int]:
    """Snapshot of the engine's process-wide tallies (always on, cheap —
    the ``scoring.engine_cache_stats`` discipline). Includes the
    process-wide ``mesh_constructions`` count: the steady state is ONE
    mesh per process, so a regression back to a throwaway
    mesh-per-pass shows up as a count tracking the pass count in every
    bench doc."""
    from .parallel import mesh as _mesh
    with _TALLY_LOCK:
        out = dict(_TALLY)
    out["mesh_constructions"] = _mesh.mesh_constructions()
    return out


def reset_fitstats_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


# ---------------------------------------------------------------------------
# requests and results
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StatRequest:
    """One declared sufficient statistic over a named column.

    ``kind``: ``count | mean | variance | std | min | max | quantile |
    mode | value_counts | set_value_counts | histogram | sanity``.
    ``label`` names the label column for label-aware kinds (``sanity``);
    ``params`` carries kind-specific knobs (ddof, bucket count, edges,
    the sanity config) and is part of the dedup key.
    """

    kind: str
    column: str
    label: Optional[str] = None
    params: Tuple = ()

    def key(self) -> Tuple:
        return (self.kind, self.column, self.label, self.params)


class StatResults:
    """Finalized stats keyed by request — what stages consume in
    ``fit_columns_from_stats``. Missing lookups raise with the full key
    so a stage/engine mismatch fails loudly, never silently."""

    def __init__(self, values: Dict[Tuple, Any]):
        self._values = values

    def value(self, kind: str, column: str, label: Optional[str] = None,
              params: Tuple = ()) -> Any:
        key = (kind, column, label, tuple(params))
        if key not in self._values:
            raise KeyError(
                f"stat {key} was not computed by the layer plan — the "
                "stage's stat_requests and fit_columns_from_stats disagree")
        return self._values[key]

    def for_request(self, req: StatRequest) -> Any:
        return self.value(req.kind, req.column, req.label, req.params)

    def __contains__(self, key: Tuple) -> bool:
        return tuple(key) in self._values

    def __len__(self) -> int:
        return len(self._values)


# ---------------------------------------------------------------------------
# sufficient statistics — the continual-learning merge seam
# ---------------------------------------------------------------------------


@dataclass
class SufficientStats:
    """One column's moment-family sufficient statistics as a MONOID:
    (count, mean, centered M2, min, max). ``merge`` is Chan's parallel
    combination — the exact merge the device tier's ``_chan_combine``
    runs across chunks, lifted to a persistable per-column record — so a
    refit over [old train window + fresh slice] is one merge plus one
    pass over the fresh slice, never a rescan of the old window
    (continual.py, docs/lifecycle.md "Continuous training")."""

    count: float = 0.0
    mean: float = 0.0
    m2: float = 0.0
    min: float = float("inf")
    max: float = float("-inf")

    def merge(self, other: "SufficientStats") -> "SufficientStats":
        tot = self.count + other.count
        if tot <= 0:
            return SufficientStats()
        delta = other.mean - self.mean
        mean = (self.count * self.mean + other.count * other.mean) / tot
        m2 = self.m2 + other.m2 + delta * delta \
            * self.count * other.count / tot
        return SufficientStats(tot, mean, m2, min(self.min, other.min),
                               max(self.max, other.max))

    def finalize(self, kind: str, params: Tuple = ()) -> Any:
        """The finalized stat value a :class:`StatRequest` of ``kind``
        asks for — the same expressions the device tier finalizes its
        Chan-merged partials with."""
        c = int(self.count)
        if kind == "count":
            return c
        if c == 0:
            return None
        if kind == "mean":
            return float(self.mean)
        if kind == "variance":
            return float(self.m2 / c)
        if kind == "std":
            ddof = params[0] if params else 0
            return (float(np.sqrt(self.m2 / (c - ddof)))
                    if c > ddof else None)
        if kind == "min":
            return float(self.min)
        if kind == "max":
            return float(self.max)
        raise ValueError(f"unknown moment kind {kind!r}")

    def to_json(self) -> Dict[str, float]:
        return {"count": self.count, "mean": self.mean, "m2": self.m2,
                "min": self.min, "max": self.max}

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "SufficientStats":
        return SufficientStats(float(d["count"]), float(d["mean"]),
                               float(d["m2"]), float(d["min"]),
                               float(d["max"]))


def collect_column_state(col) -> SufficientStats:
    """One column's :class:`SufficientStats` from its masked values —
    the host-tier state collector (the device tier reads its state
    straight out of the Chan-merged fold partials)."""
    vals = col.values[col.mask].astype(np.float64)
    n = int(vals.size)
    if n == 0:
        return SufficientStats()
    return SufficientStats(float(n), float(vals.mean()),
                           float(vals.var() * n), float(vals.min()),
                           float(vals.max()))


def sufficient_stats_to_json(states: Mapping[str, SufficientStats]
                             ) -> Dict[str, Dict[str, float]]:
    return {k: s.to_json() for k, s in states.items()}


def sufficient_stats_from_json(doc: Mapping[str, Any]
                               ) -> Dict[str, SufficientStats]:
    return {str(k): SufficientStats.from_json(v)
            for k, v in doc.items()}


def load_sufficient_stats(model_dir: str
                          ) -> Optional[Dict[str, SufficientStats]]:
    """The train-time sufficient statistics persisted with a saved
    model (``model.json``'s ``fitSufficientStats`` block), parsed back
    into mergeable :class:`SufficientStats`. Returns None — the
    full-refit degradation signal — when the model predates the
    persistence, carries no fused-fit stats, or the block is corrupt;
    the caller (``continual.load_warm_stats``) owns the TMG604
    advisory."""
    import json as _json

    from .model_io import MODEL_JSON
    try:
        with open(os.path.join(model_dir, MODEL_JSON)) as fh:
            doc = _json.load(fh)
        raw = doc.get("fitSufficientStats")
        if not raw:
            return None
        return sufficient_stats_from_json(raw)
    except (OSError, ValueError, KeyError, TypeError):
        logger.exception("sufficient stats at %s are unreadable",
                         model_dir)
        return None


# ---------------------------------------------------------------------------
# host execution — the bit-exact twin of the sequential fit_columns code
# ---------------------------------------------------------------------------


def _host_moment_bundle(col, kinds: Dict[str, List[Tuple]],
                        state_out: Optional[Dict[str, Any]] = None,
                        name: Optional[str] = None) -> Dict[Tuple, Any]:
    """All moment-family stats of one column, computed with the exact
    expressions the sequential fits use: one compressed
    ``values[mask].astype(f64)`` materialization, then numpy's own
    ``mean/std/var/min/max`` on it. When ``state_out`` is given, the
    column's :class:`SufficientStats` are derived from the SAME
    materialized array — state collection never costs a second scan
    (and never perturbs the bit-exact request values)."""
    vals = col.values[col.mask].astype(np.float64)
    count = int(vals.size)
    if state_out is not None:
        state_out[name] = (SufficientStats() if count == 0 else
                           SufficientStats(float(count),
                                           float(vals.mean()),
                                           float(vals.var() * count),
                                           float(vals.min()),
                                           float(vals.max())))
    out: Dict[Tuple, Any] = {}
    for kind, params_list in kinds.items():
        for params in params_list:
            if kind == "count":
                v: Any = count
            elif count == 0:
                v = None
            elif kind == "mean":
                v = float(vals.mean())
            elif kind == "variance":
                v = float(vals.var())
            elif kind == "std":
                ddof = params[0] if params else 0
                v = (float(vals.std(ddof=ddof))
                     if count > ddof else None)
            elif kind == "min":
                v = float(vals.min())
            elif kind == "max":
                v = float(vals.max())
            else:  # pragma: no cover - guarded by _MOMENT_KINDS
                raise ValueError(f"unknown moment kind {kind!r}")
            out[(kind, params)] = v
    return out


def _exec_quantile(store, req: StatRequest):
    """Quantile sketch: the sequential NumericBucketizer's exact
    ``np.quantile`` over masked f64 values (None when the column is
    empty — the caller applies its own default splits)."""
    col = store[req.column]
    present = col.values[col.mask].astype(np.float64)
    if present.size == 0:
        return None
    num_buckets = int(req.params[0])
    return np.quantile(present, np.linspace(0, 1, num_buckets + 1))


def _exec_mode(store, req: StatRequest):
    """Most frequent value, ties → smallest
    (SequenceAggregators.ModeSeqNullInt semantics; unique is sorted)."""
    col = store[req.column]
    if not col.mask.any():
        return None
    vals, counts = np.unique(col.values[col.mask], return_counts=True)
    return float(vals[np.argmax(counts)])


def _exec_value_counts(store, req: StatRequest):
    from .ops._hostvec import value_counts
    return value_counts(store[req.column].values)


def _exec_set_value_counts(store, req: StatRequest):
    from .ops._hostvec import flatten_ragged, value_counts
    flat, _rows, _lengths = flatten_ragged(store[req.column].values)
    return value_counts(flat)


def _exec_histogram(store, req: StatRequest):
    col = store[req.column]
    vals = col.values[col.mask].astype(np.float64)
    edges = np.asarray(req.params, dtype=np.float64)
    hist, _ = np.histogram(vals, bins=edges)
    return hist


def _exec_sanity(store, req: StatRequest):
    """The sanity checker's moments + contingency sweep — delegated to
    the SAME compute function its sequential ``fit_columns`` calls, so
    the two paths are one code path (bit-identical by construction;
    the device-vs-host gram gate lives inside it)."""
    from .ops.sanity_checker import compute_sanity_stats
    cfg = dict(req.params)
    return compute_sanity_stats(store, req.label, req.column, **cfg)


_HOST_EXEC = {
    "quantile": _exec_quantile,
    "mode": _exec_mode,
    "value_counts": _exec_value_counts,
    "set_value_counts": _exec_set_value_counts,
    "histogram": _exec_histogram,
    "sanity": _exec_sanity,
}


# ---------------------------------------------------------------------------
# device execution — chunked fold program + Chan combine
# ---------------------------------------------------------------------------

#: jitted per-chunk moment programs keyed by (chunk, k, dtype, sharded);
#: bounded like workflow._LAYER_JIT_CACHE
_PROGRAM_CACHE: Dict[Tuple, Any] = {}
_PROGRAM_CACHE_CAP = 32

#: pinned staging pool for the fold's pad-to-chunk buffers — repeat
#: passes (bench warm reps, CV re-fits) recycle instead of re-zeroing
#: fresh pages per pass (pipeline.py; reuse counts ride in
#: ``pipeline.pipeline_stats()``)
_STAGE_POOL = None


def _stage_pool():
    global _STAGE_POOL
    if _STAGE_POOL is None:
        from .pipeline import BufferPool
        _STAGE_POOL = BufferPool(max_per_key=4)
    return _STAGE_POOL


def program_cache_stats() -> Dict[str, int]:
    return {"size": len(_PROGRAM_CACHE),
            "compiles": fitstats_stats()["programs_compiled"]}


def _pow2_ceil(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _chunk_rows(n: int) -> int:
    """Fixed-shape chunk for the fold: power-of-two with a floor (tiny
    stores pad up rather than compiling a program per row count — the
    scoring engine's bucket-ladder discipline) and the module cap."""
    return min(FITSTATS_CHUNK_ROWS, max(_pow2_ceil(n), 1024))


def _moment_program(chunk: int, k: int, dtype: str):
    """ONE jitted fold step per (chunk, width, dtype) shape: per-column
    count, sum, chunk-local mean and centered M2, min, max. Masked and
    padded rows are inert (value 0, mask False)."""
    key = (chunk, k, dtype)
    prog = _PROGRAM_CACHE.pop(key, None)
    if prog is None:
        import jax
        import jax.numpy as jnp

        def step(v, b):
            bf = b.astype(v.dtype)
            cnt = bf.sum(axis=0)
            s = (v * bf).sum(axis=0)
            mean_c = s / jnp.maximum(cnt, 1.0)
            d = (v - mean_c[None, :]) * bf
            m2 = (d * d).sum(axis=0)
            mn = jnp.where(b, v, jnp.inf).min(axis=0)
            mx = jnp.where(b, v, -jnp.inf).max(axis=0)
            return cnt, s, mean_c, m2, mn, mx

        prog = jax.jit(step)
        _tally("programs_compiled")
    _PROGRAM_CACHE[key] = prog          # LRU re-insert on use
    while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_CAP:
        _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
    return prog


def _chan_combine(parts: List[Tuple]) -> Tuple[np.ndarray, ...]:
    """Merge per-chunk (count, sum, mean, M2, min, max) partials in f64
    — Chan's parallel variance combination (exact for counts/min/max;
    the same merge Spark runs across partitions)."""
    cnt, _s, mean, m2, mn, mx = [np.asarray(a, np.float64)
                                 for a in parts[0]]
    for p in parts[1:]:
        c2, _s2, me2, m22, mn2, mx2 = [np.asarray(a, np.float64)
                                       for a in p]
        tot = cnt + c2
        safe = np.maximum(tot, 1.0)
        delta = me2 - mean
        mean = np.where(tot > 0, (cnt * mean + c2 * me2) / safe, 0.0)
        m2 = m2 + m22 + delta * delta * cnt * c2 / safe
        cnt = tot
        mn = np.minimum(mn, mn2)
        mx = np.maximum(mx, mx2)
    return cnt, mean, m2, mn, mx


_MESH_OFF = os.environ.get("TMOG_FITSTATS_MESH", "1") == "0"


def _device_moment_bundles(store, col_kinds: Dict[str, Dict[str, List[Tuple]]],
                           mesh=None,
                           states_out: Optional[Dict[str, SufficientStats]]
                           = None) -> Dict[str, Dict[Tuple, Any]]:
    """Device tier: stack the requested scalar columns into [n, k],
    stream fixed-shape row chunks through ONE jitted fold program, and
    combine the per-chunk partials on host in f64.

    Uploads go through the content-keyed ``device_put_f32`` cache; with
    more than one device the chunk's rows shard over the ``data`` axis
    of the caller's mesh — falling back to the cached process-default
    mesh, never a private throwaway one (``mesh_constructions`` in
    ``fitstats_stats()`` keeps that honest) — and GSPMD inserts the
    psum for the column reductions."""
    import time

    import jax

    from . import telemetry

    names = sorted(col_kinds)
    n, k = store.n_rows, len(names)
    f64 = jax.config.jax_enable_x64
    dtype = np.float64 if f64 else np.float32
    V = np.empty((n, k), dtype)
    B = np.empty((n, k), bool)
    for j, nm in enumerate(names):
        col = store[nm]
        B[:, j] = col.mask
        # zero-fill masked slots so padded/masked rows are inert in the
        # fold (the pad_rows zero-weight discipline)
        V[:, j] = np.where(col.mask, col.values.astype(np.float64), 0.0)

    chunk = _chunk_rows(n)
    one_chunk = n <= chunk
    sharding = None
    # mesh=False forces the unsharded path; None falls back to the cached
    # process-default mesh (degenerate 1×1 resolves to no sharding)
    if not _MESH_OFF and mesh is not False:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .parallel.mesh import mesh_if_multi, process_default_mesh
        mesh = mesh_if_multi(mesh if mesh is not None
                             else process_default_mesh())
        if mesh is not None and chunk % mesh.shape["data"] == 0:
            sharding = NamedSharding(mesh, P("data", None))

    prog_was_cached = (chunk, k, str(dtype)) in _PROGRAM_CACHE
    prog = _moment_program(chunk, k, str(dtype))
    pool = _stage_pool()
    compile_clock0 = telemetry.compile_clock_s()

    def _place(off: int):
        """Pad (through the pinned staging pool) and issue one chunk's
        uploads; device_put is asynchronous, so the transfer drains
        behind whatever the caller computes next."""
        v = V[off:off + chunk]
        b = B[off:off + chunk]
        taken: List[np.ndarray] = []
        if v.shape[0] < chunk:
            m = v.shape[0]
            if one_chunk:
                # the content-keyed upload cache below may retain a
                # zero-copy ALIAS of its source array (CPU device_put):
                # pad into fresh arrays here — a recycled pool buffer
                # would be overwritten by a later fit and corrupt the
                # cached upload under its old key
                vp = np.zeros((chunk, k), dtype)
                bp = np.zeros((chunk, k), bool)
            else:
                vp = pool.take((chunk, k), dtype)
                bp = pool.take((chunk, k), bool)
                taken += [vp, bp]
            vp[:m] = v
            vp[m:] = 0
            bp[:m] = b
            bp[m:] = False
            v, b = vp, bp
        if sharding is not None:
            vd = jax.device_put(v, sharding)
            bd = jax.device_put(b, sharding)
        elif one_chunk:
            # single-chunk pass: content-keyed upload cache — repeat
            # fits of the same store (bench warm reps, CV re-fits)
            # skip the transfer entirely
            from .models.base import device_put_f32
            vd = device_put_f32(v)
            bd = device_put_f32(b)
        else:
            # multi-chunk stream: contents never repeat within the
            # pass, so the content hash would be pure overhead and the
            # insertions would flush genuinely reusable cache entries
            vd = jax.device_put(v)
            bd = jax.device_put(b)
        return vd, bd, taken

    # double-buffered fold (pipeline.py discipline): chunk i+1's upload
    # is issued BEFORE chunk i's result is pulled, so the host→device
    # transfer overlaps the device fold — the one-pass scan's ingest no
    # longer serializes upload → compute → upload. Staging buffers
    # recycle only after their chunk's pull (transfers complete by
    # then). TMOG_PIPELINE=0 serializes the fold (one chunk fully
    # pulled before the next uploads — the pre-pipeline behavior).
    from .pipeline import PIPELINE_ENABLED as _pipe_on
    parts = []
    pending = None

    def _pull(placed):
        # dispatch + pull, THEN recycle: the staging buffers' transfer
        # is complete once device_get returns
        vd, bd, taken = placed
        parts.append(jax.device_get(prog(vd, bd)))
        for buf in taken:
            pool.give(buf)

    t_fold0 = time.perf_counter()
    for off in range(0, max(n, 1), chunk):
        placed = _place(off)
        if not _pipe_on:
            _pull(placed)
            continue
        if pending is not None:
            _pull(pending)
        pending = placed
    if pending is not None:
        _pull(pending)
    # executed-FLOP attribution for the MFU block: the moment fold is
    # ~10 elementwise ops per (row, column) cell per chunk (count, sum,
    # chunk mean, centered delta, M2, min, max) — a documented analytic
    # bound, the same stance as the Pallas kernel estimate. Upload
    # overlap rides inside the window, so seconds is the fold's
    # device-side wall. A pass that compiled records NOTHING — the
    # scoring engine's warm-only discipline: compile time must not
    # pollute the MFU denominator, and untimed flops in a timed phase
    # would inflate its rate just as badly. A cached jit WRAPPER can
    # still recompile when the input sharding changes under the same
    # (chunk, k, dtype) key, so the compile clock — fed by
    # jax.monitoring whenever the fit paths installed the listener —
    # backstops the cache-presence heuristic.
    compiled_in_window = (not prog_was_cached
                          or telemetry.compile_clock_s()
                          > compile_clock0)
    if not compiled_in_window:
        telemetry.record_device_work(
            "fitstats", flops=10.0 * chunk * k * max(len(parts), 1),
            seconds=time.perf_counter() - t_fold0)

    # the per-chunk partials merge on host (Chan); the device-side column
    # reductions above are the psum GSPMD inserted when `sharding` is set
    # — the span makes the merge (and so the data-axis fan-in) visible on
    # the Perfetto timeline next to the per-axis occupancy gauges
    with telemetry.span("fit:psum_merge", chunks=len(parts), columns=k,
                        sharded=sharding is not None):
        cnt, mean, m2, mn, mx = _chan_combine(parts)
    out: Dict[str, Dict[Tuple, Any]] = {}
    for j, nm in enumerate(names):
        c = int(cnt[j])
        if states_out is not None:
            # the fold's Chan-merged partials ARE the sufficient stats —
            # the state the continual tier persists with the model
            states_out[nm] = SufficientStats(
                float(cnt[j]), float(mean[j]), float(m2[j]),
                float(mn[j]), float(mx[j]))
        vals: Dict[Tuple, Any] = {}
        for kind, params_list in col_kinds[nm].items():
            for params in params_list:
                if kind == "count":
                    v: Any = c
                elif c == 0:
                    v = None
                elif kind == "mean":
                    v = float(mean[j])
                elif kind == "variance":
                    v = float(m2[j] / c)
                elif kind == "std":
                    ddof = params[0] if params else 0
                    v = (float(np.sqrt(m2[j] / (c - ddof)))
                         if c > ddof else None)
                elif kind == "min":
                    v = float(mn[j])
                elif kind == "max":
                    v = float(mx[j])
                else:  # pragma: no cover
                    raise ValueError(f"unknown moment kind {kind!r}")
                vals[(kind, params)] = v
        out[nm] = vals
    return out


# ---------------------------------------------------------------------------
# streaming execution — the out-of-core twin of the device fold
# ---------------------------------------------------------------------------


class StreamingMomentFold:
    """Accumulate the device moment fold over row batches as they stream
    off a directory reader — no materialized store, host memory bounded
    at one staging chunk.

    Bit-parity with :func:`_device_moment_bundles` by construction: the
    incoming batches re-buffer into the EXACT fixed-shape chunks the
    materialized fold would cut the concatenated rows into —
    ``FITSTATS_CHUNK_ROWS`` rows once the stream exceeds one chunk, else
    the single padded ``_chunk_rows(n)`` chunk — each chunk runs the
    SAME jitted ``_moment_program`` (shared cache key) and the per-chunk
    partials Chan-combine in the same stream order, so ``finalize()``
    returns per-column :class:`SufficientStats` whose finalized values
    are bit-identical to a materialized device pass over the same rows.
    The fold is device-tier only (the out-of-core regime is far above
    the fusion row floor); a device failure raises to the caller, whose
    fallback is materializing.

    Usage: construct with the tracked column names, call
    ``update(batch_store)`` per streamed batch (a ColumnStore with those
    columns), then ``finalize()`` once the stream is drained.
    """

    def __init__(self, columns: Sequence[str], mesh=None):
        import jax

        self.columns = sorted(columns)
        self._k = len(self.columns)
        f64 = jax.config.jax_enable_x64
        self._dtype = np.float64 if f64 else np.float32
        self._mesh = mesh
        self._parts: List[Tuple] = []
        self._pending = None
        self._fill = 0
        self._n = 0
        self._flushed = 0
        self._fold_s = 0.0
        self._prog_key = None
        self._prog_was_cached = True
        self._cc0 = None
        self._V = None
        self._B = None
        self._taken: List[np.ndarray] = []

    @property
    def n_rows(self) -> int:
        return self._n

    def _ensure_buffers(self) -> None:
        if self._V is None:
            pool = _stage_pool()
            self._V = pool.take((FITSTATS_CHUNK_ROWS, self._k),
                                self._dtype)
            self._B = pool.take((FITSTATS_CHUNK_ROWS, self._k), bool)
            self._taken = [self._V, self._B]

    def _sharding(self, chunk: int):
        if _MESH_OFF or self._mesh is False:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .parallel.mesh import mesh_if_multi, process_default_mesh
        mesh = mesh_if_multi(self._mesh if self._mesh is not None
                             else process_default_mesh())
        if mesh is not None and chunk % mesh.shape["data"] == 0:
            return NamedSharding(mesh, P("data", None))
        return None

    def _program(self, chunk: int):
        from . import telemetry
        key = (chunk, self._k, str(self._dtype))
        if self._prog_key is None:
            self._prog_key = key
            self._prog_was_cached = key in _PROGRAM_CACHE
            self._cc0 = telemetry.compile_clock_s()
        return _moment_program(*key)

    def _place_flush(self, chunk: int):
        """Upload the staging buffers as one chunk (multi-chunk path:
        plain/sharded device_put — contents never repeat within the
        stream, so the content-keyed cache would be pure overhead) and
        hand fresh staging buffers to the accumulator."""
        import time as _time

        import jax
        t0 = _time.perf_counter()
        v, b, taken = self._V, self._B, self._taken
        sharding = self._sharding(chunk)
        if sharding is not None:
            vd = jax.device_put(v, sharding)
            bd = jax.device_put(b, sharding)
        else:
            vd = jax.device_put(v)
            bd = jax.device_put(b)
        prog = self._program(chunk)
        placed = (prog(vd, bd), taken)
        self._fold_s += _time.perf_counter() - t0
        self._V = self._B = None
        self._taken = []
        self._fill = 0
        return placed

    def _pull(self, placed) -> None:
        import time as _time

        import jax
        t0 = _time.perf_counter()
        out, taken = placed
        self._parts.append(jax.device_get(out))
        self._fold_s += _time.perf_counter() - t0
        pool = _stage_pool()
        for buf in taken:
            pool.give(buf)

    def _flush_full(self) -> None:
        # double-buffered (the materialized fold's discipline): chunk
        # i+1's upload is issued before chunk i's result is pulled;
        # TMOG_PIPELINE=0 serializes
        from .pipeline import PIPELINE_ENABLED as _pipe_on
        placed = self._place_flush(FITSTATS_CHUNK_ROWS)
        self._flushed += 1
        if not _pipe_on:
            self._pull(placed)
            return
        if self._pending is not None:
            self._pull(self._pending)
        self._pending = placed

    def update(self, store) -> None:
        """Fold one streamed batch (a ColumnStore carrying the tracked
        columns) into the running chunked state."""
        if not self.columns:
            return
        m = store.n_rows
        if m == 0:
            return
        Vb = np.empty((m, self._k), self._dtype)
        Bb = np.empty((m, self._k), bool)
        for j, nm in enumerate(self.columns):
            col = store[nm]
            Bb[:, j] = col.mask
            Vb[:, j] = np.where(col.mask,
                                col.values.astype(np.float64), 0.0)
        off = 0
        while off < m:
            if self._fill == FITSTATS_CHUNK_ROWS:
                # full AND more rows exist: only now is this a full
                # interior chunk (a stream of exactly one chunk must
                # stay the padded single-chunk program)
                self._flush_full()
            self._ensure_buffers()
            take = min(FITSTATS_CHUNK_ROWS - self._fill, m - off)
            self._V[self._fill:self._fill + take] = Vb[off:off + take]
            self._B[self._fill:self._fill + take] = Bb[off:off + take]
            self._fill += take
            off += take
        self._n += m

    def finalize(self) -> Dict[str, SufficientStats]:
        """Drain the fold and return each column's full-stream
        :class:`SufficientStats` (the Chan-merged fold partials —
        exactly what the materialized device pass reports via
        ``states_out``)."""
        import jax

        from . import telemetry
        if not self.columns:
            return {}
        if self._flushed == 0:
            # single (padded) chunk: mirror the materialized one_chunk
            # path — fresh pad arrays + the content-keyed upload cache
            # (pool buffers would alias into the cache and corrupt it)
            import time as _time
            t0 = _time.perf_counter()
            chunk = _chunk_rows(self._n)
            vp = np.zeros((chunk, self._k), self._dtype)
            bp = np.zeros((chunk, self._k), bool)
            if self._V is not None:
                vp[:self._fill] = self._V[:self._fill]
                bp[:self._fill] = self._B[:self._fill]
                pool = _stage_pool()
                for buf in self._taken:
                    pool.give(buf)
                self._V = self._B = None
                self._taken = []
            sharding = self._sharding(chunk)
            if sharding is not None:
                vd = jax.device_put(vp, sharding)
                bd = jax.device_put(bp, sharding)
            else:
                from .models.base import device_put_f32
                vd = device_put_f32(vp)
                bd = device_put_f32(bp)
            prog = self._program(chunk)
            self._parts.append(jax.device_get(prog(vd, bd)))
            self._fold_s += _time.perf_counter() - t0
        else:
            chunk = FITSTATS_CHUNK_ROWS
            if self._V is not None:
                # pad the tail chunk in place (pool staging, like the
                # materialized multi-chunk tail)
                self._V[self._fill:] = 0
                self._B[self._fill:] = False
                placed = self._place_flush(chunk)
                if self._pending is not None:
                    self._pull(self._pending)
                    self._pending = None
                self._pull(placed)
            if self._pending is not None:
                self._pull(self._pending)
                self._pending = None
        _tally("device_passes")
        _tally("stream_chunks", len(self._parts))
        _tally("bytes_scanned",
               int(self._n) * self._k
               * (np.dtype(self._dtype).itemsize + 1))
        compiled_in_window = (not self._prog_was_cached
                              or (self._cc0 is not None
                                  and telemetry.compile_clock_s()
                                  > self._cc0))
        if not compiled_in_window:
            telemetry.record_device_work(
                "fitstats",
                flops=10.0 * chunk * self._k * max(len(self._parts), 1),
                seconds=self._fold_s)
        with telemetry.span("fit:psum_merge", chunks=len(self._parts),
                            columns=self._k, sharded=False,
                            streamed=True):
            cnt, mean, m2, mn, mx = _chan_combine(self._parts)
        return {nm: SufficientStats(float(cnt[j]), float(mean[j]),
                                    float(m2[j]), float(mn[j]),
                                    float(mx[j]))
                for j, nm in enumerate(self.columns)}


# ---------------------------------------------------------------------------
# the layer plan
# ---------------------------------------------------------------------------


def _col_bytes(col) -> int:
    """Host bytes backing one column (values + explicit mask) — the
    unit of the ``fitstats.bytes_scanned`` counter."""
    b = 0
    v = getattr(col, "values", None)
    if isinstance(v, np.ndarray):
        b += v.nbytes
    elif isinstance(v, list):
        b += 8 * len(v)
    m = col.__dict__.get("mask")        # NOT TextColumn's computed property
    if isinstance(m, np.ndarray):
        b += m.nbytes
    return b


class LayerStatsPlan:
    """All of one DAG layer's StatRequests, deduplicated, executed as a
    single pass over the train store."""

    def __init__(self, requests: Sequence[StatRequest], n_stages: int = 1):
        dedup: Dict[Tuple, StatRequest] = {}
        for r in requests:
            dedup.setdefault(r.key(), r)
        self.requests: List[StatRequest] = list(dedup.values())
        self.n_stages = n_stages

    @property
    def n_requests(self) -> int:
        return len(self.requests)

    @staticmethod
    def _warm_merge(states: Dict[str, SufficientStats],
                    warm_state: Optional[Mapping[str, SufficientStats]]
                    ) -> Dict[str, SufficientStats]:
        """Chan-merge the fresh-slice states with the persisted warm
        states, per column, through the ``continual.merge_stats`` fault
        site. A fault (or a malformed warm record) degrades THAT
        column to fresh-only stats — warm start is an optimization,
        never a dependency — and the degradation is logged + counted,
        never silent."""
        merged: Dict[str, SufficientStats] = {}
        if not warm_state:
            return merged
        from . import resilience, telemetry
        for nm, fresh in states.items():
            warm = warm_state.get(nm)
            if warm is None:
                continue
            try:
                resilience.inject("continual.merge_stats", column=nm)
                merged[nm] = warm.merge(fresh)
            except Exception:  # lint: broad-except — a failed merge degrades this column to fresh-only stats
                logger.exception(
                    "warm-state merge for column %r failed; the refit "
                    "uses fresh-slice stats for it", nm)
                continue
            _tally("warm_state_merges")
            telemetry.counter("fitstats.warm_state_merges").inc()
        if merged:
            logger.info("fitstats: warm-merged %d column(s) of "
                        "persisted sufficient stats into this pass",
                        len(merged))
        return merged

    def _gate_device(self, store, tier_hint: Optional[str] = None) -> bool:
        # the breaker is deliberately process-wide (unlike the
        # per-model scoring.engine breaker): the moment-fold program is
        # model-independent — (chunk, width, dtype) shapes, no plan —
        # so a device-pass failure is a backend/link property every
        # workflow in the process shares. allow() goes LAST in the
        # chain: it may consume the half-open probe, and short-circuit
        # guarantees a device attempt (which reports back) follows.
        #
        # ``tier_hint`` is the planner's measured per-phase decision
        # (planner.ExecutionPlan.fitstats_tier): it overrides only the
        # BANDWIDTH half of the gate — the row floor (below it compile
        # cost dominates any link) and the breaker always hold.
        from . import resilience
        from .workflow import (FUSE_MIN_BANDWIDTH_MBPS, FUSE_MIN_ROWS,
                               device_roundtrip_mbps)
        if store.n_rows < FUSE_MIN_ROWS:
            return False
        if tier_hint == "host":
            return False
        if tier_hint != "device" \
                and device_roundtrip_mbps() < FUSE_MIN_BANDWIDTH_MBPS:
            return False
        return resilience.breaker("fitstats.device").allow()

    def run(self, store, device: Optional[bool] = None,
            mesh=None, tier_hint: Optional[str] = None,
            state_out: Optional[Dict[str, SufficientStats]] = None,
            warm_state: Optional[Mapping[str, SufficientStats]] = None,
            stream_state: Optional[Mapping[str, SufficientStats]] = None
            ) -> StatResults:
        """Execute every request in one pass; ``device`` overrides the
        bandwidth/row gate (tests pin it either way), ``tier_hint``
        (the planner's measured decision, ``"host"``/``"device"``)
        overrides only the bandwidth half — the row floor and the
        device-tier breaker always hold. ``mesh`` is the caller's
        (data, grid) mesh for the device tier's row sharding — None
        falls back to the cached process default, ``False`` forces the
        unsharded path.

        The continual-learning seam: ``state_out`` (a dict the caller
        provides) receives each moment column's :class:`SufficientStats`
        so the train can persist them with the model; ``warm_state``
        maps columns to PERSISTED stats from a previous train — each
        present column's fresh-slice state is Chan-merged with it
        (through the ``continual.merge_stats`` fault site) and the
        moment-family request values finalize from the MERGED state, so
        the refit covers [old window + fresh slice] without rescanning
        the old window. Columns without a warm entry stay fresh-only;
        non-moment kinds (quantiles, top-K, sanity) are not mergeable
        and always compute over the fresh store.

        The out-of-core seam: ``stream_state`` maps columns to
        full-stream :class:`SufficientStats` a
        :class:`StreamingMomentFold` produced over the un-materialized
        data. A moment request whose column is covered finalizes from
        the STREAMED state — bit-identical to a materialized device
        pass over the full stream — and the (bounded subsample) store
        is never scanned for it; uncovered columns and non-moment kinds
        compute from ``store`` as usual. ``warm_state`` composes:
        streamed states Chan-merge with warm entries like fresh ones."""
        from . import telemetry

        import time

        t_run = time.perf_counter()
        c_run = telemetry._COMPILE_CLOCK["s"]
        moment_cols: Dict[str, Dict[str, List[Tuple]]] = {}
        other: List[StatRequest] = []
        for r in self.requests:
            if r.kind in _MOMENT_KINDS:
                moment_cols.setdefault(r.column, {}) \
                    .setdefault(r.kind, []).append(tuple(r.params))
            else:
                other.append(r)

        # out-of-core seam: moment columns the streaming fold already
        # covered finalize from the streamed full-data state — the
        # (subsample) store is never scanned for them
        stream_cols: Dict[str, Dict[str, List[Tuple]]] = {}
        if stream_state:
            stream_cols = {nm: moment_cols.pop(nm)
                           for nm in list(moment_cols)
                           if nm in stream_state}

        # moment_cols first: _gate_device's breaker allow() may consume
        # the open breaker's single half-open probe, so it must only be
        # asked when a device pass (which reports the probe's outcome)
        # would actually run
        use_device = bool(moment_cols) and (
            self._gate_device(store, tier_hint) if device is None
            else bool(device))

        values: Dict[Tuple, Any] = {}
        touched: Dict[str, int] = {}
        #: fresh-slice SufficientStats per moment column — collected
        #: whenever the caller persists state OR warm-merges
        states: Dict[str, SufficientStats] = {}
        want_state = state_out is not None or warm_state is not None

        if moment_cols or stream_cols:
            bundles: Dict[str, Dict[Tuple, Any]] = {}
            if moment_cols and use_device:
                # device tier behind its fault site + breaker: a failed
                # device pass degrades to the host tier WITHIN this pass
                # (the fused scan still happens — failure costs the
                # layer nothing but the tier), and after N consecutive
                # failures the breaker stops even attempting the device
                from . import resilience
                brk = resilience.breaker("fitstats.device")
                try:
                    resilience.inject("fitstats.device_pass",
                                      rows=store.n_rows)
                    bundles = _device_moment_bundles(
                        store, moment_cols, mesh=mesh,
                        states_out=states if want_state else None)
                    brk.record_success()
                except Exception:  # lint: broad-except — breaker-governed device-tier fallback
                    brk.record_failure()
                    logger.exception(
                        "fitstats device pass failed; computing this "
                        "pass on the host tier")
                    use_device = False
                    states.clear()
                    # restart the phase-cost window: the failed device
                    # attempt's time must not be charged to the HOST
                    # observation below (it would bias the cost db
                    # toward the very tier that is failing)
                    t_run = time.perf_counter()
                    c_run = telemetry._COMPILE_CLOCK["s"]
            if moment_cols and not use_device:
                bundles = {nm: _host_moment_bundle(
                    store[nm], kinds,
                    state_out=states if want_state else None, name=nm)
                    for nm, kinds in moment_cols.items()}
            for nm in stream_cols:
                # the streamed state IS this column's sufficient stats:
                # it persists with the model and warm-merges like a
                # fresh-slice state
                states[nm] = stream_state[nm]
            merged = self._warm_merge(states, warm_state)
            for r in self.requests:
                if r.kind in _MOMENT_KINDS:
                    touched.setdefault(r.column, _col_bytes(store[r.column]))
                    if r.column in merged:
                        # warm start: the value reflects [old + fresh]
                        values[r.key()] = merged[r.column].finalize(
                            r.kind, tuple(r.params))
                    elif r.column in stream_cols:
                        values[r.key()] = stream_state[r.column].finalize(
                            r.kind, tuple(r.params))
                    else:
                        values[r.key()] = \
                            bundles[r.column][(r.kind, tuple(r.params))]
            if state_out is not None:
                # the persisted state is the cumulative union: a chain
                # of warm retrains keeps accumulating, never resets
                state_out.update({**states, **merged})

        for r in other:
            exec_fn = _HOST_EXEC.get(r.kind)
            if exec_fn is None:
                raise ValueError(f"unknown stat kind {r.kind!r}")
            values[r.key()] = exec_fn(store, r)
            touched.setdefault(r.column, _col_bytes(store[r.column]))
            if r.label is not None:
                touched.setdefault(r.label, _col_bytes(store[r.label]))

        scanned = sum(touched.values())
        saved = max(self.n_stages - 1, 0)
        _tally("layers_fused")
        _tally("passes_saved", saved)
        _tally("bytes_scanned", scanned)
        _tally("device_passes" if use_device else "host_passes")
        telemetry.counter("fitstats.layers_fused").inc()
        telemetry.counter("fitstats.passes_saved").inc(saved)
        telemetry.counter("fitstats.bytes_scanned").inc(scanned)
        telemetry.counter(  # lint: metric-name — one of two literal names
            "fitstats.device_passes" if use_device
            else "fitstats.host_passes").inc()
        logger.info(
            "fitstats: %d request(s) for %d stage(s) in one %s pass "
            "(%d column(s), %.1f MB scanned, %d pass(es) saved)",
            self.n_requests, self.n_stages,
            "device" if use_device else "host", len(touched),
            scanned / 1e6, saved)
        # feed the planner's measured per-phase tier costs — only at
        # row counts where the tier decision is contested, so the two
        # tiers' s/krow observations stay comparable (planner.py); the
        # one-time XLA compile of the fold program is subtracted so a
        # cold pass cannot poison the device tier's steady-state mean
        from .workflow import FUSE_MIN_ROWS
        if moment_cols and store.n_rows >= FUSE_MIN_ROWS:
            from . import planner
            elapsed = time.perf_counter() - t_run
            compile_s = min(telemetry._COMPILE_CLOCK["s"] - c_run,
                            elapsed)
            planner.observe_phase(
                "fitstats", "device" if use_device else "host",
                elapsed - compile_s, store.n_rows)
        return StatResults(values)
