"""Directory-watching streaming reader — the ``StreamingReaders`` analog.

Parity: ``readers/src/main/scala/com/salesforce/op/readers/
StreamingReaders.scala:1`` exposes ``avroStream``/``customStream``: a Spark
``StreamingContext.fileStream`` that watches a directory and turns every
NEW file into a micro-batch RDD. The TPU-native runtime has no long-lived
cluster scheduler, so the same contract is a host-side poll loop: snapshot
the directory, yield each unseen file's records as one batch, sleep, poll
again. Batches feed ``readers.stream_score`` (the incremental scorer) —
peak memory stays one file's records, matching the micro-batch semantics.

File formats route by extension: ``.avro`` through the in-repo container
codec (readers/avro.py), ``.csv`` through the header-driven auto reader.
``newFilesOnly`` matches Spark's flag (default True there; default False
here because a batch-backfill-then-tail is the common local workflow).
"""
from __future__ import annotations

import glob
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

__all__ = ["DirectoryStreamReader"]


class _NoReaderError(ValueError):
    """Unknown file extension — a configuration error, not a bad file."""


class DirectoryStreamReader:
    """Poll a directory and yield each new data file's records as a batch.

    ``stream(...)`` is a generator of ``List[dict]`` batches; it ends when
    ``max_batches`` or ``timeout_s`` is reached (both None = forever,
    Spark's awaitTermination). A file is only picked up once its mtime is
    at least ``settle_s`` old, so half-written files aren't read (the
    poor-host's analog of Spark's rename-into-place convention).
    """

    def __init__(self, path: str, pattern: str = "*",
                 reader_for: Optional[Callable[[str], List[Dict[str, Any]]]]
                 = None,
                 new_files_only: bool = False,
                 poll_interval_s: float = 1.0,
                 settle_s: float = 0.5,
                 columnar: bool = True):
        self.path = path
        self.pattern = pattern
        self.reader_for = reader_for
        self.new_files_only = new_files_only
        self.poll_interval_s = poll_interval_s
        self.settle_s = settle_s
        #: route Avro decode through the vectorized columnar fast path
        #: (avro.read_avro_table — bit-identical batches that iterate
        #: as the same dicts, but numpy-decoded so the pipeline's
        #: workers escape the GIL). False = the pre-pipeline per-record
        #: Python decoder, kept for the bench's serial baseline leg.
        self.columnar = columnar
        self._seen: set = set()
        #: files successfully read AND delivered — the rescan unit.
        #: Quarantined / no-reader files live only in ``_seen`` so a
        #: rescan never re-offers (and never re-quarantines) them.
        self._delivered: set = set()
        #: interruptible idle wait: ``stop()`` wakes a sleeping
        #: ``stream()`` immediately instead of blocking shutdown a full
        #: poll interval
        self._stop = threading.Event()
        if new_files_only:
            self._seen.update(self._snapshot())

    def stop(self) -> None:
        """Ask a running ``stream()`` to end now: the idle wait is an
        Event wait, so shutdown never blocks a full ``poll_interval_s``.
        The next ``stream()`` call on this reader starts fresh."""
        self._stop.set()

    def rescan(self) -> int:
        """Re-offer every file this reader has successfully DELIVERED, so
        a multi-pass consumer (out-of-core training) re-reads the same
        directory without reconstructing the reader. Returns the number
        of files re-offered. Quarantined and no-reader files stay seen —
        a bad file is quarantined (and counted) exactly once, never once
        per pass — and ``new_files_only`` pre-seeded files stay
        suppressed (they were never delivered)."""
        n = len(self._delivered)
        self._seen -= self._delivered
        self._delivered.clear()
        return n

    # -- format routing ----------------------------------------------------
    def _read_file(self, fp: str) -> List[Dict[str, Any]]:
        from .. import resilience
        resilience.inject("stream.read_file", path=fp)
        if self.reader_for is not None:
            return self.reader_for(fp)
        ext = os.path.splitext(fp)[1].lower()
        if ext == ".avro":
            from .. import pipeline
            from .avro import read_avro_records, read_avro_table
            return read_avro_table(fp) \
                if self.columnar and pipeline.PIPELINE_ENABLED \
                else read_avro_records(fp)
        if ext == ".csv":
            from .data_readers import CSVAutoReader
            return CSVAutoReader(fp).read_records()
        raise _NoReaderError(
            f"no reader for {fp!r} — pass reader_for= for custom formats "
            "(StreamingReaders.customStream analog)")

    def _snapshot(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.path, self.pattern)))

    def _poll_snapshot(self) -> List[str]:
        """One directory poll behind its fault site — a transient listing
        failure (network mount blip) rides ``READER_RETRY`` instead of
        killing the stream."""
        from .. import resilience
        resilience.inject("stream.poll", path=self.path)
        return self._snapshot()

    def _ready(self, fp: str) -> bool:
        try:
            # mtime comparison: MUST stay on the wall clock — file
            # mtimes and perf_counter share no epoch
            return (time.time() - os.path.getmtime(fp)) >= self.settle_s  # lint: wall-clock
        except OSError:
            return False        # vanished between glob and stat

    # -- the stream --------------------------------------------------------
    def _take_next(self) -> Optional[List[Dict[str, Any]]]:
        """Consume ONE settled unseen file (oldest first) — files are
        marked seen one at a time AFTER a successful read, so a consumer
        that stops at ``max_batches`` leaves later files re-offered on
        the next poll, never silently dropped. A file whose read RAISES
        gets the reader retry policy for transient IO (``OSError``);
        when retries exhaust — or the failure is non-transient (corrupt
        container) — the file is QUARANTINED to the dead-letter sink
        with its reason, counted (``resilience.quarantined_files``),
        marked seen and skipped: retrying it every poll would wedge the
        stream forever, and dropping it without trace loses data
        silently (the pre-resilience behavior)."""
        snapshot = self._retried_poll()
        for fp in snapshot:
            if fp in self._seen or not self._ready(fp):
                continue
            try:
                recs = self._retried_read(fp)
            except Exception as e:  # lint: broad-except — ANY read failure quarantines, never wedges the stream
                self._consume_error(fp, e)
                continue
            self._seen.add(fp)
            self._delivered.add(fp)
            return recs
        return None

    def _unseen_visible(self) -> bool:
        """Any file visible right now that this pass has not consumed?
        Settle state is ignored on purpose: an unseen-but-unsettled file
        means the pass is NOT drained yet (the caller idle-waits and
        re-polls). Plain snapshot — this runs once per drained poll, so
        it skips the retry/telemetry wrapping of the hot poll path."""
        try:
            snap = self._snapshot()
        except OSError:
            return False
        return any(fp not in self._seen for fp in snap)

    def _retried_poll(self) -> List[str]:
        """One retried directory listing + the backlog gauge."""
        from .. import resilience, telemetry
        snapshot = resilience.READER_RETRY.call(
            "stream.poll", self._poll_snapshot)
        if telemetry.enabled():
            # unconsumed files visible right now (including ones still
            # settling): the ingest backlog — a growing value means
            # scoring can't keep up with arrivals. Pure set arithmetic
            # off the listing this poll already does; no extra stat I/O.
            telemetry.gauge("stream.file_backlog").set(
                sum(1 for fp in snapshot if fp not in self._seen))
        return snapshot

    def _retried_read(self, fp: str) -> List[Dict[str, Any]]:
        """One file's records behind READER_RETRY — the decode unit the
        parallel workers run; the ``stream.read_file``/``avro.decode``/
        ``csv.decode`` fault sites all fire inside it, on whichever
        thread executes it."""
        from .. import resilience
        return resilience.READER_RETRY.call(
            "stream.read_file", self._read_file, fp)

    def _consume_error(self, fp: str, exc: BaseException) -> None:
        """The ONE poison-file policy both the serial and the parallel
        consumers apply, in file order: an unknown extension is a
        CONFIGURATION gap that re-raises (after marking seen so it
        cannot wedge the stream), anything else quarantines the file
        with its reason and the stream flows on."""
        import logging

        from .. import resilience
        if isinstance(exc, _NoReaderError):
            # the file must still be marked seen before raising or it
            # wedges the stream (every later poll re-hits it) and
            # blocks the readable files behind it
            self._seen.add(fp)
            raise exc
        logging.getLogger(__name__).warning(
            "stream reader quarantining unreadable file %s",
            fp, exc_info=exc)
        resilience.quarantine("stream.read_file", repr(exc),
                              kind="files", path=fp)
        self._seen.add(fp)

    def poll_once(self) -> List[List[Dict[str, Any]]]:
        """One poll: read every settled unseen file, oldest first."""
        batches = []
        while True:
            recs = self._take_next()
            if recs is None:
                return batches
            if recs:
                batches.append(recs)

    def _idle_wait(self, t0: float,
                   timeout_s: Optional[float]) -> bool:
        """Idle between polls; returns False when the stream should end
        (timeout elapsed or :meth:`stop` was called). The wait is an
        interruptible Event wait clamped to the REMAINING timeout — a
        ``timeout_s`` shorter than ``poll_interval_s`` is honored, and
        ``stop()``/``max_batches`` never block a full interval."""
        remaining = None
        if timeout_s is not None:
            remaining = timeout_s - (time.perf_counter() - t0)
            if remaining <= 0:
                return False
        wait = self.poll_interval_s if remaining is None \
            else min(self.poll_interval_s, remaining)
        return not self._stop.wait(wait)

    def stream(self, max_batches: Optional[int] = None,
               timeout_s: Optional[float] = None,
               workers: Optional[int] = None,
               passes: Optional[int] = None
               ) -> Iterator[List[Dict[str, Any]]]:
        """Yield per-file record batches as files appear.

        A productive poll is followed by another poll IMMEDIATELY (the
        stream only sleeps when a poll found nothing new), and the idle
        sleep is interruptible (:meth:`stop`) and clamped to the
        remaining ``timeout_s``.

        ``workers`` > 1 decodes the settled files of each poll on a
        parallel worker pool (pipeline.py) with DETERMINISTIC order:
        batches arrive in sorted-file order, bit-identical to the
        serial decode, and the ``stream.read_file``/``avro.decode``/
        ``csv.decode`` fault sites + READER_RETRY + poison-file
        quarantine run inside the workers unchanged.

        ``passes`` = N bounds the stream to N full scans of the
        directory (:meth:`rescan` runs between them): when a poll finds
        NOTHING unseen — not even a still-settling file — the pass is
        drained; the stream ends after pass N instead of idle-waiting
        for new arrivals. ``max_batches`` counts across all passes, and
        ``stop()``/``timeout_s`` keep their meaning. None (default) is
        the single-pass tail-forever behavior, unchanged."""
        self._stop.clear()
        if passes is not None:
            passes = int(passes)
            if passes < 1:
                raise ValueError("passes must be >= 1")
        if workers is not None:
            # an explicit count still rides the TMOG_PIPELINE=0 kill
            # switch (resolve_workers forces 1 — the incident lever is
            # not overridable); None keeps the serial default
            from .. import pipeline
            workers = pipeline.resolve_workers(int(workers))
        if workers is not None and workers > 1:
            yield from self._stream_parallel(workers, max_batches,
                                             timeout_s, passes)
            return
        t0 = time.perf_counter()
        n = 0
        pass_no = 1
        while True:
            if self._stop.is_set():
                return
            recs = self._take_next()
            if recs is not None:
                if recs:
                    yield recs
                    n += 1
                    if max_batches is not None and n >= max_batches:
                        return
                continue            # drain without sleeping
            if passes is not None and not self._unseen_visible():
                pass_no += 1        # directory drained: pass ends
                if pass_no > passes:
                    return
                self.rescan()
                continue
            if not self._idle_wait(t0, timeout_s):
                return

    def _stream_parallel(self, workers: int,
                         max_batches: Optional[int],
                         timeout_s: Optional[float],
                         passes: Optional[int] = None
                         ) -> Iterator[List[Dict[str, Any]]]:
        """Parallel-decode poll loop: each poll's settled unseen files
        fan out over the worker pool; the reorder buffer hands results
        back in sorted-file order. Files are marked seen one at a time
        AS THEIR RESULT IS CONSUMED, so a consumer that stops at
        ``max_batches`` leaves later files re-offered on the next poll,
        never silently dropped (the serial contract)."""
        from concurrent.futures import ThreadPoolExecutor

        from .. import pipeline

        t0 = time.perf_counter()
        n = 0
        pass_no = 1
        ex = None
        try:
            while True:
                if self._stop.is_set():
                    return
                snapshot = self._retried_poll()
                ready = [fp for fp in snapshot
                         if fp not in self._seen and self._ready(fp)]
                if ready:
                    if ex is None:
                        # one pool for the stream's lifetime, created
                        # lazily on the first productive poll: an idle
                        # watch never spins up threads, and productive
                        # polls never pay per-poll spin-up/teardown
                        ex = ThreadPoolExecutor(
                            max_workers=workers,
                            thread_name_prefix="stream-decode")
                    for fp, recs, exc in pipeline.map_ordered(
                            self._retried_read, ready, workers=workers,
                            name="stream-decode", executor=ex):
                        if exc is not None:
                            self._consume_error(fp, exc)
                            continue
                        self._seen.add(fp)
                        self._delivered.add(fp)
                        if recs:
                            yield recs
                            n += 1
                            if max_batches is not None \
                                    and n >= max_batches:
                                return
                        if self._stop.is_set():
                            return
                    continue        # productive poll: re-poll immediately
                if passes is not None \
                        and not any(fp not in self._seen
                                    for fp in snapshot):
                    pass_no += 1    # directory drained: pass ends
                    if pass_no > passes:
                        return
                    self.rescan()
                    continue
                if not self._idle_wait(t0, timeout_s):
                    return
        finally:
            if ex is not None:
                ex.shutdown(wait=False)

    # -- DataReader interop (batch fallback) -------------------------------
    def read_records(self) -> List[Dict[str, Any]]:
        """Drain everything currently visible — lets the same reader serve
        the batch run types (the reference's readers are likewise dual)."""
        out: List[Dict[str, Any]] = []
        for batch in self.poll_once():
            out.extend(batch)
        return out
