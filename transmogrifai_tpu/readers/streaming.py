"""Directory-watching streaming reader — the ``StreamingReaders`` analog.

Parity: ``readers/src/main/scala/com/salesforce/op/readers/
StreamingReaders.scala:1`` exposes ``avroStream``/``customStream``: a Spark
``StreamingContext.fileStream`` that watches a directory and turns every
NEW file into a micro-batch RDD. The TPU-native runtime has no long-lived
cluster scheduler, so the same contract is a host-side poll loop: snapshot
the directory, yield each unseen file's records as one batch, sleep, poll
again. Batches feed ``readers.stream_score`` (the incremental scorer) —
peak memory stays one file's records, matching the micro-batch semantics.

File formats route by extension: ``.avro`` through the in-repo container
codec (readers/avro.py), ``.csv`` through the header-driven auto reader.
``newFilesOnly`` matches Spark's flag (default True there; default False
here because a batch-backfill-then-tail is the common local workflow).
"""
from __future__ import annotations

import glob
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

__all__ = ["DirectoryStreamReader"]


class _NoReaderError(ValueError):
    """Unknown file extension — a configuration error, not a bad file."""


class DirectoryStreamReader:
    """Poll a directory and yield each new data file's records as a batch.

    ``stream(...)`` is a generator of ``List[dict]`` batches; it ends when
    ``max_batches`` or ``timeout_s`` is reached (both None = forever,
    Spark's awaitTermination). A file is only picked up once its mtime is
    at least ``settle_s`` old, so half-written files aren't read (the
    poor-host's analog of Spark's rename-into-place convention).
    """

    def __init__(self, path: str, pattern: str = "*",
                 reader_for: Optional[Callable[[str], List[Dict[str, Any]]]]
                 = None,
                 new_files_only: bool = False,
                 poll_interval_s: float = 1.0,
                 settle_s: float = 0.5):
        self.path = path
        self.pattern = pattern
        self.reader_for = reader_for
        self.new_files_only = new_files_only
        self.poll_interval_s = poll_interval_s
        self.settle_s = settle_s
        self._seen: set = set()
        if new_files_only:
            self._seen.update(self._snapshot())

    # -- format routing ----------------------------------------------------
    def _read_file(self, fp: str) -> List[Dict[str, Any]]:
        from .. import resilience
        resilience.inject("stream.read_file", path=fp)
        if self.reader_for is not None:
            return self.reader_for(fp)
        ext = os.path.splitext(fp)[1].lower()
        if ext == ".avro":
            from .avro import read_avro_records
            return read_avro_records(fp)
        if ext == ".csv":
            from .data_readers import CSVAutoReader
            return CSVAutoReader(fp).read_records()
        raise _NoReaderError(
            f"no reader for {fp!r} — pass reader_for= for custom formats "
            "(StreamingReaders.customStream analog)")

    def _snapshot(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.path, self.pattern)))

    def _poll_snapshot(self) -> List[str]:
        """One directory poll behind its fault site — a transient listing
        failure (network mount blip) rides ``READER_RETRY`` instead of
        killing the stream."""
        from .. import resilience
        resilience.inject("stream.poll", path=self.path)
        return self._snapshot()

    def _ready(self, fp: str) -> bool:
        try:
            # mtime comparison: MUST stay on the wall clock — file
            # mtimes and perf_counter share no epoch
            return (time.time() - os.path.getmtime(fp)) >= self.settle_s  # lint: wall-clock
        except OSError:
            return False        # vanished between glob and stat

    # -- the stream --------------------------------------------------------
    def _take_next(self) -> Optional[List[Dict[str, Any]]]:
        """Consume ONE settled unseen file (oldest first) — files are
        marked seen one at a time AFTER a successful read, so a consumer
        that stops at ``max_batches`` leaves later files re-offered on
        the next poll, never silently dropped. A file whose read RAISES
        gets the reader retry policy for transient IO (``OSError``);
        when retries exhaust — or the failure is non-transient (corrupt
        container) — the file is QUARANTINED to the dead-letter sink
        with its reason, counted (``resilience.quarantined_files``),
        marked seen and skipped: retrying it every poll would wedge the
        stream forever, and dropping it without trace loses data
        silently (the pre-resilience behavior)."""
        import logging

        from .. import resilience, telemetry
        snapshot = resilience.READER_RETRY.call(
            "stream.poll", self._poll_snapshot)
        if telemetry.enabled():
            # unconsumed files visible right now (including ones still
            # settling): the ingest backlog — a growing value means
            # scoring can't keep up with arrivals. Pure set arithmetic
            # off the listing this poll already does; no extra stat I/O.
            telemetry.gauge("stream.file_backlog").set(
                sum(1 for fp in snapshot if fp not in self._seen))
        for fp in snapshot:
            if fp in self._seen or not self._ready(fp):
                continue
            try:
                recs = resilience.READER_RETRY.call(
                    "stream.read_file", self._read_file, fp)
            except _NoReaderError:
                # unknown extension: a CONFIGURATION gap, but the file
                # must still be marked seen before raising or it wedges
                # the stream (every later poll re-hits it) and blocks
                # the readable files behind it
                self._seen.add(fp)
                raise
            except Exception as e:  # lint: broad-except — ANY read failure quarantines, never wedges the stream
                logging.getLogger(__name__).warning(
                    "stream reader quarantining unreadable file %s",
                    fp, exc_info=True)
                resilience.quarantine("stream.read_file", repr(e),
                                      kind="files", path=fp)
                self._seen.add(fp)
                continue
            self._seen.add(fp)
            return recs
        return None

    def poll_once(self) -> List[List[Dict[str, Any]]]:
        """One poll: read every settled unseen file, oldest first."""
        batches = []
        while True:
            recs = self._take_next()
            if recs is None:
                return batches
            if recs:
                batches.append(recs)

    def stream(self, max_batches: Optional[int] = None,
               timeout_s: Optional[float] = None
               ) -> Iterator[List[Dict[str, Any]]]:
        """Yield per-file record batches as files appear."""
        t0 = time.perf_counter()
        n = 0
        while True:
            recs = self._take_next()
            if recs is not None:
                if recs:
                    yield recs
                    n += 1
                    if max_batches is not None and n >= max_batches:
                        return
                continue            # drain without sleeping
            if timeout_s is not None \
                    and time.perf_counter() - t0 >= timeout_s:
                return
            time.sleep(self.poll_interval_s)

    # -- DataReader interop (batch fallback) -------------------------------
    def read_records(self) -> List[Dict[str, Any]]:
        """Drain everything currently visible — lets the same reader serve
        the batch run types (the reference's readers are likewise dual)."""
        out: List[Dict[str, Any]] = []
        for batch in self.poll_once():
            out.extend(batch)
        return out
