from .data_readers import (DataReader, CSVReader, CSVAutoReader,  # noqa: F401
                           AggregateReader, ConditionalReader, DataReaders,
                           JoinedDataReader, CutOffTime)
