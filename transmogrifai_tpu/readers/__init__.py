from .data_readers import (DataReader, CSVReader, CSVAutoReader,  # noqa: F401
                           ParquetReader, AvroReader,
                           AggregateReader, ConditionalReader, DataReaders,
                           JoinedDataReader, JoinedAggregateDataReader,
                           TemporalJoinReader,
                           TimeBasedFilter, FilteredReader, CutOffTime,
                           stream_score)
from .avro import (ColumnarRecords, read_avro_records,  # noqa: F401
                   read_avro_table)
from .streaming import DirectoryStreamReader  # noqa: F401
