"""Data readers — host-side ingestion into record dicts / ColumnStores.

Parity: ``readers/`` module (``DataReader.scala:57-230``,
``DataReaders.scala:43-278``, ``JoinedDataReader.scala:54-418``). Spark's
distributed read is replaced by host ingestion (readers run on CPU; only
dense arrays reach the device), keeping the same API shape:

* ``DataReader.read_records()`` → list of record dicts
* ``AggregateReader`` — group records by key, fold each feature's values
  through its monoid aggregator with event-time cutoff filtering
  (``FeatureAggregator.extract``: responses strictly AFTER cutoff,
  predictors strictly BEFORE — leak prevention,
  ``FeatureAggregator.scala:99-119``; the cutoff event itself lands in
  NEITHER fold — docs/readers.md has the boundary table)
* ``ConditionalReader`` — per-key cutoff fixed by an event predicate
  (``ConditionalParams``)
* ``JoinedDataReader`` — typed left-outer/inner joins on keys
* ``TemporalJoinReader`` — the streaming/columnar hash join
  (consistent-hash partitioned bounded build tables, vectorized probe
  when both sides are columnar — temporal.py)
* ``DataReaders.simple/aggregate/conditional`` factories

Aggregating readers auto-route to the COLUMNAR temporal engine
(``temporal.route_aggregate``) when their source yields a columnar
batch — bit-identical to the row-wise fold, vectorized group/filter —
and degrade back to the row-wise loop on any columnar failure.
"""
from __future__ import annotations

import csv as _csv
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

from ..columns import ColumnStore, column_from_values
from ..features import Feature
from ..stages.generator import FeatureGeneratorStage

__all__ = ["DataReader", "CSVReader", "CSVAutoReader", "ParquetReader",
           "AvroReader", "AggregateReader", "ConditionalReader",
           "JoinedDataReader", "JoinedAggregateDataReader",
           "TemporalJoinReader", "TimeBasedFilter",
           "FilteredReader", "DataReaders", "CutOffTime", "stream_score"]


@dataclass
class CutOffTime:
    """Event-time cutoff for aggregation (readers ``CutOffTime``)."""

    timestamp_ms: Optional[int] = None

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime(None)

    @staticmethod
    def at(timestamp_ms: int) -> "CutOffTime":
        """Fixed cutoff (CutOffTime.asOf analog)."""
        return CutOffTime(int(timestamp_ms))


class DataReader:
    """Base reader: produces record dicts; generates raw feature columns."""

    def __init__(self, key_fn: Optional[Callable[[Dict], str]] = None):
        self.key_fn = key_fn or (lambda r: str(r.get("id", "")))

    def read_records(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def generate_store(self, raw_features: Sequence[Feature]) -> ColumnStore:
        """Run every raw feature's extract_fn per record
        (DataReader.generateDataFrame, DataReader.scala:173-197)."""
        records = self.read_records()
        cols = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            cols[f.name] = gen.extract_column(records)
        return ColumnStore(cols, len(records))


class _InMemoryReader(DataReader):
    def __init__(self, records: Sequence[Mapping[str, Any]],
                 key_fn: Optional[Callable[[Dict], str]] = None):
        super().__init__(key_fn)
        self._records = [dict(r) for r in records]

    def read_records(self) -> List[Dict[str, Any]]:
        return self._records


class CSVReader(DataReader):
    """CSV with an explicit schema: column names in order
    (the avro-schema ``CSVReader`` analog)."""

    def __init__(self, path: str, schema: Sequence[str],
                 key_fn: Optional[Callable[[Dict], str]] = None,
                 delimiter: str = ","):
        super().__init__(key_fn)
        self.path = path
        self.schema = list(schema)
        self.delimiter = delimiter

    def read_records(self) -> List[Dict[str, Any]]:
        from .. import resilience
        resilience.inject("csv.decode", path=self.path)
        out = []
        with open(self.path, newline="") as fh:
            for row in _csv.reader(fh, delimiter=self.delimiter):
                rec = {name: (v if v != "" else None)
                       for name, v in zip(self.schema, row)}
                out.append(rec)
        return out


class CSVAutoReader(CSVReader):
    """Header-inferring CSV reader (CSVAutoReaders.scala:142)."""

    def __init__(self, path: str,
                 key_fn: Optional[Callable[[Dict], str]] = None,
                 delimiter: str = ","):
        with open(path, newline="") as fh:
            header = next(_csv.reader(fh, delimiter=delimiter))
        super().__init__(path, header, key_fn, delimiter)
        self._skip_header = True

    def read_records(self) -> List[Dict[str, Any]]:
        return super().read_records()[1:]


class AggregateReader(DataReader):
    """Group-by-key + monoid aggregation with cutoff-time leak prevention
    (AggregatedReader, DataReader.scala:206-230).

    Cutoff boundary (pinned — docs/readers.md): predictors fold events
    with ``ts < cutoff`` (within ``[cutoff - window, cutoff)`` under a
    declared window), responses fold events with ``ts > cutoff`` —
    STRICTLY after, so an event exactly AT the cutoff (a conditional
    reader's triggering event) lands in neither fold.

    When the source yields a columnar batch (``avro.ColumnarRecords``,
    ``temporal.Table``, a columnar join) and every extractor is
    column-keyed, ``generate_store`` routes to the vectorized temporal
    engine (``temporal.route_aggregate``) — bit-identical output, no
    per-record Python dispatch; any columnar failure degrades back to
    the row-wise fold below.
    """

    #: Workflow.train hands raw-store generation to the reader (the
    #: cutoff discipline lives HERE, not in the workflow)
    is_aggregating = True

    def __init__(self, base: DataReader,
                 timestamp_fn: Callable[[Dict], int],
                 cutoff: CutOffTime = CutOffTime.no_cutoff(),
                 key_fn: Optional[Callable[[Dict], str]] = None):
        super().__init__(key_fn or base.key_fn)
        self.base = base
        self.timestamp_fn = timestamp_fn
        self.cutoff = cutoff

    def read_records(self) -> List[Dict[str, Any]]:
        return self.base.read_records()

    def _cutoff_for_key(self, records: List[Dict[str, Any]]) -> Optional[int]:
        return self.cutoff.timestamp_ms

    def generate_store(self, raw_features: Sequence[Feature]) -> ColumnStore:
        import time as _time

        from .. import temporal
        records = self.read_records()
        store = temporal.route_aggregate(self, records, raw_features)
        if store is not None:
            return store
        # timed so the planner's cost db learns the rowwise half of the
        # columnar-vs-rowwise tier decision — but ONLY when the route
        # just declined a REAL columnar option (row-list sources,
        # forced-off mode and structurally unroutable extractors never
        # had one; their timings would poison the pooled per-tier
        # s/krow the auto-route hint compares)
        contested = temporal.last_route_contested()
        t0 = _time.perf_counter()
        store = self._rowwise_store(records, raw_features)
        temporal.tally_rowwise(
            len(records),
            seconds=(_time.perf_counter() - t0) if contested else None)
        return store

    def _rowwise_store(self, records, raw_features: Sequence[Feature]
                       ) -> ColumnStore:
        """The reference row-wise fold — also the parity oracle the
        columnar engine is asserted bit-identical against."""
        from collections import defaultdict
        groups: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
        for rec in records:
            groups[self.key_fn(rec)].append(rec)
        keys = sorted(groups)
        cols: Dict[str, Any] = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            # no explicit aggregator → the feature type's default monoid
            # (MonoidAggregatorDefaults.aggregatorOf, applied by the
            # reference's FeatureAggregator the same way)
            agg = gen.aggregator
            if agg is None:
                from ..utils.aggregators import aggregator_of
                try:
                    agg = aggregator_of(f.ftype)
                except ValueError:
                    agg = None
            values = []
            for k in keys:
                recs = groups[k]
                cutoff = self._cutoff_for_key(recs)
                window = gen.window_ms
                vals = []
                for r in recs:
                    ts = self.timestamp_fn(r)
                    if cutoff is not None:
                        if f.is_response:
                            # responses STRICTLY after cutoff: the
                            # cutoff event itself (ts == cutoff, e.g. a
                            # conditional reader's triggering purchase)
                            # must not fold into the outcome
                            if ts <= cutoff:
                                continue
                        else:
                            # predictors BEFORE cutoff, within window
                            if ts >= cutoff:
                                continue
                            if window is not None and ts < cutoff - window:
                                continue
                    v = gen.extract_fn(r)
                    if v is not None:
                        vals.append(v)
                if agg is None:
                    values.append(vals[-1] if vals else None)
                else:
                    values.append(agg.fold(vals))
            cols[f.name] = column_from_values(f.ftype, values)
        return ColumnStore(cols, len(keys))


class ConditionalReader(AggregateReader):
    """Cutoff per key = timestamp of first record matching the predicate
    (conditional readers, DataReaders.scala:196-278)."""

    def __init__(self, base: DataReader,
                 timestamp_fn: Callable[[Dict], int],
                 condition_fn: Callable[[Dict], bool],
                 drop_if_no_condition: bool = True,
                 key_fn: Optional[Callable[[Dict], str]] = None):
        super().__init__(base, timestamp_fn, CutOffTime.no_cutoff(), key_fn)
        self.condition_fn = condition_fn
        self.drop_if_no_condition = drop_if_no_condition

    def _cutoff_for_key(self, records: List[Dict[str, Any]]) -> Optional[int]:
        times = [self.timestamp_fn(r) for r in records if self.condition_fn(r)]
        return min(times) if times else None

    def _rowwise_store(self, records, raw_features: Sequence[Feature]
                       ) -> ColumnStore:
        if self.drop_if_no_condition:
            from collections import defaultdict
            groups: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
            for rec in records:
                groups[self.key_fn(rec)].append(rec)
            keep = {k for k, recs in groups.items()
                    if any(self.condition_fn(r) for r in recs)}
            filtered = [r for k, recs in groups.items() if k in keep
                        for r in recs]
            tmp = ConditionalReader(_InMemoryReader(filtered, self.key_fn),
                                    self.timestamp_fn,
                                    self.condition_fn,
                                    drop_if_no_condition=False,
                                    key_fn=self.key_fn)
            return tmp._rowwise_store(filtered, raw_features)
        return super()._rowwise_store(records, raw_features)


class ParquetReader(DataReader):
    """Parquet ingestion via the host Arrow/pandas stack
    (ParquetProductReader analog). NaN floats from nullable columns map to
    None."""

    def __init__(self, path: str,
                 key_fn: Optional[Callable[[Dict], str]] = None):
        super().__init__(key_fn)
        self.path = path

    def read_records(self) -> List[Dict[str, Any]]:
        import pandas as pd
        df = pd.read_parquet(self.path)
        records = df.to_dict(orient="records")
        for rec in records:
            for k, v in rec.items():
                if v is None or (isinstance(v, float) and v != v):
                    rec[k] = None
        return records


class AvroReader(DataReader):
    """Avro container-file ingestion (AvroReader; pure-Python decoder in
    readers/avro.py)."""

    def __init__(self, path: str,
                 key_fn: Optional[Callable[[Dict], str]] = None):
        super().__init__(key_fn)
        self.path = path

    def read_records(self) -> List[Dict[str, Any]]:
        from .avro import read_avro_records
        return read_avro_records(self.path)


@dataclass
class TimeBasedFilter:
    """Keep records whose event time falls inside [cutoff - duration,
    cutoff) (JoinedDataReader.scala TimeBasedFilter)."""

    timestamp_fn: Callable[[Dict], int]
    cutoff_ms: int
    duration_ms: Optional[int] = None

    def keep(self, record: Dict[str, Any]) -> bool:
        ts = self.timestamp_fn(record)
        if ts >= self.cutoff_ms:
            return False
        if self.duration_ms is not None and \
                ts < self.cutoff_ms - self.duration_ms:
            return False
        return True


class FilteredReader(DataReader):
    """Reader wrapper applying a TimeBasedFilter / predicate pre-read."""

    def __init__(self, base: DataReader, keep_fn: Callable[[Dict], bool]):
        super().__init__(base.key_fn)
        self.base = base
        self.keep_fn = keep_fn

    def read_records(self) -> List[Dict[str, Any]]:
        return [r for r in self.base.read_records() if self.keep_fn(r)]


class JoinedDataReader(DataReader):
    """Left-outer/inner join of two readers on their keys
    (JoinedDataReader.scala:54-418)."""

    def __init__(self, left: DataReader, right: DataReader,
                 join_type: str = "left_outer"):
        super().__init__(left.key_fn)
        self.left = left
        self.right = right
        self.join_type = join_type

    def read_records(self) -> List[Dict[str, Any]]:
        right_by_key: Dict[str, Dict[str, Any]] = {}
        for r in self.right.read_records():
            right_by_key.setdefault(self.right.key_fn(r), {}).update(r)
        out = []
        for l in self.left.read_records():
            k = self.left.key_fn(l)
            r = right_by_key.get(k)
            if r is None:
                if self.join_type == "inner":
                    continue
                out.append(dict(l))
            else:
                merged = dict(r)
                merged.update(l)
                out.append(merged)
        return out


class TemporalJoinReader(DataReader):
    """Streaming hash join — the memory-bounded, columnar-capable
    ``JoinedDataReader`` (temporal.py's native tier):

    * the build (right) side is consistent-hash partitioned into
      BOUNDED per-partition hash tables (``partitions`` ×
      ``table_max_rows`` unique keys; run defaults from
      ``customParams.joinPartitions`` / ``joinTableMaxRows``) — a new
      key arriving at a full partition spills its row to the
      dead-letter quarantine instead of growing the heap;
    * the probe (left) side streams through in order, so output order
      and merge semantics (right fields, left overwrites on shared
      names; last right record per key wins) are bit-identical to
      :class:`JoinedDataReader`;
    * when BOTH sides yield columnar batches and the key column is
      statically known, the whole join vectorizes (one stable argsort +
      one searchsorted probe) and the result stays columnar
      (``temporal.Table``) — which is what lets a downstream
      ``AggregateReader`` keep the joined-then-aggregate composition
      columnar end-to-end.

    The build step runs behind the ``temporal.join`` fault site +
    READER_RETRY, so a transient failure retries instead of killing the
    read.
    """

    is_joining = True

    def __init__(self, left: DataReader, right: DataReader,
                 join_type: str = "left_outer",
                 key_field: Optional[str] = None,
                 partitions: Optional[int] = None,
                 table_max_rows: Optional[int] = None):
        if join_type not in ("left_outer", "inner"):
            raise ValueError(
                f"join_type must be 'left_outer' or 'inner', got "
                f"{join_type!r}")
        from .. import temporal
        key_fn = temporal.field(key_field) if key_field else left.key_fn
        super().__init__(key_fn)
        self.left = left
        self.right = right
        self.join_type = join_type
        self.key_field = key_field
        self.partitions = partitions
        self.table_max_rows = table_max_rows

    def _left_key(self):
        from .. import temporal
        if self.key_field:
            return temporal.field(self.key_field)
        return self.left.key_fn

    def _right_key(self):
        from .. import temporal
        if self.key_field:
            return temporal.field(self.key_field)
        return self.right.key_fn

    def read_records(self):
        from .. import resilience, telemetry, temporal
        left = self.left.read_records()
        right = self.right.read_records()
        lkey = self.key_field or temporal.column_key_of(self._left_key())
        rkey = self.key_field or temporal.column_key_of(self._right_key())
        # decide the build shape BEFORE building anything: the
        # vectorized probe needs BOTH sides columnar and both key
        # columns statically known — otherwise the partitioned bounded
        # dict tables are the only shape that will be probed, so the
        # columnar build would be pure wasted work
        columnar = (temporal._is_table(left) and temporal._is_table(right)
                    and lkey is not None and rkey is not None
                    and temporal.columnar_mode() is not False)

        def build():
            resilience.inject("temporal.join",
                              join_type=self.join_type,
                              rows=len(right))
            if columnar:
                return temporal.build_join_table(
                    right, rkey, partitions=self.partitions,
                    table_max_rows=self.table_max_rows)
            return temporal._DictBuildTable(
                right, self._right_key(),
                temporal.join_partitions(self.partitions),
                temporal.join_table_max_rows(self.table_max_rows))

        # transient build failures (network-mount blips on the already
        # decoded tables are rare, but the fault site models them)
        # retry; the build is pure compute over in-memory records, so
        # re-running it is safe
        table = resilience.READER_RETRY.call("temporal.join", build)
        temporal._tally("joins")
        telemetry.counter("temporal.joins").inc()
        with telemetry.span("temporal:join", rows=len(left)):
            if isinstance(table, temporal._ColumnarBuildTable):
                return table.probe(left, lkey, self.join_type)
            return table.probe(left, self._left_key(), self.join_type)


class JoinedAggregateDataReader(AggregateReader):
    """Join first, then time-window aggregate the joined records —
    ``JoinedAggregateDataReader`` (JoinedDataReader.scala:119-418): the
    right side's events are windowed against the cutoff after the join, as
    in the reference's dataprep examples
    (docs/examples/Conditional-Aggregation.md). The join now rides
    :class:`TemporalJoinReader` (bounded partitioned build tables,
    vectorized when both sides are columnar), so the joined-then-
    aggregate composition is columnar end-to-end — bit-identical to the
    pre-temporal row-wise composition, asserted in tests."""

    def __init__(self, left: DataReader, right: DataReader,
                 timestamp_fn: Callable[[Dict], int],
                 cutoff: CutOffTime = CutOffTime.no_cutoff(),
                 join_type: str = "left_outer",
                 time_filter: Optional[TimeBasedFilter] = None,
                 key_field: Optional[str] = None,
                 partitions: Optional[int] = None,
                 table_max_rows: Optional[int] = None):
        joined: DataReader = TemporalJoinReader(
            left, right, join_type, key_field=key_field,
            partitions=partitions, table_max_rows=table_max_rows)
        if time_filter is not None:
            joined = FilteredReader(joined, time_filter.keep)
        super().__init__(joined, timestamp_fn, cutoff,
                         joined.key_fn if key_field else left.key_fn)


def stream_score(model, batches: Iterable[Sequence[Mapping[str, Any]]],
                 keep_intermediate: bool = False, overlap: Any = "auto",
                 on_error: Optional[str] = None,
                 workers: Optional[int] = None,
                 prefetch: Optional[int] = None):
    """Incremental scoring over record batches (StreamingScore run type /
    StreamingReaders.scala analog): yields one scored ColumnStore per
    batch, reusing the fitted DAG — jitted transforms recompile only when
    a batch size changes shape buckets.

    ``overlap`` engages the compiled scoring engine's staged input
    pipeline (scoring.stream_score_overlapped, per pipeline.py): host
    feature extraction runs on a parallel worker pool with autotuned
    prefetch while device compute and the next batch's upload overlap.
    ``"auto"`` (default) turns it on when the engine is available, the
    link clears the bandwidth gate and the first batch is big enough to
    pay for compilation; ``True``/``False`` force/forbid it.
    ``workers`` / ``prefetch`` bound the pipeline's decode/prep pool and
    prefetch-depth ceiling (None = the pipeline module defaults; the
    runner's ``customParams.pipelineWorkers`` / ``pipelineDepth``).

    ``on_error`` governs poison batches (tf.data's graceful-degradation
    contract): ``"quarantine"`` routes a batch whose scoring raises to
    the dead-letter sink (JSONL + reason + the records themselves,
    ``resilience.quarantined_batches`` counter) and continues the
    stream; ``"raise"`` propagates, killing the stream (the
    pre-resilience behavior). The default (``None``) is sink-aware:
    quarantine when a dead-letter sink is installed
    (``resilience.set_quarantine`` / the runner's
    ``quarantineLocation``), raise when none is — a dropped batch whose
    records land nowhere would be silent data loss, so without a sink
    the failure stays loud. The FIRST batch always raises either way —
    a head-of-stream failure is a configuration error (wrong features,
    missing model state), not data poison, and quarantining every batch
    of a misconfigured stream would be silence at scale."""
    import itertools

    from .. import resilience, telemetry

    on_error = resilience.resolve_on_error(on_error)
    it = iter(batches)
    first = next(it, None)
    if first is None:
        return
    chained = itertools.chain([first], it)
    from .. import pipeline as _pipeline
    use_overlap = False
    if overlap is not False and _pipeline.PIPELINE_ENABLED \
            and hasattr(model, "scoring_engine"):
        # TMOG_PIPELINE=0 is the emergency lever: it wins over an
        # explicit overlap=True and drops the stream to the
        # single-thread per-batch path
        from ..scoring import SCORING_MIN_ROWS
        eng = model.scoring_engine()
        ok = eng is not None and eng.enabled()
        use_overlap = ok and (overlap is True
                              or len(first) >= SCORING_MIN_ROWS)
    # routing evidence: which streaming mode actually served the batches
    telemetry.counter("stream.overlapped_streams" if use_overlap  # lint: metric-name — one of two literal names
                      else "stream.plain_streams").inc()
    if use_overlap:
        from ..scoring import stream_score_overlapped
        yield from stream_score_overlapped(
            model, chained, keep_intermediate=keep_intermediate,
            on_error=on_error, workers=workers, prefetch=prefetch)
        return
    for i, batch in enumerate(chained):
        try:
            resilience.inject("stream.score_batch", index=i,
                              rows=len(batch))
            with telemetry.span("stream:score_batch", rows=len(batch)):
                out = model.score(_pipeline.concrete_batch(batch),
                                  keep_intermediate=keep_intermediate)
        except Exception as e:  # lint: broad-except — poison batch quarantines, never kills the stream
            # the records ride in the dead letter: unlike a quarantined
            # FILE (still on disk), a consumed stream batch exists
            # nowhere else — without them the sink is only a tombstone
            resilience.quarantine_batch_or_raise(on_error, i, e, batch)
            continue
        yield out


class DataReaders:
    """Factory (DataReaders.scala:43)."""

    class simple:
        @staticmethod
        def csv(path: str, schema: Sequence[str], key_fn=None) -> CSVReader:
            return CSVReader(path, schema, key_fn)

        @staticmethod
        def csv_auto(path: str, key_fn=None) -> CSVAutoReader:
            return CSVAutoReader(path, key_fn)

        @staticmethod
        def records(records: Sequence[Mapping[str, Any]], key_fn=None
                    ) -> DataReader:
            return _InMemoryReader(records, key_fn)

        @staticmethod
        def parquet(path: str, key_fn=None) -> "ParquetReader":
            return ParquetReader(path, key_fn)

        @staticmethod
        def avro(path: str, key_fn=None) -> "AvroReader":
            return AvroReader(path, key_fn)

    class aggregate:
        @staticmethod
        def records(records, timestamp_fn, cutoff=CutOffTime.no_cutoff(),
                    key_fn=None) -> AggregateReader:
            return AggregateReader(_InMemoryReader(records, key_fn),
                                   timestamp_fn, cutoff, key_fn)

        @staticmethod
        def csv(path, schema, timestamp_fn, cutoff=CutOffTime.no_cutoff(),
                key_fn=None) -> AggregateReader:
            return AggregateReader(CSVReader(path, schema, key_fn),
                                   timestamp_fn, cutoff, key_fn)

    class conditional:
        @staticmethod
        def records(records, timestamp_fn, condition_fn, key_fn=None,
                    drop_if_no_condition: bool = True) -> ConditionalReader:
            return ConditionalReader(_InMemoryReader(records, key_fn),
                                     timestamp_fn, condition_fn,
                                     drop_if_no_condition, key_fn)
