"""Avro Object Container File reader — pure Python, dependency-free.

Parity: ``AvroReader`` / ``AvroInOut`` (``readers/.../DataReaders.scala``,
``utils/.../io/avro/AvroInOut.scala``). The reference reads Avro through
Spark; here a compact decoder of the Avro 1.x container format (spec:
magic ``Obj\\x01``, metadata map carrying ``avro.schema``/``avro.codec``,
sync-marker-delimited blocks of binary-encoded records; null and deflate
codecs) feeds the host record path. Supports the schema subset AutoML
data uses: primitives, records, enums, arrays, maps, fixed and unions.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["read_avro_records", "AvroDecodeError", "AvroWriter",
           "write_avro_records", "infer_avro_schema"]

_MAGIC = b"Obj\x01"


class AvroDecodeError(ValueError):
    pass


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise AvroDecodeError("Truncated avro data")
        self.pos += n
        return b

    # -- primitives (Avro binary encoding) --------------------------------
    def zigzag_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def bytes_(self) -> bytes:
        return self.read(self.zigzag_long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")


def _decode(cur: _Cursor, schema: Any, named: Dict[str, Any]) -> Any:
    if isinstance(schema, str):
        s = schema
        if s == "null":
            return None
        if s == "boolean":
            return cur.read(1) != b"\x00"
        if s in ("int", "long"):
            return cur.zigzag_long()
        if s == "float":
            return cur.float_()
        if s == "double":
            return cur.double()
        if s == "bytes":
            return cur.bytes_()
        if s == "string":
            return cur.string()
        if s in named:
            return _decode(cur, named[s], named)
        raise AvroDecodeError(f"Unknown schema reference {s!r}")
    if isinstance(schema, list):                  # union: branch index
        idx = cur.zigzag_long()
        if not (0 <= idx < len(schema)):
            raise AvroDecodeError(f"Bad union branch {idx}")
        return _decode(cur, schema[idx], named)
    t = schema["type"]
    if t == "record":
        _register(schema, named)
        return {f["name"]: _decode(cur, f["type"], named)
                for f in schema["fields"]}
    if t == "enum":
        _register(schema, named)
        return schema["symbols"][cur.zigzag_long()]
    if t == "fixed":
        _register(schema, named)
        return cur.read(schema["size"])
    if t == "array":
        out: List[Any] = []
        while True:
            n = cur.zigzag_long()
            if n == 0:
                break
            if n < 0:             # block with byte size prefix
                n = -n
                cur.zigzag_long()
            for _ in range(n):
                out.append(_decode(cur, schema["items"], named))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n = cur.zigzag_long()
            if n == 0:
                break
            if n < 0:
                n = -n
                cur.zigzag_long()
            for _ in range(n):
                # key must be read BEFORE the value — and Python evaluates
                # the assignment's RHS first, so m[cur.string()] = decode()
                # would consume them in the wrong order
                k = cur.string()
                m[k] = _decode(cur, schema["values"], named)
        return m
    return _decode(cur, t, named)     # e.g. {"type": "string"}


def _register(schema: Dict[str, Any], named: Dict[str, Any]) -> None:
    name = schema.get("name")
    if name:
        ns = schema.get("namespace")
        named[name] = schema
        if ns:
            named[f"{ns}.{name}"] = schema


def read_avro_records(path: str) -> List[Dict[str, Any]]:
    """Decode every record of an Avro container file into dicts.

    Decode failures always surface as :class:`AvroDecodeError` naming the
    file — a truncated varint (``IndexError``), a short struct read or a
    bad deflate stream are all the same poison-file condition to the
    caller (the streaming reader's quarantine routes on it)."""
    from .. import resilience
    resilience.inject("avro.decode", path=path)
    with open(path, "rb") as fh:
        data = fh.read()
    if data[:4] != _MAGIC:
        raise AvroDecodeError(f"{path} is not an Avro container file")
    try:
        return _decode_container(data)
    except AvroDecodeError as e:
        raise AvroDecodeError(f"{path}: {e}") from e
    except (IndexError, struct.error, KeyError, zlib.error,
            UnicodeDecodeError, json.JSONDecodeError) as e:
        raise AvroDecodeError(
            f"{path}: truncated or corrupt avro container "
            f"({type(e).__name__}: {e})") from e


def _decode_container(data: bytes) -> List[Dict[str, Any]]:
    cur = _Cursor(data, 4)

    meta: Dict[str, bytes] = {}
    while True:
        n = cur.zigzag_long()
        if n == 0:
            break
        if n < 0:
            n = -n
            cur.zigzag_long()
        for _ in range(n):
            k = cur.string()
            meta[k] = cur.bytes_()
    schema = json.loads(meta[b"avro.schema".decode()]
                        if isinstance(meta.get("avro.schema"), str)
                        else meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = cur.read(16)

    named: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    while cur.pos < len(data):
        count = cur.zigzag_long()
        size = cur.zigzag_long()
        block = cur.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise AvroDecodeError(f"Unsupported avro codec {codec!r}")
        bcur = _Cursor(block)
        for _ in range(count):
            records.append(_decode(bcur, schema, named))
        if cur.read(16) != sync:
            raise AvroDecodeError("Sync marker mismatch")
    return records


# ---------------------------------------------------------------------------
# Encoder — score output (OpWorkflowModel.saveScores / RichDataset.saveAvro,
# core/.../OpWorkflowModel.scala:376-421). Counterpart of the decoder above:
# same container format, null/deflate codecs, same schema subset.
# ---------------------------------------------------------------------------

def _zigzag_bytes(v: int) -> bytes:
    u = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode(buf: bytearray, schema: Any, value: Any,
            named: Dict[str, Any]) -> None:
    if isinstance(schema, str):
        s = schema
        if s == "null":
            return
        if s == "boolean":
            buf += b"\x01" if value else b"\x00"
        elif s in ("int", "long"):
            buf += _zigzag_bytes(int(value))
        elif s == "float":
            buf += struct.pack("<f", float(value))
        elif s == "double":
            buf += struct.pack("<d", float(value))
        elif s == "bytes":
            b = bytes(value)
            buf += _zigzag_bytes(len(b)) + b
        elif s == "string":
            b = str(value).encode("utf-8")
            buf += _zigzag_bytes(len(b)) + b
        elif s in named:
            _encode(buf, named[s], value, named)
        else:
            raise AvroDecodeError(f"Unknown schema reference {s!r}")
        return
    if isinstance(schema, list):                  # union: pick the branch
        # two passes: STRICT typing first (a long in ["null","string",
        # "long"] must encode as long, not be swallowed by an earlier
        # string branch), then the lenient pass where "string" acts as
        # the stringify-anything escape hatch for values the inferred
        # schema didn't anticipate
        for strict in (True, False):
            for idx, branch in enumerate(schema):
                if _union_matches(branch, value, strict=strict):
                    buf += _zigzag_bytes(idx)
                    _encode(buf, branch, value, named)
                    return
        raise AvroDecodeError(
            f"No union branch of {schema} matches {type(value).__name__}")
    t = schema["type"]
    if t == "record":
        _register(schema, named)
        for f in schema["fields"]:
            _encode(buf, f["type"], (value or {}).get(f["name"]), named)
    elif t == "enum":
        _register(schema, named)
        buf += _zigzag_bytes(schema["symbols"].index(value))
    elif t == "fixed":
        _register(schema, named)
        buf += bytes(value)
    elif t == "array":
        items = list(value or ())
        if items:
            buf += _zigzag_bytes(len(items))
            for it in items:
                _encode(buf, schema["items"], it, named)
        buf += _zigzag_bytes(0)
    elif t == "map":
        entries = dict(value or {})
        if entries:
            buf += _zigzag_bytes(len(entries))
            for k, v in entries.items():
                kb = str(k).encode("utf-8")
                buf += _zigzag_bytes(len(kb)) + kb
                _encode(buf, schema["values"], v, named)
        buf += _zigzag_bytes(0)
    else:
        _encode(buf, t, value, named)


def _union_matches(branch: Any, value: Any, strict: bool = True) -> bool:
    if branch == "null":
        return value is None
    if value is None:
        return False
    if branch == "boolean":
        return isinstance(value, bool)
    if branch in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if branch in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if branch == "string":
        if strict:
            return isinstance(value, str)
        # lenient pass: the stringify-anything escape hatch — better a
        # str()'d value than a torn container when a post-schema-lock
        # streaming batch surprises the inferred union
        return not isinstance(value, (bytes, bytearray))
    if branch == "bytes":
        return isinstance(value, (bytes, bytearray))
    if isinstance(branch, dict):
        t = branch.get("type")
        if t == "array":
            return isinstance(value, (list, tuple))
        if t in ("map", "record"):
            return isinstance(value, dict)
    return True


def _infer_value_schema(values: List[Any]) -> Any:
    """ALWAYS-nullable union schema for one field's observed values.

    Unconditional nullability (and a long+double pair for numerics) keeps
    a schema inferred from the FIRST streaming batch valid for later
    batches whose null pattern or int/float flavor differs — the sink
    locks the container schema at the first block. Collection element
    schemas are unions too, so None elements inside lists/maps encode.
    All-None fields get a catch-all branch set."""
    present = [v for v in values if v is not None]
    if not present:
        return ["null", "long", "double", "string"]
    # every union keeps a trailing "string" branch: the schema locks at
    # the first streaming block, and the lenient stringify-anything escape
    # hatch (_union_matches strict=False) can only fire if the branch
    # exists — without it a later batch whose value type surprises the
    # union aborts the scoring stream (ADVICE r3)
    if all(isinstance(p, bool) for p in present):
        return ["null", "boolean", "string"]
    if all(isinstance(p, (int, float)) and not isinstance(p, bool)
           for p in present):
        return ["null", "long", "double", "string"]
    if all(isinstance(p, (bytes, bytearray)) for p in present):
        return ["null", "bytes", "string"]
    if all(isinstance(p, dict) for p in present):
        inner = _infer_value_schema(
            [x for p in present for x in p.values()])
        return ["null", {"type": "map", "values": inner}, "string"]
    if all(isinstance(p, (list, tuple, set, frozenset)) for p in present):
        inner = _infer_value_schema([x for p in present for x in p])
        return ["null", {"type": "array", "items": inner}, "string"]
    return ["null", "string"]


def infer_avro_schema(records: List[Dict[str, Any]],
                      name: str = "ScoreRecord") -> Dict[str, Any]:
    """Record schema from score rows (field order = first-seen order)."""
    fields: List[str] = []
    for r in records:
        for k in r:
            if k not in fields:
                fields.append(k)
    return {"type": "record", "name": name,
            "fields": [{"name": f,
                        "type": _infer_value_schema(
                            [r.get(f) for r in records])}
                       for f in fields]}


class AvroWriter:
    """Streaming Avro container writer (null/deflate codecs).

    Header (magic + metadata + sync marker) goes out on construction;
    each :meth:`append` emits one sync-delimited block, so the sink can
    stream scoring batches without holding the dataset (the
    StreamingScore regime)."""

    def __init__(self, path: str, schema: Dict[str, Any],
                 codec: str = "deflate"):
        import os as _os
        import secrets

        if codec not in ("null", "deflate"):
            raise AvroDecodeError(f"Unsupported avro codec {codec!r}")
        self.schema = schema
        self.codec = codec
        self._named: Dict[str, Any] = {}
        self._sync = secrets.token_bytes(16)
        d = _os.path.dirname(path)
        if d:
            _os.makedirs(d, exist_ok=True)
        self._fh = open(path, "wb")
        header = bytearray(_MAGIC)
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        header += _zigzag_bytes(len(meta))
        for k, v in meta.items():
            kb = k.encode()
            header += _zigzag_bytes(len(kb)) + kb
            header += _zigzag_bytes(len(v)) + v
        header += _zigzag_bytes(0)
        header += self._sync
        self._fh.write(bytes(header))

    def append(self, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        buf = bytearray()
        for r in records:
            _encode(buf, self.schema, r, self._named)
        block = bytes(buf)
        if self.codec == "deflate":
            co = zlib.compressobj(wbits=-15)
            block = co.compress(block) + co.flush()
        out = bytearray()
        out += _zigzag_bytes(len(records))
        out += _zigzag_bytes(len(block))
        out += block
        out += self._sync
        self._fh.write(bytes(out))

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def write_avro_records(path: str, records: List[Dict[str, Any]],
                       schema: Optional[Dict[str, Any]] = None,
                       codec: str = "deflate") -> None:
    """One-shot counterpart of :func:`read_avro_records`."""
    w = AvroWriter(path, schema or infer_avro_schema(records), codec)
    try:
        w.append(records)
    finally:
        w.close()
