"""Avro Object Container File reader — pure Python + numpy, no deps.

Parity: ``AvroReader`` / ``AvroInOut`` (``readers/.../DataReaders.scala``,
``utils/.../io/avro/AvroInOut.scala``). The reference reads Avro through
Spark; here a compact decoder of the Avro 1.x container format (spec:
magic ``Obj\\x01``, metadata map carrying ``avro.schema``/``avro.codec``,
sync-marker-delimited blocks of binary-encoded records; null and deflate
codecs) feeds the host record path. Supports the schema subset AutoML
data uses: primitives, records, enums, arrays, maps, fixed and unions.

Two decode paths:

* :func:`read_avro_records` — the general per-record Python decoder
  (any supported schema), returning ``List[Dict]``.
* :func:`read_avro_table` — the input pipeline's VECTORIZED decode
  (pipeline.py): when a block's records verify as fixed-stride (every
  field a fixed-width primitive — double/float/boolean — possibly
  behind a constant union branch), the whole block decodes as ONE
  ``np.frombuffer`` view + per-field strided slices instead of
  count × fields Python frames. That turns record decode from the
  GIL-bound bottleneck it measured as (~90 % of streaming-scoring
  wall: BENCH_r05's ``data_prep_s``) into numpy work that releases the
  GIL — which is what lets the pipeline's decode workers actually run
  in parallel. Results come back as :class:`ColumnarRecords`, a
  sequence-of-dicts facade over the column arrays, BIT-IDENTICAL to
  the Python decoder's output (verified by branch-byte checks before
  trusting the layout; any surprise falls back to
  :func:`read_avro_records`).
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["read_avro_records", "read_avro_table", "ColumnarRecords",
           "AvroDecodeError", "AvroWriter",
           "write_avro_records", "infer_avro_schema"]

_MAGIC = b"Obj\x01"


class AvroDecodeError(ValueError):
    pass


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise AvroDecodeError("Truncated avro data")
        self.pos += n
        return b

    # -- primitives (Avro binary encoding) --------------------------------
    def zigzag_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def bytes_(self) -> bytes:
        return self.read(self.zigzag_long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")


def _decode(cur: _Cursor, schema: Any, named: Dict[str, Any]) -> Any:
    if isinstance(schema, str):
        s = schema
        if s == "null":
            return None
        if s == "boolean":
            return cur.read(1) != b"\x00"
        if s in ("int", "long"):
            return cur.zigzag_long()
        if s == "float":
            return cur.float_()
        if s == "double":
            return cur.double()
        if s == "bytes":
            return cur.bytes_()
        if s == "string":
            return cur.string()
        if s in named:
            return _decode(cur, named[s], named)
        raise AvroDecodeError(f"Unknown schema reference {s!r}")
    if isinstance(schema, list):                  # union: branch index
        idx = cur.zigzag_long()
        if not (0 <= idx < len(schema)):
            raise AvroDecodeError(f"Bad union branch {idx}")
        return _decode(cur, schema[idx], named)
    t = schema["type"]
    if t == "record":
        _register(schema, named)
        return {f["name"]: _decode(cur, f["type"], named)
                for f in schema["fields"]}
    if t == "enum":
        _register(schema, named)
        return schema["symbols"][cur.zigzag_long()]
    if t == "fixed":
        _register(schema, named)
        return cur.read(schema["size"])
    if t == "array":
        out: List[Any] = []
        while True:
            n = cur.zigzag_long()
            if n == 0:
                break
            if n < 0:             # block with byte size prefix
                n = -n
                cur.zigzag_long()
            for _ in range(n):
                out.append(_decode(cur, schema["items"], named))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n = cur.zigzag_long()
            if n == 0:
                break
            if n < 0:
                n = -n
                cur.zigzag_long()
            for _ in range(n):
                # key must be read BEFORE the value — and Python evaluates
                # the assignment's RHS first, so m[cur.string()] = decode()
                # would consume them in the wrong order
                k = cur.string()
                m[k] = _decode(cur, schema["values"], named)
        return m
    return _decode(cur, t, named)     # e.g. {"type": "string"}


def _register(schema: Dict[str, Any], named: Dict[str, Any]) -> None:
    name = schema.get("name")
    if name:
        ns = schema.get("namespace")
        named[name] = schema
        if ns:
            named[f"{ns}.{name}"] = schema


def _read_container(path: str, decode) -> Any:
    """The ONE I/O + error ladder both decoders share: the
    ``avro.decode`` fault site, the magic check, and the poison-file
    translation — a truncated varint (``IndexError``), a short struct
    read or a bad deflate stream are all the same
    :class:`AvroDecodeError` condition to the caller (the streaming
    reader's quarantine routes on it). Keeping it in one place keeps
    the two decode paths' error contracts from drifting apart."""
    from .. import resilience
    resilience.inject("avro.decode", path=path)
    with open(path, "rb") as fh:
        data = fh.read()
    if data[:4] != _MAGIC:
        raise AvroDecodeError(f"{path} is not an Avro container file")
    try:
        return decode(data)
    except AvroDecodeError as e:
        raise AvroDecodeError(f"{path}: {e}") from e
    except (IndexError, struct.error, KeyError, zlib.error,
            UnicodeDecodeError, json.JSONDecodeError) as e:
        raise AvroDecodeError(
            f"{path}: truncated or corrupt avro container "
            f"({type(e).__name__}: {e})") from e


def read_avro_records(path: str) -> List[Dict[str, Any]]:
    """Decode every record of an Avro container file into dicts.

    Decode failures always surface as :class:`AvroDecodeError` naming
    the file (see :func:`_read_container`)."""
    return _read_container(path, _decode_container)


def _parse_header(cur: _Cursor) -> Tuple[Any, str, bytes]:
    """Container header at ``cur`` (past the magic): schema, codec,
    sync marker."""
    meta: Dict[str, bytes] = {}
    while True:
        n = cur.zigzag_long()
        if n == 0:
            break
        if n < 0:
            n = -n
            cur.zigzag_long()
        for _ in range(n):
            k = cur.string()
            meta[k] = cur.bytes_()
    schema = json.loads(meta[b"avro.schema".decode()]
                        if isinstance(meta.get("avro.schema"), str)
                        else meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = cur.read(16)
    return schema, codec, sync


def _iter_blocks(cur: _Cursor, codec: str,
                 sync: bytes) -> Iterator[Tuple[int, bytes]]:
    """Yield each block's (record count, decompressed bytes)."""
    data = cur.buf
    while cur.pos < len(data):
        count = cur.zigzag_long()
        size = cur.zigzag_long()
        block = cur.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise AvroDecodeError(f"Unsupported avro codec {codec!r}")
        if cur.read(16) != sync:
            raise AvroDecodeError("Sync marker mismatch")
        yield count, block


def _decode_container(data: bytes) -> List[Dict[str, Any]]:
    cur = _Cursor(data, 4)
    schema, codec, sync = _parse_header(cur)
    named: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    for count, block in _iter_blocks(cur, codec, sync):
        bcur = _Cursor(block)
        for _ in range(count):
            records.append(_decode(bcur, schema, named))
    return records


# ---------------------------------------------------------------------------
# Vectorized columnar decode — the input pipeline's decode stage
# ---------------------------------------------------------------------------

#: fixed-width primitive payloads the strided decode understands; every
#: other kind (varint ints/longs, length-prefixed strings/bytes,
#: containers) is variable-width and routes to the Python decoder
_FIXED_WIDTH = {"null": 0, "boolean": 1, "float": 4, "double": 8}


class ColumnarRecords:
    """Sequence-of-dicts facade over numpy column arrays.

    The vectorized decoder's output: downstream code that iterates
    records (quarantine payloads, host-path retries, generic
    ``extract_fn``\\ s) sees the SAME dicts the Python decoder builds —
    materialized lazily ONCE per batch and shared across every
    iterating consumer (the pre-pipeline ``list(data)`` shared one dict
    list across all features; a per-iteration rebuild would charge
    O(rows × fields) to EACH feature whose type has no bulk lane) —
    while columnar consumers (``FeatureGeneratorStage.extract_column``,
    ``workflow._generate_raw_store``) read ``columns`` directly and
    never materialize a dict at all. ``null_fields`` are fields whose
    every row took the union's null branch (dict access yields None;
    the column array holds NaN so the bulk ingest path masks them
    missing, same as the dict path)."""

    __slots__ = ("columns", "null_fields", "_names", "n_rows", "_dicts")

    def __init__(self, columns: Dict[str, Any],
                 null_fields: Tuple[str, ...] = ()):
        self.columns = dict(columns)
        self.null_fields = frozenset(null_fields)
        self._names = list(columns)
        self.n_rows = (int(next(iter(columns.values())).shape[0])
                       if columns else 0)
        self._dicts: Optional[List[Dict[str, Any]]] = None

    def __len__(self) -> int:
        return self.n_rows

    def __bool__(self) -> bool:
        return self.n_rows > 0

    def _row(self, i: int) -> Dict[str, Any]:
        return {nm: (None if nm in self.null_fields
                     else self.columns[nm][i].item())
                for nm in self._names}

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._row(j) for j in range(*i.indices(self.n_rows))]
        n = self.n_rows
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self._row(i)

    def _materialize(self) -> List[Dict[str, Any]]:
        """The shared dict list, built in bulk on first full iteration:
        whole-column ``tolist()`` (C speed, same python scalars as
        ``_row``'s per-element ``.item()``) then one zip pass."""
        if self._dicts is None:
            lists = [([None] * self.n_rows if nm in self.null_fields
                      else self.columns[nm].tolist())
                     for nm in self._names]
            names = self._names
            self._dicts = [dict(zip(names, vals))
                           for vals in zip(*lists)]
        return self._dicts

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        return iter(self._materialize())

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, ColumnarRecords):
            return (self._names == other._names
                    and self.null_fields == other.null_fields
                    and all(_np_eq(self.columns[nm], other.columns[nm])
                            for nm in self._names))
        if isinstance(other, (list, tuple)):
            return len(other) == self.n_rows \
                and all(a == b for a, b in zip(self, other))
        return NotImplemented

    def __repr__(self) -> str:
        return (f"ColumnarRecords({self.n_rows} rows × "
                f"{len(self._names)} cols)")

    def __reduce__(self):
        # pickles (and therefore compares, in tests that pickle both
        # sides) exactly like the Python decoder's list of dicts
        return (list, (list(self),))


def _np_eq(a, b) -> bool:
    return a.shape == b.shape and a.dtype == b.dtype \
        and bool(np.array_equal(a, b))


def _probe_fixed_layout(block: bytes, schema: Any
                        ) -> Optional[List[Tuple[str, bytes, str, int]]]:
    """Walk the FIRST record of ``block`` and hypothesize a fixed-stride
    layout: per field ``(name, union-branch prefix bytes, kind, offset)``.
    None when any field is variable-width under the branch this record
    took (varint long/int, string/bytes, containers, named types)."""
    if not (isinstance(schema, dict) and schema.get("type") == "record"
            and schema.get("fields")):
        return None
    cur = _Cursor(block)
    plan: List[Tuple[str, bytes, str, int]] = []
    offset = 0
    for f in schema["fields"]:
        ft = f["type"]
        prefix = b""
        if isinstance(ft, list):              # union: record the branch
            idx = cur.zigzag_long()
            if not 0 <= idx < len(ft):
                return None
            prefix = _zigzag_bytes(idx)
            ft = ft[idx]
        if not isinstance(ft, str) or ft not in _FIXED_WIDTH:
            return None
        w = _FIXED_WIDTH[ft]
        cur.read(w)
        plan.append((f["name"], prefix, ft, offset))
        offset += len(prefix) + w
    if offset == 0:
        return None
    return plan


def _vector_decode_block(block: bytes, count: int, schema: Any
                         ) -> Optional[Tuple[Dict[str, Any], List[str]]]:
    """Decode one block as strided numpy columns — or None when the
    layout hypothesis from its first record does not VERIFY (stride ×
    count must equal the block size, and every union field's branch
    byte must be the same constant in every row; a mixed-branch column
    — some rows null, some not — fails the check and falls back to the
    exact Python decoder). Verified columns are bit-identical to the
    Python path: the payload bytes are reinterpreted, never re-encoded.
    """
    try:
        plan = _probe_fixed_layout(block, schema)
    except (AvroDecodeError, IndexError, struct.error):
        return None
    if plan is None:
        return None
    last_name, last_prefix, last_kind, last_off = plan[-1]
    stride = last_off + len(last_prefix) + _FIXED_WIDTH[last_kind]
    if stride * count != len(block):
        return None
    u8 = np.frombuffer(block, np.uint8).reshape(count, stride)
    cols: Dict[str, Any] = {}
    nulls: List[str] = []
    for name, prefix, kind, off in plan:
        for j, byte in enumerate(prefix):
            if not (u8[:, off + j] == byte).all():
                return None               # branch varies row-to-row
        po = off + len(prefix)
        if kind == "null":
            cols[name] = np.full(count, np.nan)
            nulls.append(name)
        elif kind == "boolean":
            cols[name] = u8[:, po] != 0
        else:
            dt = "<f8" if kind == "double" else "<f4"
            cols[name] = np.ascontiguousarray(
                u8[:, po:po + _FIXED_WIDTH[kind]]).view(dt).ravel()
    return cols, nulls


def _decode_container_columnar(data: bytes) -> Optional[ColumnarRecords]:
    """Whole-container vectorized decode; None = fall back to the
    Python decoder (never partially: one non-verifying block rejects
    the file, so the output is always all-columnar or all-dicts)."""
    cur = _Cursor(data, 4)
    schema, codec, sync = _parse_header(cur)
    parts: List[Tuple[Dict[str, Any], List[str]]] = []
    for count, block in _iter_blocks(cur, codec, sync):
        if count <= 0:
            continue
        dec = _vector_decode_block(block, count, schema)
        if dec is None:
            return None
        parts.append(dec)
    if not parts:
        # an empty container still needs the schema's field names; the
        # Python path returns [] — match it
        return ColumnarRecords({})
    cols0, nulls0 = parts[0]
    if len(parts) == 1:
        return ColumnarRecords(cols0, tuple(nulls0))
    # multi-block: merge only when every block agrees on names, dtypes
    # and null-branch fields (a field that is all-null in one block and
    # valued in another needs the dict decoder's per-row Nones)
    names = list(cols0)
    for cols, nulls in parts[1:]:
        if list(cols) != names or nulls != nulls0:
            return None
        if any(cols[nm].dtype != cols0[nm].dtype for nm in names):
            return None
    merged = {nm: np.concatenate([p[0][nm] for p in parts])
              for nm in names}
    return ColumnarRecords(merged, tuple(nulls0))


def read_avro_table(path: str):
    """Pipeline-facing decode: :class:`ColumnarRecords` when the file
    verifies as fixed-stride (the vectorized numpy path — releases the
    GIL, so the pipeline's decode workers truly run in parallel), else
    the exact ``List[Dict]`` the Python decoder produces. Same error
    contract and ``avro.decode`` fault site as
    :func:`read_avro_records` (shared via :func:`_read_container`);
    both shapes iterate as the same dicts.
    """
    from .. import pipeline

    def _decode(data: bytes):
        table = _decode_container_columnar(data)
        if table is not None:
            pipeline._tally("decode_vectorized")
            return table
        pipeline._tally("decode_fallback")
        return _decode_container(data)

    return _read_container(path, _decode)


# ---------------------------------------------------------------------------
# Encoder — score output (OpWorkflowModel.saveScores / RichDataset.saveAvro,
# core/.../OpWorkflowModel.scala:376-421). Counterpart of the decoder above:
# same container format, null/deflate codecs, same schema subset.
# ---------------------------------------------------------------------------

def _zigzag_bytes(v: int) -> bytes:
    u = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode(buf: bytearray, schema: Any, value: Any,
            named: Dict[str, Any]) -> None:
    if isinstance(schema, str):
        s = schema
        if s == "null":
            return
        if s == "boolean":
            buf += b"\x01" if value else b"\x00"
        elif s in ("int", "long"):
            buf += _zigzag_bytes(int(value))
        elif s == "float":
            buf += struct.pack("<f", float(value))
        elif s == "double":
            buf += struct.pack("<d", float(value))
        elif s == "bytes":
            b = bytes(value)
            buf += _zigzag_bytes(len(b)) + b
        elif s == "string":
            b = str(value).encode("utf-8")
            buf += _zigzag_bytes(len(b)) + b
        elif s in named:
            _encode(buf, named[s], value, named)
        else:
            raise AvroDecodeError(f"Unknown schema reference {s!r}")
        return
    if isinstance(schema, list):                  # union: pick the branch
        # two passes: STRICT typing first (a long in ["null","string",
        # "long"] must encode as long, not be swallowed by an earlier
        # string branch), then the lenient pass where "string" acts as
        # the stringify-anything escape hatch for values the inferred
        # schema didn't anticipate
        for strict in (True, False):
            for idx, branch in enumerate(schema):
                if _union_matches(branch, value, strict=strict):
                    buf += _zigzag_bytes(idx)
                    _encode(buf, branch, value, named)
                    return
        raise AvroDecodeError(
            f"No union branch of {schema} matches {type(value).__name__}")
    t = schema["type"]
    if t == "record":
        _register(schema, named)
        for f in schema["fields"]:
            _encode(buf, f["type"], (value or {}).get(f["name"]), named)
    elif t == "enum":
        _register(schema, named)
        buf += _zigzag_bytes(schema["symbols"].index(value))
    elif t == "fixed":
        _register(schema, named)
        buf += bytes(value)
    elif t == "array":
        items = list(value or ())
        if items:
            buf += _zigzag_bytes(len(items))
            for it in items:
                _encode(buf, schema["items"], it, named)
        buf += _zigzag_bytes(0)
    elif t == "map":
        entries = dict(value or {})
        if entries:
            buf += _zigzag_bytes(len(entries))
            for k, v in entries.items():
                kb = str(k).encode("utf-8")
                buf += _zigzag_bytes(len(kb)) + kb
                _encode(buf, schema["values"], v, named)
        buf += _zigzag_bytes(0)
    else:
        _encode(buf, t, value, named)


def _union_matches(branch: Any, value: Any, strict: bool = True) -> bool:
    if branch == "null":
        return value is None
    if value is None:
        return False
    if branch == "boolean":
        return isinstance(value, bool)
    if branch in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if branch in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if branch == "string":
        if strict:
            return isinstance(value, str)
        # lenient pass: the stringify-anything escape hatch — better a
        # str()'d value than a torn container when a post-schema-lock
        # streaming batch surprises the inferred union
        return not isinstance(value, (bytes, bytearray))
    if branch == "bytes":
        return isinstance(value, (bytes, bytearray))
    if isinstance(branch, dict):
        t = branch.get("type")
        if t == "array":
            return isinstance(value, (list, tuple))
        if t in ("map", "record"):
            return isinstance(value, dict)
    return True


def _infer_value_schema(values: List[Any]) -> Any:
    """ALWAYS-nullable union schema for one field's observed values.

    Unconditional nullability (and a long+double pair for numerics) keeps
    a schema inferred from the FIRST streaming batch valid for later
    batches whose null pattern or int/float flavor differs — the sink
    locks the container schema at the first block. Collection element
    schemas are unions too, so None elements inside lists/maps encode.
    All-None fields get a catch-all branch set."""
    present = [v for v in values if v is not None]
    if not present:
        return ["null", "long", "double", "string"]
    # every union keeps a trailing "string" branch: the schema locks at
    # the first streaming block, and the lenient stringify-anything escape
    # hatch (_union_matches strict=False) can only fire if the branch
    # exists — without it a later batch whose value type surprises the
    # union aborts the scoring stream (ADVICE r3)
    if all(isinstance(p, bool) for p in present):
        return ["null", "boolean", "string"]
    if all(isinstance(p, (int, float)) and not isinstance(p, bool)
           for p in present):
        return ["null", "long", "double", "string"]
    if all(isinstance(p, (bytes, bytearray)) for p in present):
        return ["null", "bytes", "string"]
    if all(isinstance(p, dict) for p in present):
        inner = _infer_value_schema(
            [x for p in present for x in p.values()])
        return ["null", {"type": "map", "values": inner}, "string"]
    if all(isinstance(p, (list, tuple, set, frozenset)) for p in present):
        inner = _infer_value_schema([x for p in present for x in p])
        return ["null", {"type": "array", "items": inner}, "string"]
    return ["null", "string"]


def infer_avro_schema(records: List[Dict[str, Any]],
                      name: str = "ScoreRecord") -> Dict[str, Any]:
    """Record schema from score rows (field order = first-seen order)."""
    fields: List[str] = []
    for r in records:
        for k in r:
            if k not in fields:
                fields.append(k)
    return {"type": "record", "name": name,
            "fields": [{"name": f,
                        "type": _infer_value_schema(
                            [r.get(f) for r in records])}
                       for f in fields]}


class AvroWriter:
    """Streaming Avro container writer (null/deflate codecs).

    Header (magic + metadata + sync marker) goes out on construction;
    each :meth:`append` emits one sync-delimited block, so the sink can
    stream scoring batches without holding the dataset (the
    StreamingScore regime)."""

    def __init__(self, path: str, schema: Dict[str, Any],
                 codec: str = "deflate"):
        import os as _os
        import secrets

        if codec not in ("null", "deflate"):
            raise AvroDecodeError(f"Unsupported avro codec {codec!r}")
        self.schema = schema
        self.codec = codec
        self._named: Dict[str, Any] = {}
        self._sync = secrets.token_bytes(16)
        d = _os.path.dirname(path)
        if d:
            _os.makedirs(d, exist_ok=True)
        self._fh = open(path, "wb")
        header = bytearray(_MAGIC)
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        header += _zigzag_bytes(len(meta))
        for k, v in meta.items():
            kb = k.encode()
            header += _zigzag_bytes(len(kb)) + kb
            header += _zigzag_bytes(len(v)) + v
        header += _zigzag_bytes(0)
        header += self._sync
        self._fh.write(bytes(header))

    def append(self, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        buf = bytearray()
        for r in records:
            _encode(buf, self.schema, r, self._named)
        block = bytes(buf)
        if self.codec == "deflate":
            co = zlib.compressobj(wbits=-15)
            block = co.compress(block) + co.flush()
        out = bytearray()
        out += _zigzag_bytes(len(records))
        out += _zigzag_bytes(len(block))
        out += block
        out += self._sync
        self._fh.write(bytes(out))

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def write_avro_records(path: str, records: List[Dict[str, Any]],
                       schema: Optional[Dict[str, Any]] = None,
                       codec: str = "deflate") -> None:
    """One-shot counterpart of :func:`read_avro_records`."""
    w = AvroWriter(path, schema or infer_avro_schema(records), codec)
    try:
        w.append(records)
    finally:
        w.close()
