"""Avro Object Container File reader — pure Python, dependency-free.

Parity: ``AvroReader`` / ``AvroInOut`` (``readers/.../DataReaders.scala``,
``utils/.../io/avro/AvroInOut.scala``). The reference reads Avro through
Spark; here a compact decoder of the Avro 1.x container format (spec:
magic ``Obj\\x01``, metadata map carrying ``avro.schema``/``avro.codec``,
sync-marker-delimited blocks of binary-encoded records; null and deflate
codecs) feeds the host record path. Supports the schema subset AutoML
data uses: primitives, records, enums, arrays, maps, fixed and unions.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Tuple

__all__ = ["read_avro_records", "AvroDecodeError"]

_MAGIC = b"Obj\x01"


class AvroDecodeError(ValueError):
    pass


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise AvroDecodeError("Truncated avro data")
        self.pos += n
        return b

    # -- primitives (Avro binary encoding) --------------------------------
    def zigzag_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def bytes_(self) -> bytes:
        return self.read(self.zigzag_long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")


def _decode(cur: _Cursor, schema: Any, named: Dict[str, Any]) -> Any:
    if isinstance(schema, str):
        s = schema
        if s == "null":
            return None
        if s == "boolean":
            return cur.read(1) != b"\x00"
        if s in ("int", "long"):
            return cur.zigzag_long()
        if s == "float":
            return cur.float_()
        if s == "double":
            return cur.double()
        if s == "bytes":
            return cur.bytes_()
        if s == "string":
            return cur.string()
        if s in named:
            return _decode(cur, named[s], named)
        raise AvroDecodeError(f"Unknown schema reference {s!r}")
    if isinstance(schema, list):                  # union: branch index
        idx = cur.zigzag_long()
        if not (0 <= idx < len(schema)):
            raise AvroDecodeError(f"Bad union branch {idx}")
        return _decode(cur, schema[idx], named)
    t = schema["type"]
    if t == "record":
        _register(schema, named)
        return {f["name"]: _decode(cur, f["type"], named)
                for f in schema["fields"]}
    if t == "enum":
        _register(schema, named)
        return schema["symbols"][cur.zigzag_long()]
    if t == "fixed":
        _register(schema, named)
        return cur.read(schema["size"])
    if t == "array":
        out: List[Any] = []
        while True:
            n = cur.zigzag_long()
            if n == 0:
                break
            if n < 0:             # block with byte size prefix
                n = -n
                cur.zigzag_long()
            for _ in range(n):
                out.append(_decode(cur, schema["items"], named))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n = cur.zigzag_long()
            if n == 0:
                break
            if n < 0:
                n = -n
                cur.zigzag_long()
            for _ in range(n):
                m[cur.string()] = _decode(cur, schema["values"], named)
        return m
    return _decode(cur, t, named)     # e.g. {"type": "string"}


def _register(schema: Dict[str, Any], named: Dict[str, Any]) -> None:
    name = schema.get("name")
    if name:
        ns = schema.get("namespace")
        named[name] = schema
        if ns:
            named[f"{ns}.{name}"] = schema


def read_avro_records(path: str) -> List[Dict[str, Any]]:
    """Decode every record of an Avro container file into dicts."""
    with open(path, "rb") as fh:
        data = fh.read()
    if data[:4] != _MAGIC:
        raise AvroDecodeError(f"{path} is not an Avro container file")
    cur = _Cursor(data, 4)

    meta: Dict[str, bytes] = {}
    while True:
        n = cur.zigzag_long()
        if n == 0:
            break
        if n < 0:
            n = -n
            cur.zigzag_long()
        for _ in range(n):
            k = cur.string()
            meta[k] = cur.bytes_()
    schema = json.loads(meta[b"avro.schema".decode()]
                        if isinstance(meta.get("avro.schema"), str)
                        else meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = cur.read(16)

    named: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    while cur.pos < len(data):
        count = cur.zigzag_long()
        size = cur.zigzag_long()
        block = cur.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise AvroDecodeError(f"Unsupported avro codec {codec!r}")
        bcur = _Cursor(block)
        for _ in range(count):
            records.append(_decode(bcur, schema, named))
        if cur.read(16) != sync:
            raise AvroDecodeError("Sync marker mismatch")
    return records
