"""Portable model export — the MLeap-free serving story.

Parity: the reference's ``local`` module converts Spark-wrapped models
through MLeap bundles so scoring needs no Spark
(``local/.../OpWorkflowModelLocal.scala:93-197``). Here the fitted
prediction head is already a pure JAX function, so it exports directly to
a **StableHLO artifact** via ``jax.export`` — loadable from any JAX
process (CPU serving included) without this framework installed, and
batch-size polymorphic so one artifact serves any request size.

The full row→features path stays host-side Python (``score_fn``); this
export covers the device half (feature vector → Prediction triple), which
is what model-serving infrastructure typically wants hardware-portable.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = ["export_prediction_fn", "load_prediction_fn"]

_BLOB = "prediction_fn.stablehlo"
_META = "export.json"


def export_prediction_fn(model, path: str,
                         pred_feature=None,
                         feature_dim: Optional[int] = None) -> Dict[str, Any]:
    """Export the fitted prediction head as a serialized StableHLO module.

    ``model`` — a WorkflowModel; ``pred_feature`` — the Prediction result
    feature (defaults to the first Prediction-typed result);
    ``feature_dim`` — the input vector width (defaults to the width
    recorded by the selector's input metadata, required if absent).
    Returns the metadata dict written alongside the artifact.
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    from .types.feature_types import Prediction

    if pred_feature is None:
        pred_feature = next(
            (f for f in model.result_features if f.ftype is Prediction),
            None)
        if pred_feature is None:
            raise ValueError("Model has no Prediction result feature")
    predictor = model.stage_of(pred_feature)
    if feature_dim is None:
        vec_feature = predictor.input_features[1]
        vec_stage = model.fitted_stages.get(
            vec_feature.origin_stage.uid if vec_feature.origin_stage
            else "", None)
        width = getattr(vec_stage, "width", None)
        if width is None and hasattr(vec_stage, "keep_indices"):
            width = len(vec_stage.keep_indices)
        if width is None:
            raise ValueError(
                "Cannot infer feature_dim; pass it explicitly")
        feature_dim = int(width)

    def predict(X):
        pred, raw, prob = predictor.predict_device(X)
        return {"prediction": pred, "rawPrediction": raw,
                "probability": prob}

    # batch-polymorphic: one artifact serves any request size
    b = jexport.symbolic_shape("b")[0]
    exp = jexport.export(jax.jit(predict))(
        jax.ShapeDtypeStruct((b, feature_dim), jnp.float32))
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, _BLOB), "wb") as fh:
        fh.write(exp.serialize())
    meta = {"featureDim": feature_dim,
            "predFeature": pred_feature.name,
            "outputs": ["prediction", "rawPrediction", "probability"]}
    with open(os.path.join(path, _META), "w") as fh:
        json.dump(meta, fh, indent=1)
    return meta


def load_prediction_fn(path: str) -> Callable[[np.ndarray], Dict[str, Any]]:
    """Load an exported artifact → callable(X [n, d] f32) → dict of
    prediction/raw/probability arrays. Needs only jax, not this package."""
    from jax import export as jexport

    with open(os.path.join(path, _BLOB), "rb") as fh:
        exp = jexport.deserialize(fh.read())
    meta = json.load(open(os.path.join(path, _META)))

    def call(X: np.ndarray) -> Dict[str, Any]:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2 or X.shape[1] != meta["featureDim"]:
            raise ValueError(
                f"Expected [n, {meta['featureDim']}] input, got {X.shape}")
        return {k: np.asarray(v) for k, v in exp.call(X).items()}

    return call
