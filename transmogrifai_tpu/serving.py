"""Portable model export — the MLeap-free serving story.

Parity: the reference's ``local`` module converts Spark-wrapped models
through MLeap bundles so scoring needs no Spark
(``local/.../OpWorkflowModelLocal.scala:93-197``). Here the fitted
prediction head is already a pure JAX function, so it exports directly to
a **StableHLO artifact** via ``jax.export`` — loadable from any JAX
process (CPU serving included) without this framework installed, and
batch-size polymorphic so one artifact serves any request size.

Two export granularities:

* :func:`export_prediction_fn` — the prediction head alone (feature
  vector → Prediction triple), the original contract.
* :func:`export_scoring_fn` — the compiled scoring engine's WHOLE fused
  chain (every vectorizer ``device_compute``, the combiner concat, the
  sanity-checker gather, scalers, the predictor) as one batch-polymorphic
  StableHLO program. The host half (string hashing, vocab lookups —
  ``host_prepare``) stays host-side Python by design; the artifact covers
  everything that runs on the device, so serving infrastructure re-homes
  the full device computation, not just the head.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import telemetry

logger = logging.getLogger(__name__)

__all__ = ["export_prediction_fn", "load_prediction_fn",
           "export_scoring_fn", "load_scoring_fn"]

_BLOB = "prediction_fn.stablehlo"
_META = "export.json"
_SCORE_BLOB = "scoring_fn.stablehlo"
_SCORE_META = "scoring_export.json"


def _blob_fingerprint(payload: bytes) -> Dict[str, Any]:
    """Integrity fields written into the export metadata: byte size and
    a blake2b-128 digest of the serialized module."""
    import hashlib
    return {"blobBytes": len(payload),
            "blobDigest": hashlib.blake2b(payload, digest_size=16)
                                 .hexdigest()}


def _load_verified_blob(path: str, blob_name: str, meta_name: str
                        ) -> tuple:
    """Read (meta, blob bytes), failing with a DESCRIPTIVE error on a
    truncated or corrupt artifact instead of a raw deserialization
    traceback: the metadata's recorded size and digest are checked
    before the bytes ever reach ``jax.export.deserialize``. Artifacts
    from older exports (no fingerprint fields) skip the checks."""
    import hashlib
    meta_path = os.path.join(path, meta_name)
    blob_path = os.path.join(path, blob_name)
    try:
        with open(meta_path) as fh:
            meta = json.load(fh)
    except FileNotFoundError:
        raise ValueError(
            f"no serving artifact at {path!r}: missing {meta_name} "
            "(was this directory written by export_*_fn?)") from None
    except json.JSONDecodeError as e:
        raise ValueError(
            f"corrupt serving artifact at {path!r}: {meta_name} is not "
            f"valid JSON ({e})") from e
    try:
        with open(blob_path, "rb") as fh:
            payload = fh.read()
    except FileNotFoundError:
        raise ValueError(
            f"corrupt serving artifact at {path!r}: {meta_name} present "
            f"but {blob_name} missing") from None
    expect = meta.get("blobBytes")
    if expect is not None:
        try:
            expect = int(expect)
        except (TypeError, ValueError):
            # the metadata itself is damaged — still a descriptive
            # failure, never a raw int() traceback
            raise ValueError(
                f"corrupt serving artifact at {path!r}: {meta_name} "
                f"records a non-numeric blobBytes ({expect!r})") from None
        if len(payload) != expect:
            raise ValueError(
                f"truncated serving artifact at {path!r}: {blob_name} is "
                f"{len(payload)} bytes, export recorded {expect} (partial "
                "copy or torn write — re-export or re-copy the artifact)")
    digest = meta.get("blobDigest")
    if digest is not None:
        got = hashlib.blake2b(payload, digest_size=16).hexdigest()
        if got != digest:
            raise ValueError(
                f"corrupt serving artifact at {path!r}: {blob_name} "
                f"digest {got} does not match the export's {digest} "
                "(bytes were altered in transit)")
    return meta, payload


def _deserialize_blob(payload: bytes, path: str):
    from jax import export as jexport
    try:
        return jexport.deserialize(payload)
    except Exception as e:  # lint: broad-except — wrap-and-reraise with artifact context
        raise ValueError(
            f"corrupt serving artifact at {path!r}: StableHLO "
            f"deserialization failed ({type(e).__name__}: {e}); the "
            "size/digest checks passed, so this usually means a jax "
            "version mismatch between export and load") from e


def export_prediction_fn(model, path: str,
                         pred_feature=None,
                         feature_dim: Optional[int] = None) -> Dict[str, Any]:
    """Export the fitted prediction head as a serialized StableHLO module.

    ``model`` — a WorkflowModel; ``pred_feature`` — the Prediction result
    feature (defaults to the first Prediction-typed result);
    ``feature_dim`` — the input vector width (defaults to the width
    recorded by the selector's input metadata, required if absent).
    Returns the metadata dict written alongside the artifact.
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    from .types.feature_types import Prediction

    if pred_feature is None:
        pred_feature = next(
            (f for f in model.result_features if f.ftype is Prediction),
            None)
        if pred_feature is None:
            raise ValueError("Model has no Prediction result feature")
    predictor = model.stage_of(pred_feature)
    if feature_dim is None:
        vec_feature = predictor.input_features[1]
        vec_stage = model.fitted_stages.get(
            vec_feature.origin_stage.uid if vec_feature.origin_stage
            else "", None)
        width = getattr(vec_stage, "width", None)
        if width is None and hasattr(vec_stage, "keep_indices"):
            width = len(vec_stage.keep_indices)
        if width is None:
            raise ValueError(
                "Cannot infer feature_dim; pass it explicitly")
        feature_dim = int(width)

    def predict(X):
        pred, raw, prob = predictor.predict_device(X)
        return {"prediction": pred, "rawPrediction": raw,
                "probability": prob}

    # batch-polymorphic: one artifact serves any request size
    b = jexport.symbolic_shape("b")[0]
    with telemetry.span("serving:export_prediction_fn",
                        feature_dim=feature_dim):
        exp = jexport.export(jax.jit(predict))(
            jax.ShapeDtypeStruct((b, feature_dim), jnp.float32))
        payload = exp.serialize()
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _BLOB), "wb") as fh:
            fh.write(payload)
    telemetry.counter("serving.exports").inc()
    meta = {"featureDim": feature_dim,
            "predFeature": pred_feature.name,
            "coverage": "prediction_head",
            "outputs": ["prediction", "rawPrediction", "probability"],
            **_blob_fingerprint(payload)}
    with open(os.path.join(path, _META), "w") as fh:
        json.dump(meta, fh, indent=1)
    return meta


def load_prediction_fn(path: str) -> Callable[[np.ndarray], Dict[str, Any]]:
    """Load an exported artifact → callable(X [n, d] f32) → dict of
    prediction/raw/probability arrays. Needs only jax, not this package.
    A truncated or corrupt artifact raises a descriptive ``ValueError``
    (size + digest checked against the export metadata) instead of a raw
    deserialization traceback."""
    with telemetry.span("serving:load_prediction_fn"):
        meta, payload = _load_verified_blob(path, _BLOB, _META)
        exp = _deserialize_blob(payload, path)
    telemetry.counter("serving.loads").inc()

    def call(X: np.ndarray) -> Dict[str, Any]:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2 or X.shape[1] != meta["featureDim"]:
            raise ValueError(
                f"Expected [n, {meta['featureDim']}] input, got {X.shape}")
        return {k: np.asarray(v) for k, v in exp.call(X).items()}

    return call


def _block_key(spec: Dict[str, Any]) -> str:
    return (f"{spec['uid']}/{spec['name']}" if spec["kind"] == "prepared"
            else spec["name"])


def export_scoring_fn(model, path: str, sample_data,
                      bucket_cap: Optional[int] = None,
                      aot: bool = True,
                      aot_ladder: Optional[List[int]] = None
                      ) -> Dict[str, Any]:
    """Export the FULL fused transform→predict chain as StableHLO.

    Requires every stage between the prepared host blocks and the result
    features to be device-capable (scoring.ScoringEngine's fused set must
    include the predictor); raises ``ValueError`` otherwise — callers
    wanting the head-only artifact use :func:`export_prediction_fn`.

    ``sample_data`` (records or a raw ColumnStore) supplies one host pass
    to discover the prepared-block manifest; the exported program is
    batch-size polymorphic over the row dimension. Returns the metadata
    dict (manifest + outputs) written alongside the artifact.

    With ``aot`` (the default) the whole power-of-two bucket ladder is
    additionally compiled ahead of time and shipped as serialized
    executables under ``aot_bank/`` (aot.py): a cold process that loads
    the export answers its first request without paying a single XLA
    compile. The model's attached ExecutionPlan — CSE merges,
    dead-column pruning — is baked into both the StableHLO and the
    banked programs. ``aot_ladder`` restricts the banked buckets (the
    full ladder otherwise). Whatever ``aot`` says, the export metadata
    records the bucket ladder, plan/state digests and the jax/jaxlib +
    device environment, so ``load_scoring_fn`` can warn on version skew
    even for bankless artifacts."""
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    from . import aot as aot_mod
    from .scoring import ScoringEngine, bucket_ladder

    eng = ScoringEngine(model, gate_bandwidth=False,
                        plan=getattr(model, "_execution_plan", None),
                        **({"bucket_cap": bucket_cap} if bucket_cap else {}))
    if not eng.covers_prediction:
        raise ValueError(
            "full-chain export needs the predictor inside the fused "
            "program; a host-only stage consumes a device output "
            "(export_prediction_fn covers the head alone)")
    out_names = eng._out_names(results_only=True)
    if not out_names:
        raise ValueError("no fused result features to export")
    manifest = eng.export_manifest(sample_data)
    flat_fn = eng.export_callable(manifest, out_names)

    def predict(*blocks):
        outs = flat_fn(*blocks)
        flat: Dict[str, Any] = {}
        for nm, v in outs.items():
            if isinstance(v, tuple):    # Prediction triple
                flat[f"{nm}.prediction"] = v[0]
                flat[f"{nm}.rawPrediction"] = v[1]
                flat[f"{nm}.probability"] = v[2]
            else:
                flat[nm] = v
        return flat

    b = jexport.symbolic_shape("b")[0]
    args = [jax.ShapeDtypeStruct((b, *spec["tail"]),
                                 jnp.dtype(spec["dtype"]))
            for spec in manifest]
    with telemetry.span("serving:export_scoring_fn",
                        fused_stages=eng.fused_stage_count,
                        inputs=len(manifest)):
        exp = jexport.export(jax.jit(predict))(*args)
        payload = exp.serialize()
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _SCORE_BLOB), "wb") as fh:
            fh.write(payload)
    telemetry.counter("serving.exports").inc()
    meta = {"coverage": "fused_chain",
            "fusedStages": eng.fused_stage_count,
            "inputs": manifest,
            "resultFeatures": out_names,
            # environment + identity stamps (written whether or not a
            # program bank ships): load_scoring_fn compares these and
            # WARNS on skew instead of failing silently mid-request
            "bucketCap": int(eng.bucket_cap),
            "bucketLadder": bucket_ladder(eng.bucket_cap),
            "planDigest": eng.rewrite_digest(),
            "stateDigest": eng.state_digest(),
            "environment": aot_mod.environment_fingerprint(),
            **_blob_fingerprint(payload)}
    bank = None
    if aot:
        bank = aot_mod.build_program_bank(
            eng, manifest, out_names, path, ladder=aot_ladder)
    if bank is None:
        # never leave a STALE bank (a previous export's weights) next
        # to freshly written StableHLO/meta
        aot_mod.remove_bank(path)
        meta["aot"] = None
    else:
        meta["aot"] = {"programs": len(bank["programs"]),
                       "bytes": aot_mod.bank_bytes(bank),
                       "bucketLadder": bank["bucketLadder"]}
    with open(os.path.join(path, _SCORE_META), "w") as fh:
        json.dump(meta, fh, indent=1)
    return meta


def _warn_version_skew(meta: Dict[str, Any], path: str) -> None:
    """Satellite: environment compatibility used to be silent — the blob
    digest was checked but a jax/jaxlib skew between export and load
    surfaced only as a cryptic deserialization error (or not at all).
    Compare the export's recorded environment to this process and WARN
    (TMG503 advisory, telemetry-mirrored) — never fail: StableHLO is
    designed to be forward-loadable, so skew is a risk note, not an
    error. Pre-stamp exports (no ``environment`` field) skip silently."""
    want = meta.get("environment")
    if not isinstance(want, dict):
        return
    from . import lint
    from .aot import environment_fingerprint
    env = environment_fingerprint()
    skew = {k: (want.get(k), env[k]) for k in ("jax", "jaxlib")
            if want.get(k) is not None and want.get(k) != env[k]}
    if not skew:
        return
    detail = ", ".join(f"{k}: exported {a!r} / running {b!r}"
                       for k, (a, b) in sorted(skew.items()))
    f = lint.Finding("TMG503",
                     f"serving artifact version skew ({detail}) — the "
                     "StableHLO should still load, but re-export to "
                     "clear the risk", location=path)
    logger.warning("serving: %s", f.format())
    lint.emit_findings([f])


def load_scoring_fn(path: str, prefer_bank: bool = True
                    ) -> Callable[[Dict[str, np.ndarray]],
                                  Dict[str, np.ndarray]]:
    """Load a full-chain artifact → callable({block key: array}) → dict of
    output arrays. Block keys are ``"<stage uid>/<block name>"`` for
    prepared vectorizer blocks and the bare column name for direct vector
    uploads (see ``meta["inputs"]``). Needs only jax, not this package —
    the caller supplies host-prepared blocks (every row-leading array,
    one consistent batch size). A truncated or corrupt artifact raises a
    descriptive ``ValueError`` (size + digest checked against the export
    metadata) instead of a raw deserialization traceback; a jax/jaxlib
    version skew between export and load WARNS (TMG503) but loads.

    With ``prefer_bank`` (default) and a compatible AOT program bank in
    the export directory (aot.py), requests are zero-padded to the
    nearest ladder bucket and dispatched through the bank's
    pre-compiled executables — the first request pays NO XLA compile.
    Buckets the bank lacks (and any environment mismatch, corrupt
    program, oversized batch) fall back per-call to the StableHLO JIT
    path — never an error."""
    with telemetry.span("serving:load_scoring_fn"):
        meta, payload = _load_verified_blob(path, _SCORE_BLOB,
                                            _SCORE_META)
        exp = _deserialize_blob(payload, path)
    telemetry.counter("serving.loads").inc()
    _warn_version_skew(meta, path)
    manifest: List[Dict[str, Any]] = meta["inputs"]

    bank_programs: Dict[int, Any] = {}
    bank_cap = 0
    if prefer_bank:
        from . import aot as aot_mod
        from . import lint
        bank_manifest, bank_programs, findings = \
            aot_mod.load_flat_programs(
                path, expect_digests={
                    "planDigest": meta.get("planDigest"),
                    "stateDigest": meta.get("stateDigest")})
        for f in findings:
            logger.warning("serving: %s", f.format())
        if findings:
            lint.emit_findings(findings)
        if bank_programs:
            bank_cap = int(bank_manifest.get("bucketCap", 0))

    def _bank_call(args: List[np.ndarray], n: int,
                   bucket: int) -> Dict[str, np.ndarray]:
        prepared: Dict[str, Dict[str, Any]] = {}
        uploads: Dict[str, Any] = {}
        for spec, a in zip(manifest, args):
            if bucket != n:
                pad = np.zeros((bucket - n,) + a.shape[1:], dtype=a.dtype)
                a = np.concatenate([a, pad], axis=0)
            if spec["kind"] == "prepared":
                prepared.setdefault(spec["uid"], {})[spec["name"]] = a
            else:
                uploads[spec["name"]] = a
        outs = bank_programs[bucket](prepared, uploads)
        flat: Dict[str, np.ndarray] = {}
        for nm, v in outs.items():
            if isinstance(v, tuple):    # Prediction triple
                flat[f"{nm}.prediction"] = np.asarray(v[0])[:n]
                flat[f"{nm}.rawPrediction"] = np.asarray(v[1])[:n]
                flat[f"{nm}.probability"] = np.asarray(v[2])[:n]
            else:
                flat[nm] = np.asarray(v)[:n]
        return flat

    def call(blocks: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        args = []
        for spec in manifest:
            key = _block_key(spec)
            if key not in blocks:
                raise ValueError(f"missing input block {key!r}")
            args.append(np.asarray(blocks[key], dtype=spec["dtype"]))
        ns = {a.shape[0] for a in args}
        if len(ns) > 1:
            raise ValueError(f"inconsistent batch sizes across blocks: {ns}")
        if bank_programs and args:
            from .scoring import bucket_for
            n = args[0].shape[0]
            bucket = bucket_for(n, bank_cap)
            if n <= bank_cap and bucket in bank_programs:
                telemetry.counter("serving.bank_hits").inc()
                return _bank_call(args, n, bucket)
            telemetry.counter("serving.bank_misses").inc()
        out = exp.call(*args)
        flat: Dict[str, np.ndarray] = {}
        for k, v in out.items():
            flat[k] = np.asarray(v)
        return flat

    call.meta = meta
    call.bank_buckets = sorted(bank_programs)
    return call
