"""Workload flight recorder, deterministic replay, critical-path analysis.

PR 15 gave every request a trace and a latency decomposition; this
module records the *workload itself* so it can be re-driven. Three
surfaces (docs/observability.md "Workload capture & replay"):

1. **Flight recorder** — every request accepted by ``server.serve_http``
   or the fleet router appends one compact JSONL record (arrival offset
   on the process's monotonic trace epoch, model, trace id, payload or
   shape digest, routing decision, outcome, per-phase latency
   decomposition) into a per-process ``shard-<role>-<pid>.workload.jsonl``
   under ``customParams.workloadDir``. Records are written OFF the
   request path: a bounded queue feeds one named writer thread, a full
   queue DROPS the record and tallies it (the drift-sentinel
   discipline — observation must never block a worker), files
   size-rotate at ``workloadMaxMb``, and a SIGKILL mid-write tears at
   most the last line, which ``merge`` skips and tallies.

2. **Replay harness** — :func:`merge_workload_shards` stitches the
   per-process shards into ONE arrival-ordered workload (clock-offset
   aligned on each shard's ``epochUnixS`` anchor, router+worker records
   of the same trace id combined); :func:`replay_workload` re-drives it
   open-loop against a live server/fleet at recorded (or
   ``speed``-scaled) arrival offsets, asserts score parity where
   payloads were recorded, and emits the same decomposed-latency
   summary — two configs replayed against one recording yield PAIRED
   per-phase deltas.

3. **Critical-path analyzer** — :func:`analyze_trace` walks a merged
   trace, follows router→worker→dispatch span parentage and links to
   reconstruct each request's critical path across processes, and
   reports per-phase self-time attribution (p50/p99), the top-K
   slowest requests with their paths, and (via :func:`diff_analyses`)
   a thresholded baseline diff for regression watchdogs.

Always-on tallies ride every runner metrics doc and bench doc as
``workload_stats()`` (the ``engine_cache_stats`` discipline).
"""
from __future__ import annotations

import glob
import hashlib
import http.client
import json
import logging
import os
import queue
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import telemetry
from .utils import locks

logger = logging.getLogger(__name__)

__all__ = [
    "WorkloadRecorder", "start_recorder", "stop_recorder", "recorder",
    "recording_enabled", "record_request", "merge_workload_shards",
    "write_merged_workload", "load_workload", "summarize_workload",
    "replay_workload", "analyze_trace", "diff_analyses",
    "workload_stats", "reset_workload_stats",
    "DEFAULT_MAX_MB", "DEFAULT_QUEUE_DEPTH", "PAYLOAD_CAP_BYTES",
]

#: active shard file name: shard-<role>-<pid>.workload.jsonl; rotated
#: segments insert a 3-digit sequence before the extension
SHARD_SUFFIX = ".workload.jsonl"

#: size-rotation threshold per shard segment (customParams.workloadMaxMb)
DEFAULT_MAX_MB = 64.0

#: bounded record queue between request threads and the writer thread —
#: beyond it, records are DROPPED and tallied, never block the request
DEFAULT_QUEUE_DEPTH = 512

#: per-request payload/outputs JSON byte cap: a payload serializing
#: larger than this is recorded as a shape DIGEST (rows, bytes, sha256
#: prefix) instead — the recorder bounds its own disk cost
PAYLOAD_CAP_BYTES = 65536

#: request records below this schema version are rejected by replay
WORKLOAD_VERSION = 1


# ---------------------------------------------------------------------------
# always-on tallies (bench docs stamp these; the engine_cache_stats
# discipline — docs/observability.md "Workload capture & replay")
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"records_enqueued": 0, "records_written": 0,
          "records_dropped": 0, "payloads_recorded": 0,
          "payloads_digested": 0, "rotations": 0, "shards_merged": 0,
          "merge_errors": 0, "torn_records_skipped": 0,
          "replayed_requests": 0, "replay_skipped_no_payload": 0,
          "replay_failures": 0, "parity_checked": 0,
          "parity_failures": 0, "replay_truncated": 0,
          "replay_late_sends": 0}


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


def workload_stats() -> Dict[str, Any]:
    """Process-wide flight-recorder/replay tallies (always on) plus the
    derived ``drop_rate`` (records dropped per enqueue attempt) and the
    live ``recording`` flag."""
    with _TALLY_LOCK:
        out: Dict[str, Any] = dict(_TALLY)
    attempted = out["records_enqueued"] + out["records_dropped"]
    out["drop_rate"] = (round(out["records_dropped"] / attempted, 4)
                        if attempted else None)
    out["recording"] = _RECORDER is not None
    return out


def reset_workload_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _payload_digest(body: bytes, rows: int) -> Dict[str, Any]:
    return {"rows": int(rows), "bytes": len(body),
            "sha256": hashlib.sha256(body).hexdigest()[:16]}


class WorkloadRecorder:
    """One per-process JSONL shard writer, fed through a bounded queue
    by :func:`record_request` and drained by a single named daemon
    thread. Serialization, the payload-cap decision and the disk write
    all happen on the writer thread — the request path pays one
    ``put_nowait``."""

    def __init__(self, dir_path: str, role: Optional[str] = None,
                 max_mb: float = DEFAULT_MAX_MB, payloads: bool = True,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH):
        self.dir = str(dir_path)
        self.role = str(role) if role else telemetry.trace_role()
        self.pid = os.getpid()
        self.max_bytes = max(int(float(max_mb) * 1e6), 4096)
        self.payloads = bool(payloads)
        os.makedirs(self.dir, exist_ok=True)
        # the shard's wall-clock anchor of the process's monotonic trace
        # epoch — the SAME anchor trace shards record, so workload and
        # trace merges align on identical clock offsets
        now_unix = time.time()  # lint: wall-clock — cross-process clock-offset anchor, not a duration
        self.epoch_unix = now_unix - (time.perf_counter()
                                      - telemetry._EPOCH)
        self._segment = 0
        self._fh = None
        self._bytes = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=int(queue_depth))
        self._closed = False
        self._thread = threading.Thread(target=self._run,
                                        name="tmog-workload-writer",
                                        daemon=True)
        self._thread.start()

    # -- paths -------------------------------------------------------------
    @property
    def shard_path(self) -> str:
        return os.path.join(self.dir,
                            f"shard-{self.role}-{self.pid}{SHARD_SUFFIX}")

    def _rotated_path(self, segment: int) -> str:
        return os.path.join(
            self.dir,
            f"shard-{self.role}-{self.pid}.workload.{segment:03d}.jsonl")

    # -- request path ------------------------------------------------------
    def record(self, rec: Dict[str, Any], records: Any = None,
               outputs: Any = None, payload_json: Any = None,
               response_json: Any = None) -> bool:
        """Enqueue one request record; ``records``/``outputs`` are
        attached lazily (serialized on the writer thread, capped or
        digested there). ``payload_json``/``response_json`` are
        PRE-SERIALIZED request/response bodies (str or bytes of valid
        JSON) spliced into the line verbatim — the zero-copy path for
        a serving handler that already paid the serialization. Returns
        False when the bounded queue was full and the record was
        dropped (tallied)."""
        if self._closed:
            return False
        try:
            self._queue.put_nowait((rec, records, outputs,
                                    payload_json, response_json))
        except queue.Full:
            _tally("records_dropped")
            return False
        _tally("records_enqueued")
        return True

    # -- writer thread -----------------------------------------------------
    def _open_segment(self) -> None:
        self._fh = open(self.shard_path, "ab")
        self._bytes = self._fh.tell()
        if self._bytes == 0:
            header = {"kind": "header", "version": WORKLOAD_VERSION,
                      "role": self.role, "pid": self.pid,
                      "segment": self._segment,
                      "epochUnixS": round(self.epoch_unix, 6)}
            line = json.dumps(header,
                              separators=(",", ":")).encode() + b"\n"
            self._fh.write(line)
            self._fh.flush()
            self._bytes += len(line)

    def _rotate(self) -> None:
        self._fh.close()
        self._fh = None
        os.replace(self.shard_path, self._rotated_path(self._segment))
        self._segment += 1
        _tally("rotations")
        self._open_segment()

    def _capture(self, rec: Dict[str, Any], raw_key: str,
                 obj_key: str, raw: Any, obj: Any,
                 extras: List[Tuple[str, bytes]]) -> None:
        """Attach one captured body. A pre-serialized ``raw`` body is
        spliced verbatim under ``raw_key`` (zero re-serialization —
        the caller guarantees it is valid JSON; a corrupt splice costs
        ONE line at merge, which is torn-tolerant). A plain ``obj`` is
        dumped once here, on the writer thread, under ``obj_key``.
        Either form over the cap (or with payload capture off)
        degrades to a shape digest."""
        if raw is not None:
            blob = raw if isinstance(raw, bytes) else str(raw).encode()
            key = raw_key
        elif obj is not None:
            blob = json.dumps(obj, separators=(",", ":"),
                              default=str).encode()
            key = obj_key
        else:
            return
        if self.payloads and len(blob) <= PAYLOAD_CAP_BYTES:
            extras.append((key, blob))
            if obj_key == "payload":
                _tally("payloads_recorded")
        else:
            rec[obj_key + "Digest"] = _payload_digest(
                blob, int(rec.get("rows") or 0))
            if obj_key == "payload":
                _tally("payloads_digested")

    def _write_one(self, item: Tuple[Dict[str, Any], Any, Any,
                                     Any, Any]) -> None:
        rec, records, outputs, payload_json, response_json = item
        extras: List[Tuple[str, bytes]] = []
        self._capture(rec, "request", "payload", payload_json,
                      records, extras)
        self._capture(rec, "response", "outputs", response_json,
                      outputs, extras)
        base = json.dumps(rec, separators=(",", ":"),
                          default=str).encode()
        if extras:
            base = (base[:-1]
                    + b"".join(b',"%s":%s' % (k.encode(), v)
                               for k, v in extras) + b"}")
        line = base + b"\n"
        if self._fh is None:
            self._open_segment()
        self._fh.write(line)
        self._fh.flush()
        self._bytes += len(line)
        _tally("records_written")
        if self._bytes >= self.max_bytes:
            self._rotate()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:          # shutdown sentinel
                break
            try:
                self._write_one(item)
            except (OSError, ValueError, TypeError) as e:
                logger.warning("workload: record write failed: %r", e)
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def close(self, timeout_s: float = 10.0) -> None:
        """Drain the queue, stop the writer thread, close the shard.
        Idempotent; records arriving after close are dropped silently
        (the caller is shutting down)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._queue.put(None, timeout=timeout_s)
        except queue.Full:
            # writer wedged — don't hang shutdown; the tail tears, and
            # merge is torn-tolerant by design
            pass
        self._thread.join(timeout=timeout_s)


_RECORDER: Optional[WorkloadRecorder] = None
_RECORDER_LOCK = locks.witness_lock("workload._RECORDER_LOCK")


def start_recorder(dir_path: str, role: Optional[str] = None,
                   max_mb: float = DEFAULT_MAX_MB,
                   payloads: bool = True,
                   queue_depth: int = DEFAULT_QUEUE_DEPTH
                   ) -> WorkloadRecorder:
    """Install the process-wide flight recorder (replacing any active
    one). ``cli serve`` / ``cli fleet`` call this when
    ``customParams.workloadDir`` is set and uninstall on exit."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.close()
        _RECORDER = WorkloadRecorder(dir_path, role=role, max_mb=max_mb,
                                     payloads=payloads,
                                     queue_depth=queue_depth)
        return _RECORDER


def stop_recorder() -> None:
    """Drain and uninstall the process-wide recorder (no-op when off)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.close()
            _RECORDER = None


def recorder() -> Optional[WorkloadRecorder]:
    return _RECORDER


def recording_enabled() -> bool:
    return _RECORDER is not None


def record_request(model: str, rows: int,
                   records: Any = None, outputs: Any = None,
                   payload_json: Any = None, response_json: Any = None,
                   trace_id: Optional[str] = None,
                   t_arrival: Optional[float] = None,
                   outcome: Optional[Dict[str, Any]] = None,
                   phases: Optional[Dict[str, float]] = None,
                   route: Optional[Dict[str, Any]] = None) -> bool:
    """Record one accepted request (no-op returning False when the
    recorder is off). ``t_arrival`` is the request's arrival
    ``perf_counter()`` instant — the record stores it as an offset on
    the process's monotonic trace epoch so merge can align shards from
    different processes on their ``epochUnixS`` anchors.
    ``payload_json``/``response_json`` are pre-serialized JSON bodies
    the serving path already produced — preferred over
    ``records``/``outputs`` because the writer splices them without
    re-serializing (merge unwraps them back to ``payload``/
    ``outputs``)."""
    rec = _RECORDER
    if rec is None:
        return False
    t = t_arrival if t_arrival is not None else time.perf_counter()
    doc: Dict[str, Any] = {
        "kind": "request",
        "tOffsetS": round(t - telemetry._EPOCH, 6),
        "model": model, "rows": int(rows)}
    if trace_id:
        doc["traceId"] = trace_id
    if outcome:
        doc["outcome"] = outcome
    if phases:
        doc["phases"] = {k: round(float(v), 6)
                         for k, v in phases.items()}
    if route:
        doc["route"] = route
    return rec.record(doc, records=records, outputs=outputs,
                      payload_json=payload_json,
                      response_json=response_json)


# ---------------------------------------------------------------------------
# merge — shards -> one arrival-ordered workload
# ---------------------------------------------------------------------------

def _normalize_record(r: Dict[str, Any]) -> Dict[str, Any]:
    """Unwrap the zero-copy capture keys: a spliced ``request`` body
    becomes ``payload`` (its ``records`` list), a spliced ``response``
    body contributes ``outputs`` (and ``phases`` when the record
    itself carries none) — so merged workloads expose ONE schema
    regardless of which capture path wrote the shard."""
    req = r.pop("request", None)
    if isinstance(req, dict):
        recs = req.get("records")
        if "payload" not in r and isinstance(recs, list):
            r["payload"] = recs
    resp = r.pop("response", None)
    if isinstance(resp, dict):
        outs = resp.get("outputs")
        if "outputs" not in r and isinstance(outs, list):
            r["outputs"] = outs
        if "phases" not in r and isinstance(resp.get("phases"), dict):
            r["phases"] = resp["phases"]
    return r


def _read_shard(path: str) -> Tuple[Dict[str, Any],
                                    List[Dict[str, Any]], int]:
    """Parse one shard file: returns (header, records, torn_count).
    A final line without its newline terminator is a torn tail (the
    writer was SIGKILLed mid-write) — skipped and tallied, like any
    line that fails to parse. A missing/unparseable header makes the
    whole shard unreadable (raises ValueError)."""
    with open(path, "rb") as fh:
        raw = fh.read()
    torn = 0
    lines = raw.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()                     # clean trailing newline
    elif lines:
        lines.pop()                     # torn tail: no terminator
        torn += 1
    header: Optional[Dict[str, Any]] = None
    out: List[Dict[str, Any]] = []
    for ln in lines:
        try:
            doc = json.loads(ln)
        except ValueError:
            torn += 1
            continue
        if not isinstance(doc, dict):
            torn += 1
            continue
        if doc.get("kind") == "header":
            if header is None:
                header = doc
            continue
        if doc.get("kind") == "request":
            out.append(_normalize_record(doc))
    if header is None:
        raise ValueError("no readable header record")
    return header, out, torn


def _combine(group: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the router + worker records of ONE request (same trace id)
    into a single merged record: the earliest arrival keeps the
    timeline honest, the router contributes the routing decision and
    the client-visible outcome/e2e, the worker contributes the payload,
    outputs and per-phase decomposition."""
    group = sorted(group, key=lambda r: r["tS"])
    base = dict(group[0])
    routed = next((r for r in group if r.get("route")), None)
    phased = next(
        (r for r in group
         if any(k != "e2e" for k in (r.get("phases") or ()))), None)
    if routed is not None:
        base["route"] = routed["route"]
        if routed.get("outcome"):
            base["outcome"] = routed["outcome"]
    phases = dict((phased or {}).get("phases") or {})
    if routed is not None and (routed.get("phases") or {}).get("e2e"):
        # the router's e2e is the client-visible one (adds the forward
        # hop); the worker's sub-phases decompose what's inside it
        phases["e2e"] = routed["phases"]["e2e"]
    elif not phases:
        phases = dict((group[0].get("phases") or {}))
    if phases:
        base["phases"] = phases
    for key in ("payload", "payloadDigest", "outputs", "outputsDigest"):
        if key not in base:
            for r in group:
                if key in r:
                    base[key] = r[key]
                    break
    base["sources"] = sorted({r["role"] for r in group})
    return base


def merge_workload_shards(dir_path: str) -> Dict[str, Any]:
    """Stitch every ``shard-*.workload*.jsonl`` under ``dir_path`` into
    one arrival-ordered workload doc. Clock-offset aligned like
    ``trace merge``: each record's absolute arrival is its shard's
    ``epochUnixS`` anchor plus its monotonic offset, rebased onto the
    earliest arrival. Router and worker records sharing a trace id are
    combined into one record. Unreadable shards are skipped into
    ``mergeErrors``; torn tail records are skipped and counted in
    ``tornRecordsSkipped`` — never fatal. Raises ValueError when no
    shard is readable."""
    paths = sorted(glob.glob(os.path.join(dir_path,
                                          "shard-*.workload*.jsonl")))
    if not paths:
        raise ValueError(f"no workload shards under {dir_path!r}")
    merged: List[Dict[str, Any]] = []
    errors: List[str] = []
    torn_total = 0
    shards_read = 0
    for p in paths:
        fn = os.path.basename(p)
        try:
            header, recs, torn = _read_shard(p)
        except (OSError, ValueError) as e:
            errors.append(f"{fn}: {e!r}")
            _tally("merge_errors")
            continue
        shards_read += 1
        torn_total += torn
        epoch = float(header.get("epochUnixS", 0.0))
        for r in recs:
            r["tS"] = epoch + float(r.get("tOffsetS", 0.0))
            r["role"] = header.get("role", "proc")
            r["pid"] = header.get("pid")
            merged.append(r)
    if not shards_read:
        raise ValueError(
            f"no readable workload shards under {dir_path!r}: {errors}")
    _tally("shards_merged", shards_read)
    _tally("torn_records_skipped", torn_total)
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    singles: List[Dict[str, Any]] = []
    for r in merged:
        tid = r.get("traceId")
        if tid:
            by_trace.setdefault(tid, []).append(r)
        else:
            singles.append(r)
    combined = [_combine(g) for g in by_trace.values()]
    for r in singles:
        r["sources"] = [r["role"]]
    combined.extend(singles)
    combined.sort(key=lambda r: r["tS"])
    t0 = combined[0]["tS"] if combined else 0.0
    for r in combined:
        r["tS"] = round(r["tS"] - t0, 6)
        r.pop("tOffsetS", None)
    doc: Dict[str, Any] = {"version": WORKLOAD_VERSION,
                           "mergedShards": shards_read,
                           "baseEpochUnixS": round(t0, 6),
                           "requests": len(combined),
                           "tornRecordsSkipped": torn_total,
                           "records": combined}
    if errors:
        doc["mergeErrors"] = errors
    return doc


def write_merged_workload(doc: Dict[str, Any], out_path: str) -> None:
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, out_path)


def load_workload(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "records" not in doc:
        raise ValueError(f"{path!r} is not a merged workload file "
                         "(expected a dict with 'records')")
    return doc


# ---------------------------------------------------------------------------
# summaries — the shared decomposed-latency shape recording and replay
# both emit, so two runs yield PAIRED per-phase deltas
# ---------------------------------------------------------------------------

def _pct(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5),
              len(sorted_vals) - 1)
    return float(sorted_vals[idx])


def _phase_pcts(samples: Dict[str, List[float]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name, vals in sorted(samples.items()):
        vals = sorted(vals)
        out[name] = {"n": len(vals),
                     "p50Ms": round(_pct(vals, 0.50) * 1e3, 3),
                     "p95Ms": round(_pct(vals, 0.95) * 1e3, 3),
                     "p99Ms": round(_pct(vals, 0.99) * 1e3, 3)}
    return out


def summarize_workload(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Per-model request/row counts plus p50/p95/p99 of every recorded
    latency phase — the decomposed-latency summary replay re-emits."""
    models: Dict[str, Dict[str, Any]] = {}
    phase_samples: Dict[str, Dict[str, List[float]]] = {}
    for r in doc.get("records", ()):
        m = r.get("model", "?")
        ent = models.setdefault(m, {"requests": 0, "rows": 0,
                                    "failed": 0})
        ent["requests"] += 1
        ent["rows"] += int(r.get("rows") or 0)
        if not (r.get("outcome") or {}).get("ok", True):
            ent["failed"] += 1
        for ph, v in (r.get("phases") or {}).items():
            phase_samples.setdefault(m, {}).setdefault(ph, []).append(
                float(v))
    for m, ent in models.items():
        ent["phases"] = _phase_pcts(phase_samples.get(m, {}))
    dur = max((r["tS"] for r in doc.get("records", ())), default=0.0)
    return {"requests": sum(e["requests"] for e in models.values()),
            "durationS": round(dur, 3), "models": models}


# ---------------------------------------------------------------------------
# replay — open-loop re-drive against a live server/fleet
# ---------------------------------------------------------------------------

def _max_numeric_delta(a: Any, b: Any) -> float:
    """Largest absolute numeric difference between two JSON-shaped
    values of identical structure; +inf on any structural mismatch."""
    if isinstance(a, bool) or isinstance(b, bool):
        return 0.0 if a == b else float("inf")
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return abs(float(a) - float(b))
    if isinstance(a, dict) and isinstance(b, dict):
        if set(a) != set(b):
            return float("inf")
        return max((_max_numeric_delta(a[k], b[k]) for k in a),
                   default=0.0)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return float("inf")
        return max((_max_numeric_delta(x, y) for x, y in zip(a, b)),
                   default=0.0)
    return 0.0 if a == b else float("inf")


def _post_score(host: str, port: int, model: str, payload: Any,
                timeout_s: float) -> Tuple[int, Dict[str, Any]]:
    body = json.dumps({"records": payload}).encode()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("POST", f"/v1/models/{model}:score", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            doc = json.loads(raw)
        except ValueError:
            doc = {}
        return resp.status, doc
    finally:
        conn.close()


def replay_workload(doc: Dict[str, Any], url: str, speed: float = 1.0,
                    timeout_s: float = 30.0, parity_tol: float = 1e-4,
                    max_in_flight: int = 32,
                    duration_s: Optional[float] = None,
                    max_requests: Optional[int] = None) -> Dict[str, Any]:
    """Re-drive a merged workload open-loop against ``url`` (a serve
    worker or fleet router base URL). Each recorded request fires at
    ``t0 + tS / speed`` regardless of earlier completions — the
    recorded arrival process, not a closed loop. Records without a
    recorded payload (digested over the size cap, or captured with
    ``workloadPayloads=false``) cannot be re-driven and are tallied as
    skipped. Where recorded ``outputs`` exist, the replayed response is
    compared numerically within ``parity_tol`` (score parity).
    ``duration_s``/``max_requests`` truncate the replay — only records
    whose scaled send time falls inside the window (and the first N of
    those) fire — so a tuner candidate leg can bound its cost without
    editing the recording (truncated records tally ``truncated``, not
    skipped). Returns the same decomposed-latency summary shape as
    :func:`summarize_workload`, computed from the replayed responses'
    ``phases`` blocks, so recording and replay diff phase-for-phase."""
    parsed = urllib.parse.urlsplit(url if "//" in url
                                   else "http://" + url)
    host, port = parsed.hostname or "127.0.0.1", parsed.port or 80
    speed = float(speed)
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    todo = [r for r in doc.get("records", ())
            if (r.get("outcome") or {}).get("ok", True)]
    runnable = [r for r in todo if isinstance(r.get("payload"), list)]
    skipped = len(todo) - len(runnable)
    _tally("replay_skipped_no_payload", skipped)
    n_before_cut = len(runnable)
    if duration_s is not None:
        if float(duration_s) <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {duration_s}")
        runnable = [r for r in runnable
                    if float(r.get("tS", 0.0)) / speed
                    <= float(duration_s)]
    if max_requests is not None:
        if int(max_requests) < 1:
            raise ValueError(
                f"max_requests must be >= 1, got {max_requests}")
        runnable = runnable[:int(max_requests)]
    truncated = n_before_cut - len(runnable)
    _tally("replay_truncated", truncated)

    lock = locks.witness_lock("workload.replay.lock")
    phase_samples: Dict[str, Dict[str, List[float]]] = {}
    client_e2e: List[float] = []
    models: Dict[str, Dict[str, Any]] = {}
    stats = {"sent": 0, "failed": 0, "lateSends": 0,
             "parityChecked": 0, "parityFailures": 0,
             "parityMaxAbsDelta": 0.0}
    sem = threading.BoundedSemaphore(int(max_in_flight))
    threads: List[threading.Thread] = []

    def fire(rec: Dict[str, Any]) -> None:
        try:
            t_send = time.perf_counter()
            try:
                status, resp = _post_score(host, port, rec["model"],
                                           rec["payload"], timeout_s)
            except OSError as e:
                status, resp = -1, {"error": repr(e)}
            dt = time.perf_counter() - t_send
            with lock:
                stats["sent"] += 1
                m = rec.get("model", "?")
                ent = models.setdefault(m, {"requests": 0, "rows": 0,
                                            "failed": 0})
                ent["requests"] += 1
                ent["rows"] += int(rec.get("rows") or 0)
                client_e2e.append(dt)
                if status != 200:
                    stats["failed"] += 1
                    ent["failed"] += 1
                    _tally("replay_failures")
                    return
                _tally("replayed_requests")
                for ph, v in (resp.get("phases") or {}).items():
                    phase_samples.setdefault(m, {}).setdefault(
                        ph, []).append(float(v))
                if "outputs" in rec and "outputs" in resp:
                    delta = _max_numeric_delta(rec["outputs"],
                                               resp["outputs"])
                    stats["parityChecked"] += 1
                    _tally("parity_checked")
                    if delta > parity_tol:
                        stats["parityFailures"] += 1
                        _tally("parity_failures")
                    if delta != float("inf"):
                        stats["parityMaxAbsDelta"] = max(
                            stats["parityMaxAbsDelta"], delta)
                    else:
                        stats["parityMaxAbsDelta"] = float("nan")
        finally:
            sem.release()

    t_start = time.perf_counter()
    for rec in runnable:
        due = t_start + float(rec.get("tS", 0.0)) / speed
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        elif delay < -0.05:
            with lock:
                stats["lateSends"] += 1
        sem.acquire()   # bounded in-flight: the open loop degrades
        t = threading.Thread(target=fire, args=(rec,),
                             name="tmog-workload-replay", daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=timeout_s + 5.0)
    wall = time.perf_counter() - t_start

    for m, ent in models.items():
        ent["phases"] = _phase_pcts(phase_samples.get(m, {}))
    if stats["lateSends"]:
        _tally("replay_late_sends", stats["lateSends"])
    return {"requests": len(todo), "skippedNoPayload": skipped,
            "truncated": truncated,
            "speed": speed, "durationS": round(wall, 3),
            "client": _phase_pcts({"e2e": client_e2e}),
            "models": models, **stats}


# ---------------------------------------------------------------------------
# critical-path analyzer — merged traces -> per-phase attribution
# ---------------------------------------------------------------------------

#: span names that root one request's trace (the fleet router's route
#: span when fleet traffic, the worker's request span when direct)
REQUEST_ROOTS = ("fleet:route", "server:request")


def _load_trace(source: Any) -> Dict[str, Any]:
    if isinstance(source, dict):
        return source
    if os.path.isdir(source):
        return telemetry.merge_trace_shards(source)
    with open(source) as fh:
        return json.load(fh)


def _overlap(a0: float, a1: float, b0: float, b1: float) -> float:
    return max(0.0, min(a1, b1) - max(a0, b0))


def analyze_trace(source: Any, top_k: int = 5) -> Dict[str, Any]:
    """Reconstruct each request's critical path from a merged trace
    (a doc, a merged ``.json`` file, or a shard directory — merged
    in-memory). For every trace rooted at a request span
    (:data:`REQUEST_ROOTS`): the root's duration is the request's
    end-to-end time; every span in the trace is attributed its
    SELF-time (duration minus child overlap, clipped to the root
    window); a span LINKED from another span (the micro-batcher's
    ``server:dispatch`` linking its member request spans) donates the
    linking span's overlap to that name — device time lands under
    ``server:dispatch`` even for members whose trace the batch span
    did not adopt. Reports per-span-name p50/p99 self-time and share
    of total e2e, per-request coverage (fraction of e2e attributed to
    named spans), and the top-K slowest requests with their paths."""
    doc = _load_trace(source)
    spans: List[Dict[str, Any]] = []
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if not isinstance(args, dict):
            args = {}
        spans.append({"name": ev.get("name", "?"),
                      "t0": float(ev.get("ts", 0.0)),
                      "dur": float(ev.get("dur", 0.0)),
                      "trace": args.get("trace_id"),
                      "sid": args.get("span_id"),
                      "parent": args.get("parent_span_id"),
                      "links": args.get("links") or []})
    by_sid = {s["sid"]: s for s in spans if s["sid"]}
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        if s["trace"]:
            by_trace.setdefault(s["trace"], []).append(s)
    # linked contributions: span L lists member span ids; each member's
    # TRACE receives overlap(L, member) attributed to L's name
    linked_into: Dict[str, List[Tuple[Dict[str, Any],
                                      Dict[str, Any]]]] = {}
    donor_sids = set()
    for s in spans:
        for target_sid in s["links"]:
            tgt = by_sid.get(target_sid)
            if tgt is None or not tgt["trace"]:
                continue
            if (s["parent"] == target_sid
                    and s["trace"] == tgt["trace"]):
                # the batch span is ALSO a plain child of this member
                # (the trace it adopted): ordinary parent-child
                # accounting covers it — a link donation here would
                # deduct the overlap from the member's self-time TWICE
                continue
            linked_into.setdefault(tgt["trace"], []).append((s, tgt))
            donor_sids.add(s["sid"])

    requests: List[Dict[str, Any]] = []
    agg: Dict[str, List[float]] = {}
    e2e_all: List[float] = []
    skipped = 0
    for tid, tspans in by_trace.items():
        sids = {s["sid"] for s in tspans if s["sid"]}
        roots = [s for s in tspans
                 if not s["parent"] or s["parent"] not in sids]
        roots = [s for s in roots if s["name"] in REQUEST_ROOTS] or None
        if not roots:
            skipped += 1
            continue
        root = min(roots, key=lambda s: s["t0"])
        r0, r1 = root["t0"], root["t0"] + root["dur"]
        e2e = root["dur"]
        if e2e <= 0:
            skipped += 1
            continue
        children: Dict[str, List[Dict[str, Any]]] = {}
        for s in tspans:
            if s["parent"] and s["parent"] in sids and s is not root:
                children.setdefault(s["parent"], []).append(s)
        # linked overlap stolen from each member span's self-time
        link_steal: Dict[str, float] = {}
        link_attr: Dict[str, float] = {}
        for linker, tgt in linked_into.get(tid, ()):
            ov = _overlap(linker["t0"], linker["t0"] + linker["dur"],
                          max(tgt["t0"], r0),
                          min(tgt["t0"] + tgt["dur"], r1))
            if ov > 0 and tgt["sid"]:
                link_steal[tgt["sid"]] = link_steal.get(
                    tgt["sid"], 0.0) + ov
                link_attr[linker["name"]] = link_attr.get(
                    linker["name"], 0.0) + ov
        attribution: Dict[str, float] = dict(link_attr)
        for s in tspans:
            if s["sid"] in donor_sids and s["parent"] not in sids:
                # a batch-level span donates its time to member traces
                # through its links; when it is NOT also parented into
                # this trace, attributing its self-time here too would
                # double-count it in its home trace
                continue
            s0 = max(s["t0"], r0)
            s1 = min(s["t0"] + s["dur"], r1)
            if s1 <= s0:
                continue
            covered = sum(
                _overlap(s0, s1, c["t0"], c["t0"] + c["dur"])
                for c in children.get(s["sid"], ()))
            self_t = max((s1 - s0) - covered
                         - link_steal.get(s["sid"], 0.0), 0.0)
            attribution[s["name"]] = attribution.get(s["name"],
                                                     0.0) + self_t
        covered_frac = min(sum(attribution.values()) / e2e, 1.0)
        # greedy critical path: at each level descend into the child
        # with the largest overlap of the current span
        path = [{"name": root["name"],
                 "ms": round(root["dur"] / 1e3, 3)}]
        path_sids = {root["sid"]}
        cur = root
        while True:
            kids = children.get(cur["sid"], [])
            if not kids:
                break
            cur = max(kids, key=lambda c: _overlap(
                cur["t0"], cur["t0"] + cur["dur"],
                c["t0"], c["t0"] + c["dur"]))
            path.append({"name": cur["name"],
                         "ms": round(cur["dur"] / 1e3, 3)})
            path_sids.add(cur["sid"])
        # a batch span linking ANY member on the path extends it
        # across the coalescing boundary (the micro-batcher's dispatch
        # usually links the request span, not the descent's leaf)
        for linker, tgt in linked_into.get(tid, ()):
            if tgt["sid"] in path_sids:
                path.append({"name": linker["name"],
                             "ms": round(linker["dur"] / 1e3, 3)})
                break
        requests.append({"traceId": tid,
                         "e2eMs": round(e2e / 1e3, 3),
                         "coveredFraction": round(covered_frac, 4),
                         "path": path,
                         "attributionMs": {
                             k: round(v / 1e3, 3)
                             for k, v in sorted(attribution.items())}})
        e2e_all.append(e2e)
        for name, v in attribution.items():
            agg.setdefault(name, []).append(v)

    total_e2e = sum(e2e_all) or 1.0
    phases = {}
    for name, vals in sorted(agg.items()):
        vals_s = sorted(vals)
        phases[name] = {
            "n": len(vals),
            "p50Ms": round(_pct(vals_s, 0.50) / 1e3, 3),
            "p99Ms": round(_pct(vals_s, 0.99) / 1e3, 3),
            "share": round(sum(vals) / total_e2e, 4)}
    e2e_sorted = sorted(e2e_all)
    coverage = [r["coveredFraction"] for r in requests]
    requests.sort(key=lambda r: -r["e2eMs"])
    return {"requests": len(requests), "skippedTraces": skipped,
            "e2e": {"p50Ms": round(_pct(e2e_sorted, 0.50) / 1e3, 3),
                    "p99Ms": round(_pct(e2e_sorted, 0.99) / 1e3, 3)},
            "phases": phases,
            "coverage": {
                "min": round(min(coverage), 4) if coverage else None,
                "mean": round(sum(coverage) / len(coverage), 4)
                if coverage else None},
            "slowest": requests[:max(int(top_k), 0)]}


def diff_analyses(current: Dict[str, Any], baseline: Dict[str, Any],
                  threshold: float = 0.25,
                  abs_floor_ms: float = 0.5) -> Dict[str, Any]:
    """Regression watchdog: compare two :func:`analyze_trace` outputs.
    A phase (or e2e) REGRESSES when its p99 grew by more than
    ``threshold`` (relative) AND ``abs_floor_ms`` (absolute — sub-floor
    jitter on a fast phase is not a regression). Phases present in only
    one analysis are reported as added/removed, never as regressions."""
    verdicts: List[Dict[str, Any]] = []

    def check(name: str, cur: Optional[float],
              base: Optional[float]) -> None:
        if cur is None or base is None:
            verdicts.append({"phase": name,
                             "verdict": ("added" if base is None
                                         else "removed"),
                             "currentP99Ms": cur, "baselineP99Ms": base})
            return
        regressed = (cur > base * (1.0 + threshold)
                     and cur - base > abs_floor_ms)
        verdicts.append({
            "phase": name, "currentP99Ms": cur, "baselineP99Ms": base,
            "deltaMs": round(cur - base, 3),
            "deltaPct": (round((cur - base) / base * 100, 1)
                         if base else None),
            "verdict": "regressed" if regressed else "ok"})

    check("e2e", (current.get("e2e") or {}).get("p99Ms"),
          (baseline.get("e2e") or {}).get("p99Ms"))
    names = set(current.get("phases", {})) | set(
        baseline.get("phases", {}))
    for name in sorted(names):
        check(name,
              (current.get("phases", {}).get(name) or {}).get("p99Ms"),
              (baseline.get("phases", {}).get(name) or {}).get("p99Ms"))
    regressions = sum(1 for v in verdicts
                      if v["verdict"] == "regressed")
    return {"threshold": threshold, "absFloorMs": abs_floor_ms,
            "regressions": regressions, "ok": regressions == 0,
            "verdicts": verdicts}
