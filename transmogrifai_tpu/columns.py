"""Columnar data layer — the TPU-native replacement for Spark DataFrames.

The reference carries rows of boxed feature values through Spark
(``readers/.../DataReader.scala:173-197`` builds ``Row``s per record). On TPU
that is exactly wrong: XLA wants dense, statically-shaped arrays. So the
framework's in-memory dataset is a :class:`ColumnStore` — a dict of named
:class:`Column` objects, each a struct of dense host numpy arrays (values +
validity mask) or, for strings, host object arrays that only ever reach the
device after hashing/indexing.

Device transfer happens at jit boundaries in the workflow runtime; columns
here stay numpy so readers/aggregation/tokenization run on host at full
speed without device round-trips.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Set, Tuple, Type

import numpy as np

from .types import feature_types as ft
from .types.feature_types import ColumnKind, FeatureType

__all__ = [
    "Column", "NumericColumn", "TextColumn", "TextListColumn", "TextSetColumn",
    "RaggedColumn", "GeoColumn", "VectorColumn", "MapColumn", "PredictionColumn",
    "ColumnStore", "column_from_values", "column_from_array",
    "column_of_empty",
]


_KIND_TO_DTYPE = {
    ColumnKind.REAL: np.float64,
    ColumnKind.INTEGRAL: np.int64,
    ColumnKind.BINARY: np.bool_,
}


class Column:
    """Abstract column: ``n_rows`` values of one feature type."""

    ftype: Type[FeatureType]

    def __len__(self) -> int:
        raise NotImplementedError

    def get_boxed(self, i: int) -> FeatureType:
        """Boxed value at row i (slow path: tests/serving only)."""
        return self.ftype(self.get_raw(i))

    def get_raw(self, i: int) -> Any:
        raise NotImplementedError

    def to_list(self) -> List[Any]:
        return [self.get_raw(i) for i in range(len(self))]

    def take(self, indices: np.ndarray) -> "Column":
        raise NotImplementedError


@dataclass
class NumericColumn(Column):
    """Scalar numerics: dense values + validity mask.

    ``values`` is f64/i64/bool [n]; ``mask`` is bool[n], True = present.
    Missing slots hold 0 — compute must always combine with the mask.
    """

    ftype: Type[FeatureType]
    values: np.ndarray
    mask: np.ndarray
    #: index → label mapping when this column holds string-indexed values
    #: (Spark's NominalAttribute metadata analog; consumed by
    #: PredictionDeIndexer and DataCutter)
    labels: Optional[List[str]] = None

    def __post_init__(self):
        assert self.values.shape == self.mask.shape, (self.values.shape, self.mask.shape)

    def __len__(self) -> int:
        return self.values.shape[0]

    def get_raw(self, i: int):
        if not self.mask[i]:
            return None
        v = self.values[i]
        return v.item() if isinstance(v, np.generic) else v

    def take(self, indices: np.ndarray) -> "NumericColumn":
        return NumericColumn(self.ftype, self.values[indices],
                             self.mask[indices], self.labels)

    def astype_float(self) -> np.ndarray:
        return self.values.astype(np.float64)


@dataclass
class TextColumn(Column):
    """Host strings: object[n] of Optional[str]. Never shipped to device raw."""

    ftype: Type[FeatureType]
    values: np.ndarray  # dtype=object

    def __len__(self) -> int:
        return self.values.shape[0]

    def get_raw(self, i: int):
        return self.values[i]

    def take(self, indices: np.ndarray) -> "TextColumn":
        return TextColumn(self.ftype, self.values[indices])

    @property
    def mask(self) -> np.ndarray:
        return np.array([v is not None for v in self.values], dtype=bool)


@dataclass
class TextListColumn(Column):
    ftype: Type[FeatureType]
    values: List[List[str]]

    def __len__(self) -> int:
        return len(self.values)

    def get_raw(self, i: int):
        return self.values[i]

    def take(self, indices: np.ndarray) -> "TextListColumn":
        return TextListColumn(self.ftype, [self.values[int(i)] for i in indices])


@dataclass
class TextSetColumn(Column):
    ftype: Type[FeatureType]
    values: List[Set[str]]

    def __len__(self) -> int:
        return len(self.values)

    def get_raw(self, i: int):
        return self.values[i]

    def take(self, indices: np.ndarray) -> "TextSetColumn":
        return TextSetColumn(self.ftype, [self.values[int(i)] for i in indices])


@dataclass
class RaggedColumn(Column):
    """Ragged numeric lists in CSR layout: flat values + row offsets.

    offsets has n+1 entries; row i is flat[offsets[i]:offsets[i+1]].
    This is the device-friendly encoding of DateList / DateTimeList.
    """

    ftype: Type[FeatureType]
    flat: np.ndarray
    offsets: np.ndarray  # i64[n + 1]

    def __len__(self) -> int:
        return self.offsets.shape[0] - 1

    def get_raw(self, i: int):
        return self.flat[self.offsets[i]:self.offsets[i + 1]].tolist()

    def take(self, indices: np.ndarray) -> "RaggedColumn":
        rows = [self.flat[self.offsets[int(i)]:self.offsets[int(i) + 1]] for i in indices]
        lengths = np.array([len(r) for r in rows], dtype=np.int64)
        offsets = np.concatenate([[0], np.cumsum(lengths)])
        flat = np.concatenate(rows) if rows else np.zeros((0,), self.flat.dtype)
        return RaggedColumn(self.ftype, flat, offsets)


@dataclass
class GeoColumn(Column):
    """Geolocation: f64[n, 3] (lat, lon, accuracy) + mask."""

    ftype: Type[FeatureType]
    values: np.ndarray  # f64[n, 3]
    mask: np.ndarray    # bool[n]

    def __len__(self) -> int:
        return self.values.shape[0]

    def get_raw(self, i: int):
        return self.values[i].tolist() if self.mask[i] else []

    def take(self, indices: np.ndarray) -> "GeoColumn":
        return GeoColumn(self.ftype, self.values[indices], self.mask[indices])


@dataclass
class VectorColumn(Column):
    """Dense feature matrix [n, d] + per-column provenance metadata.

    ``values`` carries the pipeline dtype (f32 — vectorizers compute on
    f32-canonicalized inputs, see ops/vectorizer_base.py); consumers that
    need f64 cast at the point of use.

    ``metadata`` is an ``OpVectorMetadata`` (see vector_metadata.py) — the
    contract consumed by SanityChecker and ModelInsights.
    """

    ftype: Type[FeatureType]
    values: np.ndarray  # [n, d], pipeline dtype (f32)
    metadata: Any = None  # OpVectorMetadata | None

    def __len__(self) -> int:
        return self.values.shape[0]

    @property
    def width(self) -> int:
        return self.values.shape[1]

    def get_raw(self, i: int):
        return self.values[i]

    def take(self, indices: np.ndarray) -> "VectorColumn":
        return VectorColumn(self.ftype, self.values[indices], self.metadata)


@dataclass
class MapColumn(Column):
    """String-keyed map column: struct of per-key subcolumns.

    The key set is discovered from the data (host side); each key's values
    form a child column of the map's element kind. This is the columnar
    answer to the reference's 23 ``OPMap`` types.
    """

    ftype: Type[FeatureType]
    children: Dict[str, Column]
    n_rows: int

    def __len__(self) -> int:
        return self.n_rows

    def get_raw(self, i: int):
        out = {}
        for k, child in self.children.items():
            v = child.get_raw(i)
            if v is None:
                continue
            if isinstance(v, (list, set)) and len(v) == 0:
                continue
            out[k] = v
        return out

    def take(self, indices: np.ndarray) -> "MapColumn":
        return MapColumn(self.ftype,
                         {k: c.take(indices) for k, c in self.children.items()},
                         int(len(indices)))


@dataclass
class PredictionColumn(Column):
    """Model output struct-of-arrays: prediction f64[n], raw/prob f64[n, k]."""

    prediction: np.ndarray       # f64[n]
    raw_prediction: np.ndarray   # f64[n, k] (k may be 0)
    probability: np.ndarray      # f64[n, k]
    ftype: Type[FeatureType] = ft.Prediction

    def __len__(self) -> int:
        return self.prediction.shape[0]

    def get_raw(self, i: int):
        out = {ft.Prediction.PREDICTION_KEY: float(self.prediction[i])}
        for j in range(self.raw_prediction.shape[1]):
            out[f"{ft.Prediction.RAW_PREFIX}{j}"] = float(self.raw_prediction[i, j])
        for j in range(self.probability.shape[1]):
            out[f"{ft.Prediction.PROB_PREFIX}{j}"] = float(self.probability[i, j])
        return out

    def take(self, indices: np.ndarray) -> "PredictionColumn":
        return PredictionColumn(self.prediction[indices],
                                self.raw_prediction[indices],
                                self.probability[indices])


# ---------------------------------------------------------------------------
# Construction from boxed / python values
# ---------------------------------------------------------------------------

def _stock_convert(ftype, base) -> bool:
    """True when ``ftype`` inherits ``base._convert`` unchanged — the gate
    for the bulk (vectorized) conversion fast paths below, which restate
    exactly the stock converters' semantics."""
    return ftype._convert.__func__ is base._convert.__func__


def _bulk_numeric_gate(ftype: Type[FeatureType], kind: ColumnKind) -> bool:
    """True when ``ftype``'s kind/converter admit the bulk numeric path
    — the ONE gate both bulk builders share."""
    return ((kind is ColumnKind.REAL and _stock_convert(ftype, ft.Real))
            or (kind is ColumnKind.INTEGRAL
                and _stock_convert(ftype, ft.Integral)))


def _bulk_numeric_column(ftype: Type[FeatureType], fvals: np.ndarray,
                         kind: ColumnKind) -> Optional[NumericColumn]:
    """The shared masking/round-trip expressions of BOTH bulk numeric
    builders (:func:`column_from_array` and the fast path inside
    :func:`column_from_values`) — one copy, so the 'columnar batch is
    bit-identical to dicts' invariant cannot drift between them.
    NaN = missing; int64 magnitudes beyond 2^53 don't round-trip
    through f64, so those return None for the caller's exact path."""
    mask = ~np.isnan(fvals)
    fvals = np.where(mask, fvals, 0.0)
    dtype = _KIND_TO_DTYPE[kind]
    if dtype == np.float64:
        return NumericColumn(ftype, fvals, mask)
    vals = fvals.astype(dtype)
    if bool((vals == fvals).all()):
        return NumericColumn(ftype, vals, mask)
    return None


def column_from_array(ftype: Type[FeatureType], arr) -> Optional[Column]:
    """Bulk counterpart of :func:`column_from_values` for a numpy column
    (the input pipeline's columnar-decode lane): NaN = missing, bools =
    1/0 — the SAME expressions as the stock-converter fast path below
    (shared via :func:`_bulk_numeric_column`), so a column built here is
    bit-identical to one built from the equivalent python values.
    Returns None when ``ftype`` has no bulk form (custom ``_convert``,
    non-numeric kind) — the caller falls back to the per-record path."""
    kind = ftype.column_kind
    if not _bulk_numeric_gate(ftype, kind):
        return None
    try:
        fvals = np.asarray(arr, dtype=np.float64)
    except (TypeError, ValueError, OverflowError):
        return None
    if fvals.ndim != 1:
        return None
    return _bulk_numeric_column(ftype, fvals, kind)


def column_from_values(ftype: Type[FeatureType], values: Sequence[Any]) -> Column:
    """Build a column from raw python values (None = missing).

    Values may be raw payloads or boxed ``FeatureType`` instances.
    """
    unboxed = [v.value if isinstance(v, FeatureType) else v for v in values]
    kind = ftype.column_kind
    n = len(unboxed)

    if kind in (ColumnKind.REAL, ColumnKind.INTEGRAL, ColumnKind.BINARY):
        dtype = _KIND_TO_DTYPE[kind]
        # bulk fast path for stock converters: one C-speed np.array pass
        # (None → nan, bools → 1/0) replaces n Python _convert frames —
        # at the 300k-row bench ingest this loop alone was seconds/column
        if _bulk_numeric_gate(ftype, kind):
            try:
                fvals = np.array(unboxed, dtype=np.float64)
            except (TypeError, ValueError, OverflowError):
                fvals = None
            if fvals is not None and fvals.shape == (n,):
                col = _bulk_numeric_column(ftype, fvals, kind)
                if col is not None:
                    return col
        vals = np.zeros((n,), dtype=dtype)
        mask = np.zeros((n,), dtype=bool)
        for i, v in enumerate(unboxed):
            bv = ftype._convert(v)
            if bv is not None:
                vals[i] = bv
                mask[i] = True
        return NumericColumn(ftype, vals, mask)

    if kind == ColumnKind.TEXT:
        from .types import feature_types as _ft
        arr = np.empty((n,), dtype=object)
        if _stock_convert(ftype, _ft.Text):
            arr[:] = unboxed
            # str() only the stragglers (a C-speed type scan finds them)
            bad = np.fromiter(
                (v is not None and type(v) is not str for v in unboxed),
                bool, count=n)
            if bad.any():
                for i in np.nonzero(bad)[0]:
                    arr[i] = str(unboxed[i])
            return TextColumn(ftype, arr)
        for i, v in enumerate(unboxed):
            arr[i] = ftype._convert(v)
        return TextColumn(ftype, arr)

    if kind == ColumnKind.TEXT_LIST:
        return TextListColumn(ftype, [ftype._convert(v) for v in unboxed])

    if kind == ColumnKind.TEXT_SET:
        return TextSetColumn(ftype, [ftype._convert(v) for v in unboxed])

    if kind == ColumnKind.INTEGRAL_LIST:
        rows = [ftype._convert(v) for v in unboxed]
        lengths = np.array([len(r) for r in rows], dtype=np.int64)
        offsets = np.concatenate([[0], np.cumsum(lengths)])
        flat = (np.concatenate([np.asarray(r, dtype=np.int64) for r in rows])
                if any(lengths) else np.zeros((0,), np.int64))
        return RaggedColumn(ftype, flat, offsets)

    if kind == ColumnKind.GEO:
        vals = np.zeros((n, 3), dtype=np.float64)
        mask = np.zeros((n,), dtype=bool)
        for i, v in enumerate(unboxed):
            gv = ftype._convert(v)
            if gv:
                vals[i] = gv
                mask[i] = True
        return GeoColumn(ftype, vals, mask)

    if kind == ColumnKind.VECTOR:
        rows = [ftype._convert(v) for v in unboxed]
        widths = {r.shape[0] for r in rows}
        if len(widths) > 1:
            raise ValueError(f"OPVector column with ragged widths {widths}")
        return VectorColumn(ftype, np.stack(rows) if rows else np.zeros((0, 0)))

    if kind == ColumnKind.PREDICTION:
        preds = np.zeros((n,), dtype=np.float64)
        raw_rows, prob_rows = [], []
        for i, v in enumerate(unboxed):
            p = v if isinstance(v, ft.Prediction) else ft.Prediction(v)
            preds[i] = p.prediction
            raw_rows.append(p.raw_prediction)
            prob_rows.append(p.probability)
        k_raw = max((len(r) for r in raw_rows), default=0)
        k_prob = max((len(r) for r in prob_rows), default=0)
        raw = np.zeros((n, k_raw))
        prob = np.zeros((n, k_prob))
        for i in range(n):
            raw[i, :len(raw_rows[i])] = raw_rows[i]
            prob[i, :len(prob_rows[i])] = prob_rows[i]
        return PredictionColumn(preds, raw, prob)

    if kind == ColumnKind.MAP:
        elem_kind = ftype.map_element_kind
        dicts = [ftype._convert(v) for v in unboxed]
        keys = sorted({k for d in dicts for k in d})
        children: Dict[str, Column] = {}
        elem_ftype = ftype.element_type
        for k in keys:
            children[k] = column_from_values(
                elem_ftype, [d.get(k) for d in dicts])
        return MapColumn(ftype, children, n)

    raise NotImplementedError(f"column kind {kind}")


def column_of_empty(ftype: Type[FeatureType], n: int) -> Column:
    return column_from_values(ftype, [None] * n)


# ---------------------------------------------------------------------------
# ColumnStore — the "DataFrame"
# ---------------------------------------------------------------------------

class ColumnStore:
    """Named columns with a shared row count. The framework's dataset object."""

    def __init__(self, columns: Optional[Mapping[str, Column]] = None,
                 n_rows: Optional[int] = None):
        self._columns: Dict[str, Column] = dict(columns or {})
        if n_rows is None:
            lengths = {len(c) for c in self._columns.values()}
            if len(lengths) > 1:
                raise ValueError(f"Mismatched column lengths: {lengths}")
            n_rows = lengths.pop() if lengths else 0
        self.n_rows = n_rows
        for name, c in self._columns.items():
            if len(c) != self.n_rows:
                raise ValueError(
                    f"Column {name!r} has {len(c)} rows, expected {self.n_rows}")

    # -- dict-ish API ------------------------------------------------------
    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> Column:
        return self._columns[name]

    def get(self, name: str) -> Optional[Column]:
        return self._columns.get(name)

    def names(self) -> List[str]:
        return list(self._columns)

    def items(self):
        return self._columns.items()

    def __len__(self) -> int:
        return self.n_rows

    # -- functional updates ------------------------------------------------
    def with_column(self, name: str, column: Column) -> "ColumnStore":
        if len(column) != self.n_rows and self._columns:
            raise ValueError(
                f"Column {name!r} has {len(column)} rows, store has {self.n_rows}")
        cols = dict(self._columns)
        cols[name] = column
        return ColumnStore(cols, self.n_rows if self._columns else len(column))

    def with_columns(self, new: Mapping[str, Column]) -> "ColumnStore":
        store = self
        for k, v in new.items():
            store = store.with_column(k, v)
        return store

    def select(self, names: Iterable[str]) -> "ColumnStore":
        return ColumnStore({n: self._columns[n] for n in names}, self.n_rows)

    def drop(self, names: Iterable[str]) -> "ColumnStore":
        dropset = set(names)
        return ColumnStore(
            {n: c for n, c in self._columns.items() if n not in dropset},
            self.n_rows)

    def take(self, indices: np.ndarray) -> "ColumnStore":
        indices = np.asarray(indices)
        return ColumnStore({n: c.take(indices) for n, c in self._columns.items()},
                          int(indices.shape[0]))

    def filter_mask(self, mask: np.ndarray) -> "ColumnStore":
        return self.take(np.nonzero(np.asarray(mask))[0])

    # -- row access (slow path: serving/tests) -----------------------------
    def row(self, i: int) -> Dict[str, Any]:
        return {n: c.get_raw(i) for n, c in self._columns.items()}

    def rows(self) -> List[Dict[str, Any]]:
        return [self.row(i) for i in range(self.n_rows)]

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_dict(data: Mapping[str, Tuple[Type[FeatureType], Sequence[Any]]]
                  ) -> "ColumnStore":
        """Build from {name: (ftype, values)}."""
        cols = {name: column_from_values(ftype, values)
                for name, (ftype, values) in data.items()}
        return ColumnStore(cols)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}: {c.ftype.__name__}" for n, c in self._columns.items())
        return f"ColumnStore(n_rows={self.n_rows}, [{cols}])"
