"""Staged asynchronous input pipeline — the tf.data analog.

Ingest, not compute, is the measured scoring bottleneck (BENCH_r05:
host→device probed 23 MB/s against the 500 MB/s fusion gate, and
``data_prep_s`` was 16.6 s of a 57.7 s 10M-row run) while ``readers/``
decoded Avro/CSV on a single thread and the "overlapped" streaming
scorer pipelined exactly one batch deep. This module is the staged
pipeline the tf.data paper describes (PAPERS.md) — the building blocks
every ingest path in the runtime now shares:

* **Ordered parallel stage** (:func:`map_ordered`) — a named worker
  pool runs a decode/prepare function over a stream of items
  concurrently while the consumer sees results in EXACT submission
  order (a bounded deque of futures is the reorder buffer — item k is
  yielded only after items 0..k-1, whatever the workers' interleaving).
  Per-item exceptions ride alongside results instead of killing the
  stream, so the resilience layer's quarantine/retry semantics survive
  the move onto worker threads unchanged. In-flight depth is bounded —
  backpressure is explicit, never an unbounded queue (TMG308).
* **Pinned-buffer reuse** (:class:`BufferPool`) — preallocated numpy
  staging buffers keyed by (shape, dtype) and recycled across batches,
  so the pad-to-bucket step of every streaming batch stops allocating
  (and re-faulting) fresh pages per batch; the reuse/alloc split is
  tallied so churn regressions show in bench docs.
* **Autotuned prefetch** (:class:`PrefetchAutotuner`) — the in-flight
  depth starts small, GROWS while the consumer starves (a result was
  not ready when asked for: the pipeline is the bottleneck) and SHRINKS
  when a full tuning window passes with no starvation (depth beyond
  what hides the latency is pure buffer memory) — tf.data's AUTOTUNE
  analog, with the chosen depth observable (``pipeline.prefetch_depth``
  gauge + the always-on tallies).
* **Double-buffered uploads** — the scoring engine stages batch k+1's
  ``device_put`` (``ScoringEngine.stage_batch``) before batch k's
  result is pulled, so the host→device transfer overlaps device
  compute; :func:`probe_sustained_mbps` measures the link through
  exactly that path (pinned buffers, one transfer in flight behind the
  compute) — the SUSTAINED number the fusion gate and the planner's
  cost db now reason with, instead of the cold single-shot probe.

Consumers: ``readers.DirectoryStreamReader.stream(workers=)`` (parallel
file decode), ``scoring.stream_score_overlapped`` (parallel host prep +
staged uploads), ``fitstats._device_moment_bundles`` (double-buffered
chunk uploads in the one-pass scan). Knobs ride in the runner as
``customParams.pipeline`` / ``pipelineWorkers`` / ``pipelineDepth``
(docs/performance.md "Input pipeline").

Everything here is host-side python/numpy plus ``jax.device_put`` — no
new dependencies, no device compute.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple, TypeVar)

import numpy as np

from . import telemetry

logger = logging.getLogger(__name__)

__all__ = [
    "DEFAULT_WORKERS", "MIN_PREFETCH", "DEFAULT_MAX_PREFETCH",
    "resolve_workers", "concrete_batch", "map_ordered",
    "BufferPool", "PrefetchAutotuner", "SeededRowSample",
    "probe_sustained_mbps",
    "pipeline_stats", "reset_pipeline_stats",
]

#: default decode/prep worker count: enough to hide host decode behind
#: device compute without oversubscribing small hosts
DEFAULT_WORKERS = max(1, min(4, (os.cpu_count() or 2) - 1))

#: prefetch depth floor — one batch computing + one in flight is the
#: minimum that overlaps at all
MIN_PREFETCH = 2

#: prefetch depth ceiling: beyond it the autotuner never grows (each
#: unit of depth pins one decoded+padded batch in host memory)
DEFAULT_MAX_PREFETCH = 8

#: ``TMOG_PIPELINE=0`` forces every consumer back to the single-thread
#: ingest path (kill switch, the TMOG_FITSTATS discipline)
PIPELINE_ENABLED = os.environ.get("TMOG_PIPELINE", "1") != "0"


def concrete_batch(batch):
    """A re-iterable batch: columnar batches (avro.ColumnarRecords —
    already concrete, and listifying one would undo the vectorized
    decode by materializing every dict) and lists/tuples pass through;
    one-shot iterables materialize."""
    if hasattr(batch, "columns") or isinstance(batch, (list, tuple)):
        return batch
    return list(batch)


def resolve_workers(workers: Optional[int]) -> int:
    """The effective worker count for a pipeline stage: the
    ``TMOG_PIPELINE=0`` kill switch forces 1 for EVERY consumer (even
    an explicit ``pipelineWorkers`` — the incident lever must not be
    overridable from a params file), else an explicit value wins
    (floored at 1) and None means the module default."""
    if not PIPELINE_ENABLED:
        return 1
    if workers is not None:
        return max(1, int(workers))
    return DEFAULT_WORKERS


# ---------------------------------------------------------------------------
# always-on tallies (bench/runner stamp these on every doc; telemetry
# mirrors the interesting ones as counters/gauges when enabled)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY: Dict[str, Any] = {
    "streams": 0, "batches": 0, "starvations": 0,
    "prefetch_grows": 0, "prefetch_shrinks": 0,
    "buffer_allocs": 0, "buffer_reuses": 0,
    "staged_uploads": 0,
    "decode_vectorized": 0, "decode_fallback": 0,
    "last_workers": 0, "last_prefetch_depth": 0,
    "sustained_mbps": None,
}


def pipeline_stats() -> Dict[str, Any]:
    """Snapshot of the process-wide input-pipeline tallies (always on —
    the ``fitstats_stats`` discipline, cheap enough to never turn off).
    ``last_prefetch_depth`` is the depth the autotuner converged to on
    the most recent stream; ``sustained_mbps`` the last pinned-buffer
    double-buffered bandwidth measurement (None before any probe)."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_pipeline_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = None if k == "sustained_mbps" else 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


def _tally_set(key: str, v: Any) -> None:
    with _TALLY_LOCK:
        _TALLY[key] = v


# ---------------------------------------------------------------------------
# pinned-buffer pool
# ---------------------------------------------------------------------------


class BufferPool:
    """Reusable preallocated numpy staging buffers keyed by
    (shape, dtype).

    ``take`` returns a buffer with UNSPECIFIED contents (callers
    overwrite every element — the pad helpers fill ``[:n]`` with data
    and zero ``[n:]``); ``give`` recycles it. Per-key free lists are
    bounded so a shape that appears once (the odd tail bucket) cannot
    pin memory forever. Thread-safe: prep workers take concurrently
    while the consumer gives back.

    The point is allocation churn, not correctness: padding every
    streaming batch to its bucket used to ``np.zeros`` + concatenate
    fresh arrays per block per batch. With the pool, the steady state
    allocates ~(prefetch depth × blocks) buffers once and then recycles
    — the ``buffer_reuses`` / ``buffer_allocs`` tallies make a churn
    regression visible in every bench doc."""

    def __init__(self, max_per_key: int = 16):
        self.max_per_key = int(max_per_key)
        self.reuses = 0
        self.allocs = 0
        self._free: Dict[Tuple, List[np.ndarray]] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(shape: Tuple[int, ...], dtype) -> Tuple:
        return (tuple(int(s) for s in shape), np.dtype(dtype).str)

    def take(self, shape: Tuple[int, ...], dtype) -> np.ndarray:
        """A writable buffer of exactly ``shape``/``dtype`` — recycled
        when one is free, freshly allocated otherwise."""
        key = self._key(shape, dtype)
        with self._lock:
            free = self._free.get(key)
            if free:
                buf = free.pop()
                self.reuses += 1
                _tally("buffer_reuses")
                return buf
            self.allocs += 1
        _tally("buffer_allocs")
        telemetry.counter("pipeline.buffer_allocs").inc()
        return np.empty(shape, dtype)

    def give(self, buf: np.ndarray) -> None:
        """Recycle ``buf``. The caller must no longer read or write it
        — the next ``take`` hands it to another batch."""
        key = self._key(buf.shape, buf.dtype)
        with self._lock:
            free = self._free.setdefault(key, [])
            if len(free) < self.max_per_key:
                free.append(buf)

    def pad_rows(self, a: np.ndarray, n: int, bucket: int,
                 taken: List[np.ndarray]) -> np.ndarray:
        """Zero-pad the leading (row) axis of ``a`` from ``n`` to
        ``bucket`` into a pooled buffer, appending it to ``taken`` so
        the caller can recycle after the batch is consumed. Blocks
        whose leading dim is not the row count (fitted constants) and
        already-full buckets pass through untouched — exactly the
        ``ScoringEngine._pad_rows`` contract, same values bit-for-bit."""
        a = np.asarray(a)
        if a.ndim == 0 or a.shape[0] != n or n == bucket:
            return a
        out = self.take((bucket,) + a.shape[1:], a.dtype)
        out[:n] = a
        out[n:] = 0
        taken.append(out)
        return out

    def free_buffers(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._free.values())


# ---------------------------------------------------------------------------
# autotuned prefetch depth
# ---------------------------------------------------------------------------


class PrefetchAutotuner:
    """tf.data-AUTOTUNE analog for the in-flight batch depth.

    The depth bounds how many items :func:`map_ordered` keeps submitted
    ahead of the consumer. Tuning runs on a fixed window of consumed
    batches:

    * any **starvation** in the window (the consumer asked for a result
      that was not ready — the pipeline, not the device, was the
      bottleneck) grows the depth by one, up to ``max_depth``;
    * two consecutive starvation-free windows shrink it by one, down to
      ``min_depth`` — depth beyond what hides the latency is pure
      buffer memory (each unit pins one decoded+padded batch), so the
      tuner backs off under the implicit memory pressure instead of
      camping at the ceiling.

    The chosen depth is observable: the ``pipeline.prefetch_depth``
    gauge tracks every change and the always-on tallies record the
    final depth plus the grow/shrink/starvation counts that explain it.
    """

    def __init__(self, min_depth: int = MIN_PREFETCH,
                 max_depth: int = DEFAULT_MAX_PREFETCH,
                 window: int = 4):
        if max_depth < min_depth:
            # an explicit cap below the floor wins (pipelineDepth: 1 is
            # the sanctioned way to force serial prefetch)
            min_depth = max_depth
        self.min_depth = int(min_depth)
        self.max_depth = int(max_depth)
        self.window = max(1, int(window))
        self._depth = self.min_depth
        self._batches = 0
        self._starved = 0
        self._calm_windows = 0
        self.starvations = 0
        self.grows = 0
        self.shrinks = 0
        self._lock = threading.Lock()
        telemetry.gauge("pipeline.prefetch_depth").set(self._depth)

    def depth(self) -> int:
        return self._depth

    def record_starvation(self) -> None:
        with self._lock:
            self._starved += 1
            self.starvations += 1
        _tally("starvations")
        telemetry.counter("pipeline.starvations").inc()

    def on_batch(self) -> None:
        """One batch consumed; closes a tuning window every
        ``window`` batches."""
        with self._lock:
            self._batches += 1
            if self._batches % self.window:
                return
            if self._starved:
                self._calm_windows = 0
                if self._depth < self.max_depth:
                    self._depth += 1
                    self.grows += 1
                    _tally("prefetch_grows")
            else:
                self._calm_windows += 1
                if self._calm_windows >= 2 and self._depth > self.min_depth:
                    self._depth -= 1
                    self.shrinks += 1
                    self._calm_windows = 0
                    _tally("prefetch_shrinks")
            self._starved = 0
        telemetry.gauge("pipeline.prefetch_depth").set(self._depth)


# ---------------------------------------------------------------------------
# ordered parallel map — the decode/prep stage
# ---------------------------------------------------------------------------

_T = TypeVar("_T")


def map_ordered(fn: Callable[[_T], Any], items: Iterable[_T],
                workers: Optional[int] = None,
                tuner: Optional[PrefetchAutotuner] = None,
                name: str = "pipeline",
                executor: Optional[Any] = None
                ) -> Iterator[Tuple[_T, Any, Optional[BaseException]]]:
    """Run ``fn`` over ``items`` on a named worker pool, yielding
    ``(item, result, exception)`` in EXACT submission order.

    The deque of in-flight futures is the reorder buffer: results
    complete in any order on the workers, but the consumer always pops
    the oldest submission first, so N-worker output is bit-identical
    (in content AND order) to the serial loop. A failing item yields
    its exception instead of raising — the caller owns the poison
    policy (quarantine / re-raise), same as the serial paths.

    In-flight depth is ``tuner.depth()`` when a tuner is attached
    (autotuned prefetch with explicit backpressure: the upstream
    iterator is only advanced when a slot frees), else ``workers + 1``.
    A consumer that stops iterating mid-stream cancels everything still
    queued — items never submitted are simply not consumed, which is
    what lets ``max_batches`` leave unread files re-offered.

    Live sources (anything but an in-memory sequence) are advanced on a
    dedicated feeder thread: ``next(it)`` on a directory stream between
    file arrivals can block a full poll interval, and a batch that
    finished DURING that block must not sit in the reorder buffer
    behind it — the consumer only ever waits on the oldest future, so
    results flow the moment they are ready however sparse the source.
    An exception out of the source itself (not an item) is re-raised to
    the consumer after every batch submitted before it has been
    yielded.

    ``executor`` lets a long-lived caller (the directory stream's poll
    loop) reuse one pool across many ``map_ordered`` calls instead of
    paying thread spin-up/teardown per call; a caller-owned executor is
    never shut down here."""
    from concurrent.futures import ThreadPoolExecutor

    n_workers = resolve_workers(workers)
    tel = telemetry.enabled()
    own_ex = executor is None
    ex = executor if executor is not None else ThreadPoolExecutor(
        max_workers=n_workers, thread_name_prefix=name)
    dq: deque = deque()
    cv = threading.Condition()
    state: Dict[str, Any] = {"exhausted": False, "stop": False,
                             "error": None}
    it = iter(items)
    live = not isinstance(items, (list, tuple))

    def _depth() -> int:
        return tuner.depth() if tuner is not None else n_workers + 1

    def _gauge() -> None:
        if tel:
            telemetry.gauge("pipeline.queue_depth").set(len(dq))

    def _feed_live() -> None:
        # runs on the feeder thread; dq/state mutations only under cv
        try:
            while True:
                with cv:
                    while not state["stop"] and len(dq) >= _depth():  # lint: thread-loop — bare cv-wait inside the function-wide try/finally (exhausted flag always set)
                        cv.wait()
                    if state["stop"]:
                        return
                    # while the feeder is inside next(it) the SOURCE is
                    # the limiter — a consumer starving then must not
                    # grow the prefetch depth (extra depth cannot make
                    # files arrive faster); cleared on submit, and left
                    # set on source exhaustion for the same reason
                    state["source_wait"] = True
                try:
                    item = next(it)
                except StopIteration:
                    return
                except BaseException as e:  # lint: broad-except — a source failure rides to the consumer, after in-flight items
                    state["error"] = e
                    return
                with cv:
                    state["source_wait"] = False
                    if state["stop"]:
                        return
                    dq.append((item, ex.submit(fn, item)))
                    _gauge()
                    cv.notify_all()
        finally:
            with cv:
                state["exhausted"] = True
                cv.notify_all()

    def _top_up_inline() -> None:
        # sequence source: next() cannot block, so feed from the
        # consumer and skip the feeder thread entirely
        while not state["exhausted"] and len(dq) < _depth():
            try:
                item = next(it)
            except StopIteration:
                state["exhausted"] = True
                break
            dq.append((item, ex.submit(fn, item)))
        _gauge()

    if live:
        threading.Thread(target=_feed_live, name=f"{name}-feeder",
                         daemon=True).start()
    first_pop = True
    try:
        while True:
            src_bound = False
            if live:
                with cv:
                    while not dq and not state["exhausted"]:
                        cv.wait()
                    if not dq:
                        break
                    item, fut = dq.popleft()
                    src_bound = state.get("source_wait", False)
                    _gauge()
                    cv.notify_all()        # a slot freed for the feeder
            else:
                _top_up_inline()
                if not dq:
                    break
                item, fut = dq.popleft()
                _gauge()
            # the first pop lands microseconds after the first submit
            # and is ~always unfinished — that's cold start, not "the
            # pipeline is the bottleneck", so it must not count as a
            # starvation (it would grow the depth and pollute the
            # tallies on EVERY stream, balanced or not)
            if tuner is not None and not fut.done() and not first_pop \
                    and not src_bound:
                tuner.record_starvation()
            first_pop = False
            try:
                res, exc = fut.result(), None
            except BaseException as e:  # lint: broad-except — per-item failures ride to the caller's poison policy
                res, exc = None, e
            yield item, res, exc
            if tuner is not None:
                tuner.on_batch()
                if live:
                    with cv:
                        cv.notify_all()    # depth may have grown
        if state["error"] is not None:
            raise state["error"]
    finally:
        with cv:
            state["stop"] = True
            cv.notify_all()
        for _item, fut in dq:
            fut.cancel()
        if own_ex:
            ex.shutdown(wait=False)


# ---------------------------------------------------------------------------
# sustained-bandwidth probe (the double-buffered path's number)
# ---------------------------------------------------------------------------


def probe_sustained_mbps(n_transfers: int = 8,
                         buf_mb: int = 4) -> float:
    """Host→device bandwidth (MB/s) through the pipeline's own path:
    TWO pinned (reused) 4 MB staging buffers, each transfer issued
    while the previous one is still in flight — the double-buffered
    discipline ``ScoringEngine.stage_batch`` runs, so this is the rate
    streaming ingest actually sustains, not the cold single-shot
    round-trip ``telemetry.probe_device_roundtrip_mbps`` measures
    (23 MB/s vs the 500 MB/s gate in BENCH_r05 — the number that kept
    the fusion gate OFF). Measures on every call;
    ``workflow.device_roundtrip_mbps`` owns the once-per-process cache.

    One h2d direction only: the overlapped scorer pulls results once
    per batch but uploads the (much wider) prepared feature blocks —
    upload is the direction the gate is about."""
    import jax

    n_elems = (buf_mb << 20) // 4
    bufs = [np.zeros((n_elems,), np.float32) for _ in range(2)]
    # warm-up absorbs backend init / dispatch compilation
    jax.block_until_ready(jax.device_put(bufs[0]))
    nbytes = bufs[0].nbytes
    with telemetry.span("pipeline:sustained_probe",
                        bytes=n_transfers * nbytes):
        t0 = time.perf_counter()
        prev = None
        for i in range(n_transfers):
            # reusing buffer i % 2 is safe: its previous transfer
            # (i - 2) was blocked on at iteration i - 1
            cur = jax.device_put(bufs[i % 2])
            if prev is not None:
                jax.block_until_ready(prev)
            prev = cur
        jax.block_until_ready(prev)
        dt = max(time.perf_counter() - t0, 1e-9)
    mbps = (n_transfers * nbytes / 1e6) / dt
    _tally_set("sustained_mbps", round(mbps, 1))
    telemetry.gauge("device.sustained_mbps").set(mbps)
    logger.info("sustained host->device bandwidth (double-buffered, "
                "pinned reuse): %.0f MB/s", mbps)
    return mbps


# ---------------------------------------------------------------------------
# stream bookkeeping shared by the pipelined consumers
# ---------------------------------------------------------------------------


def record_stream(n_batches: int, workers: int,
                  tuner: Optional[PrefetchAutotuner] = None,
                  pool: Optional[BufferPool] = None) -> None:
    """Fold one finished pipelined stream into the always-on tallies
    and emit the ``on_pipeline_stats`` RunListener event — the
    OpSparkListener-style summary row the runner's metrics doc and the
    bench stamp (docs/observability.md)."""
    _tally("streams")
    _tally("batches", n_batches)
    _tally_set("last_workers", int(workers))
    depth = tuner.depth() if tuner is not None else 0
    if tuner is not None:
        _tally_set("last_prefetch_depth", depth)
    telemetry.counter("pipeline.batches").inc(n_batches)
    telemetry.emit(
        "pipeline_stats", batches=n_batches, workers=int(workers),
        prefetch_depth=depth,
        starvations=tuner.starvations if tuner is not None else 0,
        buffer_reuses=pool.reuses if pool is not None else 0,
        buffer_allocs=pool.allocs if pool is not None else 0)


# ---------------------------------------------------------------------------
# deterministic bounded row subsample (out-of-core training)
# ---------------------------------------------------------------------------


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 — a bijection, so
    distinct row indices always get distinct priorities."""
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class SeededRowSample:
    """Deterministic bounded row subsample over a stream of batches —
    the out-of-core stand-in for the quantile sketch's seeded
    permutation (models/_treefit.quantile_bin_edges): keep the ``k``
    rows whose seeded hash priority is smallest.

    Each row's priority is a pure function of its GLOBAL index in the
    concatenated stream and the seed — independent of batch boundaries,
    worker counts and whether the data was streamed or materialized
    (``map_ordered`` delivers batches in submission order, so the
    global index is stable). The working set is bounded at ~2k buffered
    rows; ``result()`` returns the selected rows in global-row order,
    so for n <= k the sample IS the stream, in order.

    Protocol per batch: ``loc = offer(len(batch))`` gives the LOCAL
    indices of candidate rows (priority under the current running
    cutoff); the caller gathers those rows and hands them to
    ``keep(rows)`` in the same order.
    """

    def __init__(self, k: int, seed: int = 0x51EED):
        if k < 1:
            raise ValueError("sample size k must be >= 1")
        self.k = int(k)
        self.seed = int(seed)
        self._n = 0
        self._cut: Optional[int] = None
        self._buf: List[Tuple[int, int, Any]] = []
        self._pending: Tuple[np.ndarray, np.ndarray] = (
            np.empty(0, np.uint64), np.empty(0, np.uint64))

    @property
    def total_rows(self) -> int:
        return self._n

    def offer(self, n_rows: int) -> np.ndarray:
        """Local candidate indices for the next ``n_rows`` rows."""
        n_rows = int(n_rows)
        g0 = self._n
        self._n += n_rows
        gidx = np.arange(g0, self._n, dtype=np.uint64)
        pri = _splitmix64(
            gidx + np.uint64((self.seed * 0x9E3779B97F4A7C15)
                             & 0xFFFFFFFFFFFFFFFF))
        if self._cut is not None:
            loc = np.nonzero(pri <= np.uint64(self._cut))[0]
        else:
            loc = np.arange(n_rows)
        self._pending = (pri[loc], gidx[loc])
        return loc

    def keep(self, rows: Sequence[Any]) -> None:
        """Buffer the rows matching the last ``offer``'s candidates."""
        pri, gidx = self._pending
        self._pending = (np.empty(0, np.uint64), np.empty(0, np.uint64))
        self._buf.extend(zip(pri.tolist(), gidx.tolist(), rows))
        if len(self._buf) > 2 * self.k:
            self._compact()

    def _compact(self) -> None:
        # keep the k smallest (priority, index) pairs; the kth becomes
        # the pruning cutoff for future offers
        self._buf.sort(key=lambda t: (t[0], t[1]))
        del self._buf[self.k:]
        if len(self._buf) >= self.k:
            self._cut = self._buf[-1][0]

    def result(self) -> List[Any]:
        """The selected rows, in global-row (stream) order."""
        self._compact()
        return [row for _, _, row in
                sorted(self._buf, key=lambda t: t[1])]
