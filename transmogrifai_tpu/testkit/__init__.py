"""Testkit — deterministic random typed-data generators.

Parity: the reference publishes a ``testkit`` module of random feature-type
generators (``testkit/src/main/scala/com/salesforce/op/testkit/RandomText.scala:1``,
``RandomReal``, ``RandomList``, ``RandomMap``, …) with a
``ProbabilityOfEmpty`` knob and deterministic streams, used by vectorizer
and checker tests. This is the columnar analog: every generator yields raw
Python values (``None`` = missing) and can materialize a
:class:`~transmogrifai_tpu.columns.Column` directly.

Usage::

    col = RandomData.reals(mean=1.0).with_prob_empty(0.2).column(Real, 100)
    vals = RandomData.texts().take(50, seed=7)
"""
from __future__ import annotations

import string
from dataclasses import dataclass, field, replace
from typing import Any, Callable, List, Optional, Sequence, Type

import numpy as np

from ..columns import Column, column_from_values
from ..types.feature_types import FeatureType

__all__ = ["RandomData"]

_WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india juliet "
          "kilo lima mike november oscar papa quebec romeo sierra tango "
          "uniform victor whiskey xray yankee zulu").split()


@dataclass
class RandomData:
    """A sampler of one value kind with a probability of empty."""

    sampler: Callable[[np.random.Generator], Any]
    probability_of_empty: float = 0.0

    # -- stream ------------------------------------------------------------
    def with_prob_empty(self, p: float) -> "RandomData":
        return replace(self, probability_of_empty=p)

    def take(self, n: int, seed: int = 42) -> List[Any]:
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            if (self.probability_of_empty > 0
                    and rng.random() < self.probability_of_empty):
                out.append(None)
            else:
                out.append(self.sampler(rng))
        return out

    def column(self, ftype: Type[FeatureType], n: int,
               seed: int = 42) -> Column:
        return column_from_values(ftype, self.take(n, seed))

    # -- factories (RandomReal / RandomText / … analogs) -------------------
    @staticmethod
    def reals(mean: float = 0.0, sigma: float = 1.0) -> "RandomData":
        return RandomData(lambda r: float(r.normal(mean, sigma)))

    @staticmethod
    def integrals(low: int = 0, high: int = 100) -> "RandomData":
        return RandomData(lambda r: int(r.integers(low, high)))

    @staticmethod
    def binaries(p: float = 0.5) -> "RandomData":
        return RandomData(lambda r: bool(r.random() < p))

    @staticmethod
    def texts(n_words: int = 3, vocab: Sequence[str] = _WORDS) -> "RandomData":
        return RandomData(lambda r: " ".join(
            r.choice(vocab) for _ in range(max(1, int(r.integers(
                1, n_words + 1))))))

    @staticmethod
    def unique_texts(length: int = 8) -> "RandomData":
        chars = np.array(list(string.ascii_lowercase))
        return RandomData(lambda r: "".join(r.choice(chars, length)))

    @staticmethod
    def picklists(domain: Sequence[str] = ("red", "green", "blue", "teal")
                  ) -> "RandomData":
        return RandomData(lambda r: str(r.choice(list(domain))))

    @staticmethod
    def text_lists(max_len: int = 4, vocab: Sequence[str] = _WORDS
                   ) -> "RandomData":
        return RandomData(lambda r: [str(r.choice(vocab)) for _ in
                                     range(int(r.integers(0, max_len + 1)))])

    @staticmethod
    def multi_picklists(domain: Sequence[str] = ("a", "b", "c", "d"),
                        max_len: int = 3) -> "RandomData":
        return RandomData(lambda r: {
            str(v) for v in r.choice(list(domain),
                                     int(r.integers(0, max_len + 1)),
                                     replace=False)})

    @staticmethod
    def real_maps(keys: Sequence[str] = ("k1", "k2", "k3")) -> "RandomData":
        def sample(r):
            return {k: float(r.normal()) for k in keys
                    if r.random() < 0.8}
        return RandomData(sample)

    @staticmethod
    def text_maps(keys: Sequence[str] = ("k1", "k2"),
                  domain: Sequence[str] = ("x", "y", "z")) -> "RandomData":
        def sample(r):
            return {k: str(r.choice(list(domain))) for k in keys
                    if r.random() < 0.8}
        return RandomData(sample)

    @staticmethod
    def geolocations() -> "RandomData":
        return RandomData(lambda r: (float(r.uniform(-90, 90)),
                                     float(r.uniform(-180, 180)), 5.0))

    @staticmethod
    def dates(start_ms: int = 1_400_000_000_000,
              span_ms: int = 200_000_000_000) -> "RandomData":
        return RandomData(lambda r: int(start_ms + r.integers(0, span_ms)))

    @staticmethod
    def date_lists(max_len: int = 3,
                   start_ms: int = 1_400_000_000_000) -> "RandomData":
        return RandomData(lambda r: [
            int(start_ms + r.integers(0, 100_000_000_000))
            for _ in range(int(r.integers(0, max_len + 1)))])

    @staticmethod
    def vectors(dim: int = 4) -> "RandomData":
        return RandomData(lambda r: r.normal(size=dim))
