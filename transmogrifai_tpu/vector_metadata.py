"""Vector column provenance — the contract between vectorizers, SanityChecker
and ModelInsights.

Mirrors ``utils``' ``OpVectorColumnMetadata`` / ``OpVectorMetadata``
(``features/.../utils/spark/OpVectorColumnMetadata.scala:67-75``,
``OpVectorMetadata.scala``): every column of every feature vector records
which raw feature produced it, its feature type, an optional grouping (e.g.
the pivot value group or map key), an optional indicator value (one-hot
category), and an optional descriptor (e.g. "x" / "y" for unit-circle dates).
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["VectorColumnMetadata", "VectorMetadata"]

NULL_INDICATOR = "NullIndicatorValue"
OTHER_INDICATOR = "OTHER"


@dataclass(frozen=True)
class VectorColumnMetadata:
    """Provenance of one slot in a feature vector."""

    parent_feature_name: str
    parent_feature_type: str
    grouping: Optional[str] = None        # pivot group / map key
    indicator_value: Optional[str] = None  # one-hot category value
    descriptor_value: Optional[str] = None  # e.g. unit-circle "x"/"y"
    index: int = 0                         # slot in the combined vector

    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_INDICATOR

    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_INDICATOR

    def column_name(self) -> str:
        parts = [self.parent_feature_name]
        if self.grouping is not None:
            parts.append(self.grouping)
        if self.indicator_value is not None:
            parts.append(self.indicator_value)
        if self.descriptor_value is not None:
            parts.append(self.descriptor_value)
        return "_".join(parts) + f"_{self.index}"

    def with_index(self, index: int) -> "VectorColumnMetadata":
        return VectorColumnMetadata(
            self.parent_feature_name, self.parent_feature_type, self.grouping,
            self.indicator_value, self.descriptor_value, index)

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorColumnMetadata":
        return VectorColumnMetadata(**d)


@dataclass
class VectorMetadata:
    """Metadata for a whole OPVector column: ordered per-slot provenance."""

    name: str
    columns: List[VectorColumnMetadata] = field(default_factory=list)

    def __post_init__(self):
        self.columns = [c.with_index(i) for i, c in enumerate(self.columns)]

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.column_name() for c in self.columns]

    def parent_features(self) -> List[str]:
        seen, out = set(), []
        for c in self.columns:
            if c.parent_feature_name not in seen:
                seen.add(c.parent_feature_name)
                out.append(c.parent_feature_name)
        return out

    def indices_of_parent(self, parent: str) -> List[int]:
        return [c.index for c in self.columns if c.parent_feature_name == parent]

    @staticmethod
    def flatten(name: str, metas: Sequence["VectorMetadata"]) -> "VectorMetadata":
        """Concatenate vector metadatas in order (VectorsCombiner semantics)."""
        cols: List[VectorColumnMetadata] = []
        for m in metas:
            cols.extend(m.columns)
        return VectorMetadata(name, cols)

    def select(self, indices: Sequence[int]) -> "VectorMetadata":
        """Keep only the given slots (SanityChecker column dropping)."""
        return VectorMetadata(self.name, [self.columns[i] for i in indices])

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorMetadata":
        return VectorMetadata(
            d["name"], [VectorColumnMetadata.from_json(c) for c in d["columns"]])
