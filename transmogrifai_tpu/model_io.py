"""Model persistence — the ``op-model.json`` analog.

Mirrors ``OpWorkflowModelWriter``/``Reader``
(``core/.../OpWorkflowModelWriter.scala:75-146``, ``OpWorkflowModelReader.scala``):
one ``model.json`` holding the workflow uid, result-feature uids, the
topologically-sorted feature graph and stage descriptors (class + ctor
params + JSON state), plus one ``weights.npz`` holding every stage's numeric
arrays. Stages are reconstructed from ``STAGE_REGISTRY`` by class name, the
feature graph is rebuilt topologically, and fitted models are rebound by uid
— which is also what powers warm-starting (``OpWorkflow.withModelStages``).
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from .columns import ColumnStore
from .features import Feature
from .graph import compute_dag
from .stages.base import FittedModel, OpPipelineStage, STAGE_REGISTRY, Transformer
from .stages.generator import FeatureGeneratorStage
from .types.feature_types import FeatureType, feature_type_by_name
from .vector_metadata import VectorMetadata

MODEL_JSON = "model.json"
WEIGHTS_NPZ = "weights.npz"
#: v2: weights live in a save-unique ``weights-<id>.npz`` referenced by
#: model.json's ``weightsFile`` (crash-consistent overwrites); v1 saves
#: (fixed weights.npz) still load via the legacy branch
FORMAT_VERSION = 2


import functools


@functools.lru_cache(maxsize=None)
def _codec_bases():
    """Config-object families encoded structurally (ctor-arg capture):
    model families, splitters, validators (an unfitted ModelSelector's
    params — reached by feature-graph serialization and layer
    checkpoints). Cached: this runs per encoded leaf."""
    from .models.base import ModelFamily
    from .models.tuning import Splitter, _ValidatorBase
    return (ModelFamily, Splitter, _ValidatorBase)


def _encode_obj(v: Any, arrays: Dict[str, np.ndarray], prefix: str) -> Any:
    import inspect
    cls = type(v)
    sig = inspect.signature(cls.__init__)
    params = {}
    for name, p in sig.parameters.items():
        if name in ("self", "mesh") or p.kind is p.VAR_POSITIONAL:
            continue
        if p.kind is p.VAR_KEYWORD:
            # **kwargs conventionally stored under the parameter's name
            # (ModelFamily's **fixed → self.fixed)
            kw = getattr(v, name, None)
            if isinstance(kw, dict) and kw:
                params["__var_kw__"] = _encode_param(kw, arrays, prefix)
            continue
        if hasattr(v, name):
            params[name] = _encode_param(getattr(v, name), arrays, prefix)
    return {"__obj__": f"{cls.__module__}:{cls.__qualname__}",
            "params": params}


def _encode_param(v: Any, arrays: Dict[str, np.ndarray], prefix: str) -> Any:
    if isinstance(v, type) and issubclass(v, FeatureType):
        return {"__ftype__": v.__name__}
    if isinstance(v, np.ndarray):
        key = f"{prefix}::{len(arrays)}"
        arrays[key] = v
        return {"__array__": key}
    if isinstance(v, VectorMetadata):
        return {"__vecmeta__": v.to_json()}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (list, tuple)):
        return [_encode_param(x, arrays, prefix) for x in v]
    if isinstance(v, dict):
        return {str(k): _encode_param(x, arrays, prefix) for k, x in v.items()}
    if isinstance(v, _codec_bases()):
        return _encode_obj(v, arrays, prefix)
    if isinstance(v, OpPipelineStage):
        # nested stage (e.g. the scalar transformer inside an
        # OPCollectionTransformer lift): className + ctor params
        # (+ fitted state), decoded through the same registry as
        # top-level stage records
        rec: Dict[str, Any] = {
            "__stage__": type(v).__name__,
            "params": {k: _encode_param(x, arrays, prefix)
                       for k, x in v.get_params().items()}}
        if isinstance(v, FittedModel):
            rec["modelState"] = {k: _encode_param(x, arrays, prefix)
                                 for k, x in v.get_model_state().items()}
        return rec
    if callable(v):
        return {"__dropped_callable__": getattr(v, "__name__", "fn")}
    return v


def _decode_param(v: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(v, dict):
        if "__ftype__" in v:
            return feature_type_by_name(v["__ftype__"])
        if "__array__" in v:
            return arrays[v["__array__"]]
        if "__vecmeta__" in v:
            return VectorMetadata.from_json(v["__vecmeta__"])
        if "__stage__" in v:
            cls = STAGE_REGISTRY.get(v["__stage__"])
            if cls is None:
                raise ValueError(
                    f"Nested stage class {v['__stage__']!r} is not "
                    "registered; import its module before loading")
            params = {k: _decode_param(x, arrays)
                      for k, x in v["params"].items()}
            params.pop("uid", None)
            stage = cls(**params)
            state = v.get("modelState")
            if state:
                decoded = {k: _decode_param(x, arrays)
                           for k, x in state.items()}
                if hasattr(stage, "apply_model_state"):
                    stage.apply_model_state(decoded)
                else:
                    for k, x in decoded.items():
                        setattr(stage, k, x)
            return stage
        if "__obj__" in v:
            import importlib
            mod_name, _, qual = v["__obj__"].partition(":")
            # module allowlist BEFORE import: importing runs a module's
            # top-level code, so an arbitrary module path in tampered
            # JSON must be rejected here, not after (every codec base
            # lives inside this package)
            pkg = __name__.partition(".")[0]
            if mod_name != pkg and not mod_name.startswith(pkg + "."):
                raise ValueError(
                    f"Refusing to import {mod_name!r} from serialized "
                    f"data: only {pkg} modules may be referenced")
            obj = importlib.import_module(mod_name)
            for part in qual.split("."):
                obj = getattr(obj, part)
            # allowlist: only the codec's config base classes may be
            # instantiated from serialized data (same discipline as
            # STAGE_REGISTRY for stages — never arbitrary callables)
            if not (isinstance(obj, type)
                    and issubclass(obj, _codec_bases())):
                raise ValueError(
                    f"Refusing to instantiate {v['__obj__']!r}: not a "
                    "registered config class")
            kwargs = {k: _decode_param(x, arrays)
                      for k, x in v["params"].items()}
            kwargs.update(kwargs.pop("__var_kw__", None) or {})
            return obj(**kwargs)
        if "__dropped_callable__" in v:
            return None
        return {k: _decode_param(x, arrays) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_param(x, arrays) for x in v]
    return v


def _stage_record(stage: OpPipelineStage, arrays: Dict[str, np.ndarray]
                  ) -> Dict[str, Any]:
    params = _encode_param(stage.get_params(), arrays, stage.uid)
    rec: Dict[str, Any] = {
        "className": type(stage).__name__,
        "uid": stage.uid,
        "params": params,
        "inputFeatureUids": [f.uid for f in stage.input_features],
    }
    if isinstance(stage, FittedModel):
        rec["isModel"] = True
        state = _encode_param(stage.get_model_state(), arrays, stage.uid + "#s")
        rec["modelState"] = state
    return rec


def _feature_record(f: Feature) -> Dict[str, Any]:
    return {
        "uid": f.uid,
        "name": f.name,
        "typeName": f.ftype.__name__,
        "isResponse": f.is_response,
        "originStageUid": f.origin_stage.uid if f.origin_stage else None,
        "parentUids": [p.uid for p in f.parents],
    }


def _topo_features(result_features) -> List[Feature]:
    """All features reachable from results, parents before children."""
    order: List[Feature] = []
    seen = set()

    def visit(f: Feature) -> None:
        if f.uid in seen:
            return
        seen.add(f.uid)
        for p in f.parents:
            visit(p)
        order.append(f)

    for f in result_features:
        visit(f)
    return order


def collect_stage_records(features: List[Feature],
                          arrays: Dict[str, np.ndarray],
                          fitted_lookup: Optional[Dict[str, Any]] = None
                          ) -> List[Dict[str, Any]]:
    """One stage record per distinct origin stage of ``features`` (topo
    order, deduped by uid), substituting fitted models when a lookup is
    given. Shared by the model writer and the feature-graph JSON codec so
    the two serializations cannot drift."""
    records: List[Dict[str, Any]] = []
    recorded = set()
    for f in features:
        st = f.origin_stage
        if st is None or st.uid in recorded:
            continue
        recorded.add(st.uid)
        if fitted_lookup is not None:
            st = fitted_lookup.get(st.uid, st)
        records.append(_stage_record(st, arrays))
    return records


def _fit_stats_json(model):
    from .fitstats import SufficientStats
    return {k: (v.to_json() if isinstance(v, SufficientStats) else v)
            for k, v in model.fit_stats.items()}


def save_workflow_model(model, path: str, overwrite: bool = False) -> None:
    if os.path.exists(os.path.join(path, MODEL_JSON)) and not overwrite:
        raise FileExistsError(f"Model already exists at {path}")
    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}

    features = _topo_features(model.result_features)
    stage_records = collect_stage_records(
        features, arrays, fitted_lookup=model.fitted_stages)

    from .utils.version import version_info
    doc = {
        "formatVersion": FORMAT_VERSION,
        "versionInfo": version_info(),
        "uid": model.uid,
        "resultFeatureUids": [f.uid for f in model.result_features],
        "blacklistedFeatureUids": [f.uid for f in model.blacklisted_features],
        "features": [_feature_record(f) for f in features],
        "stages": stage_records,
        "parameters": model.parameters,
        "trainTimeSeconds": model.train_time_s,
        "rawFeatureFilterResults": (model.rff_results.to_json()
                                    if model.rff_results is not None else None),
        # train-time sufficient statistics (fitstats.SufficientStats
        # monoids per fused moment column): the continual tier's
        # warm-start seam — a retrain merges these with the fresh
        # slice's stats instead of rescanning the old train window
        "fitSufficientStats": (_fit_stats_json(model)
                               if getattr(model, "fit_stats", None)
                               else None),
    }
    # Crash-consistent DIRECT save (ADVICE r2): the weights go to a save-
    # unique file name recorded in model.json, and model.json lands last
    # via tmp + atomic replace. At every instant the marker on disk
    # references a weights file that is fully written: a crash mid-weights
    # leaves the PREVIOUS (json, weights) pair untouched and loadable; a
    # crash before the json replace leaves the new weights as an orphan
    # (cleaned up by the next successful save). MODEL_JSON's existence
    # remains the completeness marker `_recover_checkpoint` relies on.
    import uuid
    mj = os.path.join(path, MODEL_JSON)
    weights_name = f"weights-{uuid.uuid4().hex[:12]}.npz"
    doc["weightsFile"] = weights_name
    # in-flight sidecar (ADVICE r3): a concurrent saver stalled for any
    # length of time between its np.savez and its model.json replace is
    # exempt from the orphan sweep via this marker — the previous pure
    # mtime gate could delete a >60s-stalled saver's fresh weights
    pending = os.path.join(path, weights_name + ".pending")
    with open(pending, "w") as fh:
        fh.write(str(os.getpid()))
    np.savez(os.path.join(path, weights_name), **arrays)
    json_tmp = mj + ".tmp"
    with open(json_tmp, "w") as fh:
        json.dump(doc, fh, indent=1, default=str)
    os.replace(json_tmp, mj)
    try:
        os.remove(pending)
    except OSError:
        pass
    # orphaned weights from prior/torn saves: skip any npz whose .pending
    # sidecar still exists (a live concurrent saver), age-gate the rest;
    # stale sidecars (crashed savers) fall to a 24h gate with their npz
    now = time.time()   # lint: wall-clock — compared against file mtimes
    for fn in os.listdir(path):
        full = os.path.join(path, fn)
        try:
            if fn.endswith(".npz.pending") and fn != weights_name + ".pending":
                if now - os.path.getmtime(full) > 86_400.0:
                    os.remove(full)
                continue
            if (fn.endswith(".npz") and fn != weights_name
                    and (fn.startswith("weights-") or fn == WEIGHTS_NPZ)):
                if os.path.exists(full + ".pending"):
                    continue            # concurrent saver still in flight
                if now - os.path.getmtime(full) > 60.0:
                    os.remove(full)
        except OSError:
            pass


def rebuild_stages(records, arrays: Dict[str, np.ndarray]
                   ) -> Dict[str, OpPipelineStage]:
    """Stage records → instances (registry-checked), cross-references
    re-bound by uid. Shared by model loading and feature-graph JSON."""
    stage_by_uid: Dict[str, OpPipelineStage] = {}
    for rec in records:
        cls = STAGE_REGISTRY.get(rec["className"])
        if cls is None:
            raise ValueError(
                f"Stage class {rec['className']!r} is not registered; "
                "import its module before loading")
        params = _decode_param(rec["params"], arrays)
        params.pop("uid", None)
        stage = cls(uid=rec["uid"], **params)
        if rec.get("isModel"):
            state = _decode_param(rec.get("modelState", {}), arrays)
            if hasattr(stage, "apply_model_state"):
                stage.apply_model_state(state)
            else:
                for k, v in state.items():
                    setattr(stage, k, v)
        stage_by_uid[rec["uid"]] = stage

    # stages that reference other stages (e.g. RecordInsightsLOCO's scored
    # model) re-attach them by uid now that every stage exists
    for stage in stage_by_uid.values():
        if hasattr(stage, "rebind_stages"):
            stage.rebind_stages(stage_by_uid)
    return stage_by_uid


def rebuild_features(records, stage_by_uid: Dict[str, OpPipelineStage]
                     ) -> Dict[str, Feature]:
    """Feature records (topological order) → wired Feature graph."""
    feat_by_uid: Dict[str, Feature] = {}
    for frec in records:
        stage = stage_by_uid.get(frec["originStageUid"])
        if stage is None:
            raise ValueError(
                f"Feature {frec['name']!r} has unknown origin stage")
        if frec["parentUids"]:
            parents = [feat_by_uid[u] for u in frec["parentUids"]]
            if tuple(stage.input_features) != tuple(parents):
                stage.set_input(*parents)
        feat = stage.get_output()
        feat.uid = frec["uid"]
        feat.name = frec["name"]
        feat.is_response = frec["isResponse"]
        feat_by_uid[frec["uid"]] = feat
    return feat_by_uid


def _recover_checkpoint(path: str) -> str:
    """Resolve a checkpoint dir that a preemption left mid-swap.

    ``workflow._atomic_checkpoint`` renames ``<path>.tmp`` (a complete
    save) over ``<path>``, parking the previous good save at
    ``<path>.old``. If the process died between the renames, the target
    dir is missing but one of the siblings is loadable — prefer ``.tmp``
    (newer; it is fully written before any rename starts) and fall back
    to ``.old``. The chosen sibling is renamed into place so the next
    checkpoint cycle starts clean. MODEL_JSON doubles as the completeness
    marker: ``save_workflow_model`` writes it last (atomic replace, after
    weights), so a crash mid-save leaves no MODEL_JSON in ``.tmp`` and
    the torn sibling is correctly ignored."""
    if os.path.exists(os.path.join(path, MODEL_JSON)):
        return path
    from .parallel.multihost import is_coordinator
    if not is_coordinator():
        # multi-host: only the coordinator repairs the shared directory
        # (single-writer invariant). When a complete sibling exists,
        # workers wait for the repaired target — reading the sibling
        # immediately would race the coordinator's rename out from under
        # the open() calls; if the coordinator never repairs (it crashed
        # again / this load runs on workers only), fall back to the
        # sibling, which nothing is renaming any more. No sibling → fail
        # fast downstream.
        sibs = [s for s in (f"{path}.tmp", f"{path}.old")
                if os.path.exists(os.path.join(s, MODEL_JSON))]
        if not sibs:
            return path
        import time
        for _ in range(60):
            if os.path.exists(os.path.join(path, MODEL_JSON)):
                return path
            time.sleep(0.5)
        # timed out: the coordinator may have completed its rename JUST
        # after the poll (sibling gone, target repaired) — re-check both
        # rather than returning a possibly-vanished sibling (ADVICE r2)
        if os.path.exists(os.path.join(path, MODEL_JSON)):
            return path
        sibs = [s for s in (f"{path}.tmp", f"{path}.old")
                if os.path.exists(os.path.join(s, MODEL_JSON))]
        return sibs[0] if sibs else path
    for sibling in (f"{path}.tmp", f"{path}.old"):
        if os.path.exists(os.path.join(sibling, MODEL_JSON)):
            if not os.path.exists(path):
                try:
                    os.rename(sibling, path)
                except FileNotFoundError:
                    continue   # lost a rename race; retry next candidate
            return path
    return path


def _load_weights_npz(npz_path: str) -> Dict[str, np.ndarray]:
    """Load a weights archive with a magic/size check first: a truncated
    copy or torn write fails with a descriptive error naming the file
    instead of a raw ``zipfile.BadZipFile`` traceback (npz IS a zip —
    the ``PK\\x03\\x04`` magic is the cheapest integrity gate)."""
    import zipfile
    with open(npz_path, "rb") as fh:
        head = fh.read(4)
    # PK\x03\x04 = local file header; PK\x05\x06 = the empty-archive
    # end record (a model with no weight arrays saves an empty zip)
    if head[:2] != b"PK":
        raise ValueError(
            f"corrupt model weights at {npz_path!r}: "
            f"{'empty file' if not head else 'bad magic ' + repr(head)} "
            "— the archive is truncated or was not written by np.savez "
            "(partial copy or torn write; re-save or re-copy the model)")
    try:
        with np.load(npz_path, allow_pickle=False) as npz:
            return {k: npz[k] for k in npz.files}
    except (zipfile.BadZipFile, OSError, ValueError) as e:
        if isinstance(e, FileNotFoundError):
            raise
        raise ValueError(
            f"corrupt model weights at {npz_path!r}: {type(e).__name__}: "
            f"{e} (truncated archive — re-save or re-copy the model)"
        ) from e


def load_workflow_model(path: str):
    from .workflow import WorkflowModel

    # a concurrent coordinator repair can rename the resolved directory out
    # from under these opens (worker-side race, ADVICE r2): re-resolve and
    # retry rather than surfacing FileNotFoundError for a repairable state
    for attempt in range(3):
        resolved = _recover_checkpoint(path)
        try:
            mj = os.path.join(resolved, MODEL_JSON)
            try:
                with open(mj) as fh:
                    doc = json.load(fh)
            except json.JSONDecodeError as e:
                # model.json lands via atomic replace, so a decode error
                # is real corruption, not a torn concurrent write —
                # fail now, descriptively (no point retrying)
                raise ValueError(
                    f"corrupt model at {path!r}: {MODEL_JSON} is not "
                    f"valid JSON ({e})") from e
            if int(doc.get("formatVersion", 1)) > FORMAT_VERSION:
                raise ValueError(
                    f"Model at {path} uses format "
                    f"{doc['formatVersion']}, newer than this library "
                    f"supports ({FORMAT_VERSION}); upgrade the package")
            arrays: Dict[str, np.ndarray] = {}
            if "weightsFile" in doc:
                # new format: the marker references a weights file written
                # BEFORE it — absence means a concurrent re-save's orphan
                # cleanup won the race; raising re-enters the retry with a
                # fresh marker read instead of crashing later on a missing
                # array ref
                arrays = _load_weights_npz(
                    os.path.join(resolved, doc["weightsFile"]))
            else:
                npz_path = os.path.join(resolved, WEIGHTS_NPZ)  # legacy
                if os.path.exists(npz_path):
                    arrays = _load_weights_npz(npz_path)
            break
        except FileNotFoundError:
            if attempt == 2:
                raise
            import time
            time.sleep(0.25)

    stage_by_uid = rebuild_stages(doc["stages"], arrays)
    feat_by_uid = rebuild_features(doc["features"], stage_by_uid)
    result_features = [feat_by_uid[u] for u in doc["resultFeatureUids"]]
    fitted = {uid: st for uid, st in stage_by_uid.items()
              if isinstance(st, FittedModel)}
    rff_results = None
    if doc.get("rawFeatureFilterResults"):
        # round-trip the train-time feature distributions + exclusion
        # reasons: the serving-time drift sentinel compares live traffic
        # against these, so a loaded model must carry what its save wrote
        from .filters.raw_feature_filter import RawFeatureFilterResults
        rff_results = RawFeatureFilterResults.from_json(
            doc["rawFeatureFilterResults"])
    fit_stats = None
    if doc.get("fitSufficientStats"):
        # tolerant round-trip: a corrupt stats block degrades to a
        # model without warm-start state, never a failed load
        try:
            from .fitstats import sufficient_stats_from_json
            fit_stats = sufficient_stats_from_json(
                doc["fitSufficientStats"])
        except (KeyError, TypeError, ValueError):
            logger.warning("fitSufficientStats block at %s is "
                           "malformed; warm-start state dropped", path)
    model = WorkflowModel(
        result_features=result_features,
        fitted_stages=fitted,
        parameters=doc.get("parameters") or {},
        rff_results=rff_results,
        train_time_s=doc.get("trainTimeSeconds", 0.0),
        fit_stats=fit_stats,
    )
    model.uid = doc["uid"]
    return model
