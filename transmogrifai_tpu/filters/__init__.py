"""Raw feature filtering — pre-DAG data-quality gate.

Parity target: ``core/src/main/scala/com/salesforce/op/filters/``
(``RawFeatureFilter.scala``, ``FeatureDistribution.scala``,
``PreparedFeatures.scala``, ``RawFeatureFilterResults.scala``).
"""
from .distribution import FeatureDistribution, Summary  # noqa: F401
from .raw_feature_filter import (  # noqa: F401
    ExclusionReasons, FilteredRawData, RawFeatureFilter,
    RawFeatureFilterMetrics, RawFeatureFilterResults)
