"""Per-feature summaries and binned distributions.

Parity: ``core/.../filters/FeatureDistribution.scala`` (monoid of nulls /
count / histogram bins, JS divergence, fill metrics) and the ``Summary``
min/max/sum/count monoid (``core/.../filters/Summary.scala``).

TPU re-design: the reference folds these monoids per-row over an RDD. Here
each statistic is one vectorized pass over a column's dense arrays — masks
give null counts for free, numeric histograms are a single
``np.histogram`` over masked values, and text histograms hash the whole
column into a fixed bin space (the hashed "text distribution" trick the
reference uses so train/score text can be compared without a vocabulary).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..columns import (Column, GeoColumn, MapColumn, NumericColumn,
                       RaggedColumn, TextColumn, TextListColumn,
                       TextSetColumn, VectorColumn)
from ..ops.hashing import hash_tokens

__all__ = ["Summary", "FeatureDistribution",
           "summaries_of_column", "distributions_of_column"]


@dataclass
class Summary:
    """Min/max/sum/count monoid per feature (Summary.scala)."""

    min: float = float("inf")
    max: float = float("-inf")
    sum: float = 0.0
    count: float = 0.0

    def __add__(self, other: "Summary") -> "Summary":
        return Summary(min(self.min, other.min), max(self.max, other.max),
                       self.sum + other.sum, self.count + other.count)

    @staticmethod
    def of_values(values: np.ndarray) -> "Summary":
        if values.size == 0:
            return Summary()
        v = values.astype(np.float64)
        return Summary(float(v.min()), float(v.max()),
                       float(v.sum()), float(v.size))

    def to_json(self) -> Dict[str, Any]:
        return {"min": self.min, "max": self.max,
                "sum": self.sum, "count": self.count}


@dataclass
class FeatureDistribution:
    """Distribution of one raw feature (or one map key) on one data split.

    ``distribution`` is the binned histogram: equi-width over the combined
    train/score ``Summary`` range for numerics, hash bins for text. The
    monoid ``+`` and the divergence/fill metrics mirror
    ``FeatureDistribution.scala:...`` (jsDivergence, fillRate, relativeFillRate,
    relativeFillRatio).
    """

    name: str
    key: Optional[str] = None        # map key, if this is a map sub-feature
    count: int = 0                   # total rows
    nulls: int = 0                   # empty rows
    distribution: np.ndarray = field(
        default_factory=lambda: np.zeros(0))
    summary_info: List[float] = field(default_factory=list)  # bin edges / [bins]

    @property
    def full_name(self) -> str:
        return self.name if self.key is None else f"{self.name}({self.key})"

    def __add__(self, other: "FeatureDistribution") -> "FeatureDistribution":
        assert self.name == other.name and self.key == other.key
        # total monoid: either side may carry an empty histogram (e.g. a
        # default-constructed accumulator)
        if not self.distribution.size:
            dist = other.distribution.copy()
        elif not other.distribution.size:
            dist = self.distribution.copy()
        else:
            dist = self.distribution + other.distribution
        return FeatureDistribution(self.name, self.key,
                                   self.count + other.count,
                                   self.nulls + other.nulls, dist,
                                   self.summary_info or other.summary_info)

    # -- metrics (FeatureDistribution.scala) -------------------------------
    def fill_rate(self) -> float:
        return 0.0 if self.count == 0 else (self.count - self.nulls) / self.count

    def relative_fill_rate(self, other: "FeatureDistribution") -> float:
        return abs(self.fill_rate() - other.fill_rate())

    def relative_fill_ratio(self, other: "FeatureDistribution") -> float:
        a, b = self.fill_rate(), other.fill_rate()
        lo, hi = min(a, b), max(a, b)
        # two identically-empty features are maximally SIMILAR, not
        # maximally drifted: 0/0 is ratio 1, not inf
        if hi == 0.0:
            return 1.0
        return float("inf") if lo == 0.0 else hi / lo

    def js_divergence(self, other: "FeatureDistribution") -> float:
        """Jensen-Shannon divergence of the two normalized histograms,
        log base 2 → bounded in [0, 1]."""
        p, q = self.distribution, other.distribution
        if p.size == 0 or q.size == 0 or p.sum() == 0 or q.sum() == 0:
            return 0.0
        if p.shape != q.shape:
            return 1.0
        p = p / p.sum()
        q = q / q.sum()
        m = 0.5 * (p + q)
        with np.errstate(divide="ignore", invalid="ignore"):
            kl_pm = np.where(p > 0, p * (np.log2(p) - np.log2(m)), 0.0)
            kl_qm = np.where(q > 0, q * (np.log2(q) - np.log2(m)), 0.0)
        return float(0.5 * kl_pm.sum() + 0.5 * kl_qm.sum())

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "key": self.key, "count": self.count,
                "nulls": self.nulls,
                "distribution": self.distribution.tolist(),
                "summaryInfo": list(self.summary_info)}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "FeatureDistribution":
        return FeatureDistribution(
            d["name"], d.get("key"), int(d["count"]), int(d["nulls"]),
            np.asarray(d.get("distribution", []), dtype=np.float64),
            list(d.get("summaryInfo", [])))


# ---------------------------------------------------------------------------
# Column → null mask / numeric payload extraction
# ---------------------------------------------------------------------------

def _null_mask(col: Column) -> np.ndarray:
    """bool[n]: True where the row is EMPTY."""
    if isinstance(col, NumericColumn):
        return ~col.mask
    if isinstance(col, TextColumn):
        return np.array([v is None for v in col.values], dtype=bool)
    if isinstance(col, (TextListColumn, TextSetColumn)):
        return np.array([len(v) == 0 for v in col.values], dtype=bool)
    if isinstance(col, RaggedColumn):
        return (np.diff(col.offsets) == 0)
    if isinstance(col, GeoColumn):
        return ~col.mask
    if isinstance(col, VectorColumn):
        return np.zeros(len(col), dtype=bool)
    if isinstance(col, MapColumn):
        empty = np.ones(len(col), dtype=bool)
        for child in col.children.values():
            empty &= _null_mask(child)
        return empty
    raise TypeError(f"Unsupported column for distribution: {type(col)}")


def _numeric_values(col: Column) -> Optional[np.ndarray]:
    """Present numeric payload values (flattened), or None if text-like."""
    if isinstance(col, NumericColumn):
        return col.values[col.mask].astype(np.float64)
    if isinstance(col, RaggedColumn):
        return col.flat.astype(np.float64)
    if isinstance(col, GeoColumn):
        return col.values[col.mask][:, :2].ravel()
    if isinstance(col, VectorColumn):
        return col.values.ravel().astype(np.float64)
    return None


def _text_tokens(col: Column) -> Optional[List[str]]:
    if isinstance(col, TextColumn):
        return [v for v in col.values if v is not None]
    if isinstance(col, (TextListColumn, TextSetColumn)):
        return [t for row in col.values for t in row]
    return None


def summaries_of_column(name: str, col: Column) -> Dict[Tuple[str, Optional[str]], Summary]:
    """Per-(feature, map key) numeric Summary; text features get a
    count-only summary (their bins are the hash space)."""
    if isinstance(col, MapColumn):
        out: Dict[Tuple[str, Optional[str]], Summary] = {}
        for k, child in col.children.items():
            for (_, _), s in summaries_of_column(name, child).items():
                out[(name, k)] = s
        return out
    vals = _numeric_values(col)
    if vals is not None:
        return {(name, None): Summary.of_values(vals)}
    toks = _text_tokens(col)
    return {(name, None): Summary(0.0, 0.0, 0.0, float(len(toks or [])))}


def distributions_of_column(
        name: str, col: Column, bins: int,
        summaries: Dict[Tuple[str, Optional[str]], Summary],
        key: Optional[str] = None) -> List[FeatureDistribution]:
    """Binned FeatureDistribution(s) for a column.

    ``summaries`` supplies the (train ∪ score) numeric range so both splits
    share bin edges (the reference reduces Summary over both readers before
    binning, RawFeatureFilter.scala:135-196).
    """
    if isinstance(col, MapColumn):
        out: List[FeatureDistribution] = []
        for k, child in sorted(col.children.items()):
            out.extend(distributions_of_column(name, child, bins, summaries, k))
        return out

    nulls = _null_mask(col)
    n = len(col)
    summ = summaries.get((name, key)) or Summary()

    vals = _numeric_values(col)
    if vals is not None:
        lo, hi = summ.min, summ.max
        if not np.isfinite(lo) or not np.isfinite(hi):
            lo, hi = 0.0, 1.0
        if hi <= lo:
            hi = lo + 1.0
        hist, edges = np.histogram(vals, bins=bins, range=(lo, hi))
        return [FeatureDistribution(name, key, n, int(nulls.sum()),
                                    hist.astype(np.float64),
                                    [float(lo), float(hi), float(bins)])]

    toks = _text_tokens(col)
    if toks is not None:
        hist = np.zeros(bins, dtype=np.float64)
        if toks:
            idx = hash_tokens(toks).astype(np.int64) % bins
            np.add.at(hist, idx, 1.0)
        return [FeatureDistribution(name, key, n, int(nulls.sum()), hist,
                                    [float(bins)])]

    raise TypeError(f"Unsupported column for distribution: {type(col)}")
