"""RawFeatureFilter — the pre-DAG data-quality gate.

Parity: ``core/.../filters/RawFeatureFilter.scala`` (:90 ctor params,
``computeFeatureStats`` :135-196, ``getRawFeatureFilterMetrics`` :207-291,
exclusion reasons :302+) and ``RawFeatureFilterResults.scala``.

Given the training data (and optionally a scoring dataset), computes per
raw feature — and per map key — fill rates, binned distributions, the
train↔score Jensen-Shannon divergence, and the null-indicator↔label
correlation, then blacklists features that look unusable or leaky:

* training / scoring fill rate below ``min_fill``
* |train fill − score fill| above ``max_fill_difference``
* fill-rate ratio above ``max_fill_ratio_diff``
* JS divergence above ``max_js_divergence``
* null-label absolute correlation above ``max_correlation``

TPU re-design: all statistics are vectorized column passes (see
``distribution.py``); the null-leakage correlations for ALL features are one
matrix product between the stacked null-indicator matrix and the label
vector instead of the reference's per-row PreparedFeatures RDD reduce.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columns import Column, ColumnStore, MapColumn, NumericColumn
from ..features import Feature
from .distribution import (FeatureDistribution, Summary,
                           distributions_of_column, summaries_of_column,
                           _null_mask)

__all__ = ["RawFeatureFilter", "FilteredRawData", "RawFeatureFilterMetrics",
           "ExclusionReasons", "RawFeatureFilterResults"]


@dataclass
class RawFeatureFilterMetrics:
    """Per-(feature, key) metrics (RawFeatureFilterResults.scala)."""

    name: str
    key: Optional[str]
    training_fill_rate: float
    training_null_label_abs_corr: Optional[float]
    scoring_fill_rate: Optional[float]
    js_divergence: Optional[float]
    fill_rate_diff: Optional[float]
    fill_ratio_diff: Optional[float]

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "key": self.key,
                "trainingFillRate": self.training_fill_rate,
                "trainingNullLabelAbsoluteCorr": self.training_null_label_abs_corr,
                "scoringFillRate": self.scoring_fill_rate,
                "jsDivergence": self.js_divergence,
                "fillRateDiff": self.fill_rate_diff,
                "fillRatioDiff": self.fill_ratio_diff}


@dataclass
class ExclusionReasons:
    """Why a (feature, key) was excluded (RawFeatureFilterResults.scala)."""

    name: str
    key: Optional[str]
    training_unfilled_state: bool = False
    training_null_label_leaker: bool = False
    scoring_unfilled_state: bool = False
    js_divergence_mismatch: bool = False
    fill_rate_diff_mismatch: bool = False
    fill_ratio_diff_mismatch: bool = False
    excluded: bool = False

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "key": self.key,
                "trainingUnfilledState": self.training_unfilled_state,
                "trainingNullLabelLeaker": self.training_null_label_leaker,
                "scoringUnfilledState": self.scoring_unfilled_state,
                "jsDivergenceMismatch": self.js_divergence_mismatch,
                "fillRateDiffMismatch": self.fill_rate_diff_mismatch,
                "fillRatioDiffMismatch": self.fill_ratio_diff_mismatch,
                "excluded": self.excluded}


@dataclass
class RawFeatureFilterResults:
    """Config + metrics + reasons, serialized with the model."""

    config: Dict[str, Any] = field(default_factory=dict)
    metrics: List[RawFeatureFilterMetrics] = field(default_factory=list)
    exclusion_reasons: List[ExclusionReasons] = field(default_factory=list)
    training_distributions: List[FeatureDistribution] = field(default_factory=list)
    scoring_distributions: List[FeatureDistribution] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"config": self.config,
                "metrics": [m.to_json() for m in self.metrics],
                "exclusionReasons": [r.to_json() for r in self.exclusion_reasons],
                "trainingDistributions": [d.to_json() for d in self.training_distributions],
                "scoringDistributions": [d.to_json() for d in self.scoring_distributions]}

    def summary(self) -> Dict[str, Any]:
        """The compact block the runner stamps in its train metrics doc:
        how many (feature, key) pairs were checked, which were excluded
        and why-counts, and whether train-time distributions were
        persisted (the serving-time drift sentinel's baseline)."""
        excluded = [(f"{r.name}({r.key})" if r.key is not None else r.name)
                    for r in self.exclusion_reasons if r.excluded]
        return {"featuresChecked": len(self.metrics),
                "excluded": excluded,
                "excludedCount": len(excluded),
                "trainingDistributions": len(self.training_distributions),
                "config": dict(self.config)}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "RawFeatureFilterResults":
        return RawFeatureFilterResults(
            config=d.get("config", {}),
            metrics=[RawFeatureFilterMetrics(
                m["name"], m.get("key"), m["trainingFillRate"],
                m.get("trainingNullLabelAbsoluteCorr"),
                m.get("scoringFillRate"), m.get("jsDivergence"),
                m.get("fillRateDiff"), m.get("fillRatioDiff"))
                for m in d.get("metrics", [])],
            exclusion_reasons=[ExclusionReasons(
                r["name"], r.get("key"),
                r.get("trainingUnfilledState", False),
                r.get("trainingNullLabelLeaker", False),
                r.get("scoringUnfilledState", False),
                r.get("jsDivergenceMismatch", False),
                r.get("fillRateDiffMismatch", False),
                r.get("fillRatioDiffMismatch", False),
                r.get("excluded", False))
                for r in d.get("exclusionReasons", [])],
            training_distributions=[FeatureDistribution.from_json(x)
                                    for x in d.get("trainingDistributions", [])],
            scoring_distributions=[FeatureDistribution.from_json(x)
                                   for x in d.get("scoringDistributions", [])])


@dataclass
class FilteredRawData:
    """Output of the filter (FilteredRawData, RawFeatureFilter.scala:467-478)."""

    clean_store: ColumnStore
    blacklisted_features: List[Feature]
    blacklisted_map_keys: Dict[str, List[str]]
    results: RawFeatureFilterResults


class RawFeatureFilter:
    """Data-quality gate over raw features, run before DAG fitting."""

    def __init__(self,
                 bins: int = 100,
                 min_fill: float = 0.001,
                 max_fill_difference: float = 0.90,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.90,
                 max_correlation: float = 0.95,
                 protected_features: Sequence[str] = (),
                 js_divergence_protected_features: Sequence[str] = (),
                 scoring_data=None):
        self.bins = bins
        self.min_fill = min_fill
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_correlation = max_correlation
        self.protected_features = set(protected_features)
        self.js_protected = set(js_divergence_protected_features)
        self.scoring_data = scoring_data

    def config_json(self) -> Dict[str, Any]:
        return {"bins": self.bins, "minFill": self.min_fill,
                "maxFillDifference": self.max_fill_difference,
                "maxFillRatioDiff": self.max_fill_ratio_diff,
                "maxJSDivergence": self.max_js_divergence,
                "maxCorrelation": self.max_correlation,
                "protectedFeatures": sorted(self.protected_features),
                "jsDivergenceProtectedFeatures": sorted(self.js_protected)}

    # -- statistics --------------------------------------------------------
    def _distributions(self, store: ColumnStore, predictors: List[Feature],
                       summaries) -> Dict[Tuple[str, Optional[str]],
                                          FeatureDistribution]:
        out: Dict[Tuple[str, Optional[str]], FeatureDistribution] = {}
        for f in predictors:
            for d in distributions_of_column(f.name, store[f.name],
                                             self.bins, summaries):
                out[(d.name, d.key)] = d
        return out

    @staticmethod
    def _with_missing_as_null(store: ColumnStore,
                              predictors: List[Feature]) -> ColumnStore:
        """A predictor absent from a store counts as 100% null — missing at
        scoring time must trip the unfilled/fill-diff gates, not bypass
        them."""
        from ..columns import column_of_empty
        missing = {f.name: column_of_empty(f.ftype, store.n_rows)
                   for f in predictors if f.name not in store}
        return store.with_columns(missing) if missing else store

    @staticmethod
    def _label_vector(store: ColumnStore, responses: List[Feature]
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(label values, present mask) — rows with null labels must be
        excluded from the leakage correlation, not treated as label 0."""
        for f in responses:
            col = store.get(f.name)
            if isinstance(col, NumericColumn):
                return col.values.astype(np.float64), col.mask.copy()
        return None

    def _null_label_corrs(self, store: ColumnStore, predictors: List[Feature],
                          label: Optional[Tuple[np.ndarray, np.ndarray]]
                          ) -> Dict[Tuple[str, Optional[str]], float]:
        """|corr(is-null, label)| for every (feature, key) — one matmul.

        Replaces the reference's per-row PreparedFeatures summaries +
        correlation matrix job (RawFeatureFilter.scala:175-187).
        """
        if label is None:
            return {}
        label, label_mask = label
        keys: List[Tuple[str, Optional[str]]] = []
        indicators: List[np.ndarray] = []
        for f in predictors:
            col = store[f.name]
            if isinstance(col, MapColumn):
                for k, child in sorted(col.children.items()):
                    keys.append((f.name, k))
                    indicators.append(_null_mask(child).astype(np.float64))
            else:
                keys.append((f.name, None))
                indicators.append(_null_mask(col).astype(np.float64))
        if not indicators:
            return {}
        M = np.stack(indicators)[:, label_mask]       # [d, n_labeled]
        labeled = label[label_mask]
        if labeled.size == 0:
            return {}
        y = labeled - labeled.mean()
        Mc = M - M.mean(axis=1, keepdims=True)
        num = Mc @ y
        denom = np.sqrt((Mc * Mc).sum(axis=1) * (y * y).sum())
        with np.errstate(divide="ignore", invalid="ignore"):
            corr = np.where(denom > 0, num / denom, 0.0)
        return {k: float(abs(c)) for k, c in zip(keys, corr)}

    # -- main entry --------------------------------------------------------
    def filter_raw(self, store: ColumnStore, raw_features: Sequence[Feature],
                   scoring_data=None) -> FilteredRawData:
        predictors = [f for f in raw_features if not f.is_response]
        responses = [f for f in raw_features if f.is_response]

        store = self._with_missing_as_null(store, predictors)
        score_store = self._scoring_store(scoring_data, raw_features, predictors)
        if score_store is not None:
            score_store = self._with_missing_as_null(score_store, predictors)

        # combined numeric summaries → shared bin edges for both splits
        summaries: Dict[Tuple[str, Optional[str]], Summary] = {}
        for f in predictors:
            for k, s in summaries_of_column(f.name, store[f.name]).items():
                summaries[k] = summaries.get(k, Summary()) + s
        if score_store is not None:
            for f in predictors:
                for k, s in summaries_of_column(
                        f.name, score_store[f.name]).items():
                    summaries[k] = summaries.get(k, Summary()) + s

        train_dists = self._distributions(store, predictors, summaries)
        score_dists = (self._distributions(score_store, predictors, summaries)
                       if score_store is not None else {})
        if score_store is not None:
            # a map key seen in training but entirely absent from the scoring
            # store must still face the scoring-side gates: synthesize an
            # all-null distribution (fill rate 0), as the reference's empty
            # scoring distribution does (FeatureDistribution.scala)
            n_score = score_store.n_rows
            for (name, key), td in train_dists.items():
                if (name, key) not in score_dists:
                    score_dists[(name, key)] = FeatureDistribution(
                        name=name, key=key, count=n_score, nulls=n_score,
                        distribution=np.zeros_like(td.distribution),
                        summary_info=list(td.summary_info))
        corrs = self._null_label_corrs(
            store, predictors, self._label_vector(store, responses))

        metrics: List[RawFeatureFilterMetrics] = []
        reasons: List[ExclusionReasons] = []
        excluded: Dict[str, List[Optional[str]]] = {}

        for (name, key), td in sorted(train_dists.items(),
                                      key=lambda kv: (kv[0][0], kv[0][1] or "")):
            sd = score_dists.get((name, key))
            corr = corrs.get((name, key))
            m = RawFeatureFilterMetrics(
                name=name, key=key,
                training_fill_rate=td.fill_rate(),
                training_null_label_abs_corr=corr,
                scoring_fill_rate=sd.fill_rate() if sd else None,
                js_divergence=td.js_divergence(sd) if sd else None,
                fill_rate_diff=td.relative_fill_rate(sd) if sd else None,
                fill_ratio_diff=td.relative_fill_ratio(sd) if sd else None)
            metrics.append(m)

            r = ExclusionReasons(name=name, key=key)
            if name not in self.protected_features:
                r.training_unfilled_state = m.training_fill_rate < self.min_fill
                r.training_null_label_leaker = (
                    corr is not None and corr > self.max_correlation)
                if sd is not None:
                    r.scoring_unfilled_state = (
                        m.scoring_fill_rate < self.min_fill)
                    if name not in self.js_protected:
                        r.js_divergence_mismatch = (
                            m.js_divergence > self.max_js_divergence)
                    r.fill_rate_diff_mismatch = (
                        m.fill_rate_diff > self.max_fill_difference)
                    r.fill_ratio_diff_mismatch = (
                        m.fill_ratio_diff > self.max_fill_ratio_diff)
            r.excluded = any([r.training_unfilled_state,
                              r.training_null_label_leaker,
                              r.scoring_unfilled_state,
                              r.js_divergence_mismatch,
                              r.fill_rate_diff_mismatch,
                              r.fill_ratio_diff_mismatch])
            reasons.append(r)
            if r.excluded:
                excluded.setdefault(name, []).append(key)

        blacklisted_features, blacklisted_keys, clean = self._apply_exclusions(
            store, predictors, excluded)

        results = RawFeatureFilterResults(
            config=self.config_json(), metrics=metrics,
            exclusion_reasons=reasons,
            training_distributions=list(train_dists.values()),
            scoring_distributions=list(score_dists.values()))
        return FilteredRawData(clean, blacklisted_features, blacklisted_keys,
                               results)

    def _scoring_store(self, scoring_data, raw_features,
                       predictors) -> Optional[ColumnStore]:
        data = scoring_data if scoring_data is not None else self.scoring_data
        if data is None:
            return None
        if isinstance(data, ColumnStore):
            return data
        from ..workflow import _generate_raw_store
        return _generate_raw_store(data, predictors)

    @staticmethod
    def _apply_exclusions(store: ColumnStore, predictors: List[Feature],
                          excluded: Dict[str, List[Optional[str]]]
                          ) -> Tuple[List[Feature], Dict[str, List[str]],
                                     ColumnStore]:
        blacklisted_features: List[Feature] = []
        blacklisted_keys: Dict[str, List[str]] = {}
        drop_cols: List[str] = []
        replace: Dict[str, Column] = {}
        by_name = {f.name: f for f in predictors}
        for name, keys in excluded.items():
            col = store[name]
            if isinstance(col, MapColumn):
                bad = sorted(k for k in keys if k is not None)
                blacklisted_keys[name] = bad
                remaining = {k: c for k, c in col.children.items()
                             if k not in set(bad)}
                if remaining:
                    replace[name] = MapColumn(col.ftype, remaining, len(col))
                else:
                    blacklisted_features.append(by_name[name])
                    drop_cols.append(name)
            else:
                blacklisted_features.append(by_name[name])
                drop_cols.append(name)
        clean = store.drop(drop_cols).with_columns(replace)
        return blacklisted_features, blacklisted_keys, clean
