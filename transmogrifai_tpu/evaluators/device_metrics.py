"""On-device (JAX) metric kernels for the CV sweep.

The round-1 validator pulled every (fold × grid) prediction back to host
and ran numpy AuPR per cell (O(grid × folds) host sorts —
``models/tuning.py`` r1). Here the selection metric rides the device: one
jitted program per family computes fit → predict → metric and returns just
a [folds, grid] metric matrix, so predictions never leave HBM.

Semantics match ``evaluators/metrics.py`` (MLlib threshold curves): ties
are grouped per distinct score, ROC gets (0,0)/(1,1) endpoints, PR is
prepended with (0, p@first). Validation rows are selected by a 0/1 weight
vector instead of boolean indexing (static shapes): zero-weight rows
contribute nothing to the cumulative TP/FP counts — they only add
duplicate curve points, which have zero trapezoid width.

Reference: ``core/.../evaluators/OpBinaryClassificationEvaluator.scala:180-203``,
``OpCrossValidation.scala:56-69`` (fold-metric averaging).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["device_metric_fn", "DEVICE_METRICS"]

_EPS = 1e-12


def _curve(y, s, w):
    """Weighted cumulative (tp, fp) at each sorted position, tie-grouped.

    Returns (tp, fp, P, N) where tp/fp are [n] cumulative counts evaluated
    at each position's tie-group END (MLlib's distinct-threshold curve,
    with harmless duplicate points inside tie groups).
    """
    order = jnp.argsort(-s)
    ys = y[order] * w[order]
    ws = w[order]
    ss = s[order]
    tp = jnp.cumsum(ys)
    fp = jnp.cumsum(ws - ys)
    # group end: index of the last element equal to ss[i] in the sort
    end_idx = jnp.searchsorted(-ss, -ss, side="right") - 1
    tp = tp[end_idx]
    fp = fp[end_idx]
    P = jnp.sum(y * w)
    N = jnp.sum(w) - P
    return tp, fp, P, N


def _trapz(yv, xv):
    return 0.5 * jnp.sum((xv[1:] - xv[:-1]) * (yv[1:] + yv[:-1]))


def auroc(y, s, w):
    tp, fp, P, N = _curve(y, s, w)
    tpr = jnp.concatenate([jnp.zeros((1,)), tp / jnp.maximum(P, _EPS),
                           jnp.ones((1,))])
    fpr = jnp.concatenate([jnp.zeros((1,)), fp / jnp.maximum(N, _EPS),
                           jnp.ones((1,))])
    return jnp.where((P > 0) & (N > 0), _trapz(tpr, fpr), 0.0)


def aupr(y, s, w):
    tp, fp, P, _ = _curve(y, s, w)
    precision = tp / jnp.maximum(tp + fp, _EPS)
    recall = tp / jnp.maximum(P, _EPS)
    precision = jnp.concatenate([precision[:1], precision])
    recall = jnp.concatenate([jnp.zeros((1,)), recall])
    return jnp.where(P > 0, _trapz(precision, recall), 0.0)


def _binary_confusion(y, pred, w):
    tp = jnp.sum(w * ((pred == 1) & (y == 1)))
    tn = jnp.sum(w * ((pred == 0) & (y == 0)))
    fp = jnp.sum(w * ((pred == 1) & (y == 0)))
    fn = jnp.sum(w * ((pred == 0) & (y == 1)))
    return tp, tn, fp, fn


def binary_precision(y, pred, w):
    tp, _, fp, _ = _binary_confusion(y, pred, w)
    return tp / jnp.maximum(tp + fp, _EPS)


def binary_recall(y, pred, w):
    tp, _, _, fn = _binary_confusion(y, pred, w)
    return tp / jnp.maximum(tp + fn, _EPS)


def binary_f1(y, pred, w):
    p = binary_precision(y, pred, w)
    r = binary_recall(y, pred, w)
    return 2.0 * p * r / jnp.maximum(p + r, _EPS)


def binary_error(y, pred, w):
    return jnp.sum(w * (pred != y)) / jnp.maximum(jnp.sum(w), _EPS)


def multiclass_weighted(y, pred, w, n_classes: int, which: str):
    """Weighted Precision/Recall/F1 over ``n_classes`` (MulticlassMetrics)."""
    yi = y.astype(jnp.int32)
    pi = pred.astype(jnp.int32)
    oh_y = (jnp.arange(n_classes)[None, :] == yi[:, None]) * w[:, None]
    oh_p = (jnp.arange(n_classes)[None, :] == pi[:, None]) * w[:, None]
    conf = oh_y.T @ (jnp.arange(n_classes)[None, :]
                     == pi[:, None]).astype(w.dtype)   # [true, pred]
    tp = jnp.diagonal(conf)
    per_true = oh_y.sum(0)            # class weight numerators
    per_pred = oh_p.sum(0)
    prec = tp / jnp.maximum(per_pred, _EPS)
    rec = tp / jnp.maximum(per_true, _EPS)
    f1 = 2.0 * prec * rec / jnp.maximum(prec + rec, _EPS)
    cw = per_true / jnp.maximum(jnp.sum(w), _EPS)
    vals = {"Precision": prec, "Recall": rec, "F1": f1}[which]
    return jnp.sum(cw * vals)


def multiclass_error(y, pred, w):
    return jnp.sum(w * (pred != y)) / jnp.maximum(jnp.sum(w), _EPS)


def rmse(y, pred, w):
    W = jnp.maximum(jnp.sum(w), _EPS)
    return jnp.sqrt(jnp.sum(w * (y - pred) ** 2) / W)


def mse(y, pred, w):
    return jnp.sum(w * (y - pred) ** 2) / jnp.maximum(jnp.sum(w), _EPS)


def mae(y, pred, w):
    return jnp.sum(w * jnp.abs(y - pred)) / jnp.maximum(jnp.sum(w), _EPS)


def r2(y, pred, w):
    W = jnp.maximum(jnp.sum(w), _EPS)
    mean = jnp.sum(w * y) / W
    var = jnp.sum(w * (y - mean) ** 2) / W
    m = jnp.sum(w * (y - pred) ** 2) / W
    return jnp.where(var > 0, 1.0 - m / var, 0.0)


#: (task, metric name) → callable; signature depends on the metric kind
DEVICE_METRICS = {
    ("binary", "AuROC"): ("score", auroc),
    ("binary", "AuPR"): ("score", aupr),
    ("binary", "Precision"): ("pred", binary_precision),
    ("binary", "Recall"): ("pred", binary_recall),
    ("binary", "F1"): ("pred", binary_f1),
    ("binary", "Error"): ("pred", binary_error),
    ("multiclass", "Error"): ("pred", multiclass_error),
    ("regression", "RootMeanSquaredError"): ("pred", rmse),
    ("regression", "MeanSquaredError"): ("pred", mse),
    ("regression", "MeanAbsoluteError"): ("pred", mae),
    ("regression", "R2"): ("pred", r2),
}


def device_metric_fn(task: str, metric_name: str, n_classes: int = 2):
    """→ fn(y, pred, prob, w) → scalar, or None if not device-supported.

    ``prob`` may be [n, k] class probabilities or an empty [n, 0] array
    (regression); binary score metrics use prob[:, 1] when available,
    falling back to ``pred``.
    """
    if task == "multiclass" and metric_name in ("Precision", "Recall", "F1"):
        def mc(y, pred, prob, w):
            return multiclass_weighted(y, pred, w, n_classes, metric_name)
        return mc
    entry = DEVICE_METRICS.get((task, metric_name))
    if entry is None:
        return None
    kind, fn = entry
    if kind == "score":
        def scored(y, pred, prob, w):
            s = prob[:, 1] if (prob.ndim == 2 and prob.shape[1] >= 2) else pred
            return fn(y, s, w)
        return scored

    def predded(y, pred, prob, w):
        return fn(y, pred, w)
    return predded
