"""Metric computations — pure array math (numpy or jax.numpy).

Parity: Spark MLlib's ``BinaryClassificationMetrics`` /
``MulticlassMetrics`` / ``RegressionMetrics`` as consumed by the reference's
evaluators (``core/.../evaluators/OpBinaryClassificationEvaluator.scala:180-203``
etc.). AuROC/AuPR follow MLlib's threshold-curve construction: thresholds at
every distinct score, ROC prepended with (0,0) and appended with (1,1),
PR prepended with (0, p@first-threshold); areas by trapezoid.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["binary_metrics", "multiclass_metrics", "regression_metrics",
           "auroc", "aupr", "confusion_binary"]


def _curve_points(labels: np.ndarray, scores: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, float, float]:
    """Cumulative TP/FP at each distinct score threshold (descending)."""
    order = np.argsort(-scores, kind="stable")
    s = scores[order]
    y = labels[order]
    # group equal scores: take cumulative counts at last index of each group
    boundaries = np.nonzero(np.diff(s))[0]
    idx = np.concatenate([boundaries, [len(s) - 1]])
    tp_cum = np.cumsum(y)[idx].astype(np.float64)
    fp_cum = np.cumsum(1 - y)[idx].astype(np.float64)
    p = float(labels.sum())
    n = float(len(labels) - p)
    return tp_cum, fp_cum, p, n


def auroc(labels: np.ndarray, scores: np.ndarray) -> float:
    tp, fp, p, n = _curve_points(labels, scores)
    if p == 0 or n == 0:
        return 0.0
    tpr = np.concatenate([[0.0], tp / p, [1.0]])
    fpr = np.concatenate([[0.0], fp / n, [1.0]])
    return float(np.trapezoid(tpr, fpr))


def aupr(labels: np.ndarray, scores: np.ndarray) -> float:
    tp, fp, p, _ = _curve_points(labels, scores)
    if p == 0:
        return 0.0
    precision = tp / np.maximum(tp + fp, 1e-12)
    recall = tp / p
    # MLlib prepends (0, precision@first)
    precision = np.concatenate([[precision[0]], precision])
    recall = np.concatenate([[0.0], recall])
    return float(np.trapezoid(precision, recall))


def confusion_binary(labels: np.ndarray, predictions: np.ndarray
                     ) -> Tuple[float, float, float, float]:
    tp = float(np.sum((predictions == 1) & (labels == 1)))
    tn = float(np.sum((predictions == 0) & (labels == 0)))
    fp = float(np.sum((predictions == 1) & (labels == 0)))
    fn = float(np.sum((predictions == 0) & (labels == 1)))
    return tp, tn, fp, fn


def binary_metrics(labels: np.ndarray, predictions: np.ndarray,
                   scores: Optional[np.ndarray] = None) -> Dict[str, float]:
    """Precision/Recall/F1/Error/AuROC/AuPR/TP/TN/FP/FN
    (OpBinaryClassificationEvaluator.scala:180-203)."""
    labels = np.asarray(labels, dtype=np.float64)
    predictions = np.asarray(predictions, dtype=np.float64)
    tp, tn, fp, fn = confusion_binary(labels, predictions)
    precision = tp / (tp + fp) if tp + fp > 0 else 0.0
    recall = tp / (tp + fn) if tp + fn > 0 else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall > 0 else 0.0)
    err = (fp + fn) / max(len(labels), 1)
    out = {"Precision": precision, "Recall": recall, "F1": f1, "Error": err,
           "TP": tp, "TN": tn, "FP": fp, "FN": fn}
    if scores is not None:
        scores = np.asarray(scores, dtype=np.float64)
        out["AuROC"] = auroc(labels, scores)
        out["AuPR"] = aupr(labels, scores)
    return out


def multiclass_metrics(labels: np.ndarray, predictions: np.ndarray
                       ) -> Dict[str, float]:
    """Weighted Precision/Recall/F1 + Error (MulticlassMetrics parity)."""
    labels = np.asarray(labels).astype(np.int64)
    predictions = np.asarray(predictions).astype(np.int64)
    classes = np.unique(np.concatenate([labels, predictions]))
    n = max(len(labels), 1)
    w_prec = w_rec = w_f1 = 0.0
    for c in classes:
        tp = float(np.sum((predictions == c) & (labels == c)))
        fp = float(np.sum((predictions == c) & (labels != c)))
        fn = float(np.sum((predictions != c) & (labels == c)))
        weight = float(np.sum(labels == c)) / n
        prec = tp / (tp + fp) if tp + fp > 0 else 0.0
        rec = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
        w_prec += weight * prec
        w_rec += weight * rec
        w_f1 += weight * f1
    error = float(np.mean(labels != predictions)) if len(labels) else 0.0
    return {"Precision": w_prec, "Recall": w_rec, "F1": w_f1, "Error": error}


def regression_metrics(labels: np.ndarray, predictions: np.ndarray
                       ) -> Dict[str, float]:
    labels = np.asarray(labels, dtype=np.float64)
    predictions = np.asarray(predictions, dtype=np.float64)
    resid = labels - predictions
    mse = float(np.mean(resid ** 2)) if len(labels) else 0.0
    mae = float(np.mean(np.abs(resid))) if len(labels) else 0.0
    var = float(np.mean((labels - labels.mean()) ** 2)) if len(labels) else 0.0
    r2 = 1.0 - mse / var if var > 0 else 0.0
    return {"RootMeanSquaredError": float(np.sqrt(mse)),
            "MeanSquaredError": mse, "MeanAbsoluteError": mae, "R2": r2}


def binary_threshold_curves(labels: np.ndarray, scores: np.ndarray,
                            max_points: int = 200) -> Dict[str, list]:
    """Threshold curves (BinaryClassificationMetrics parity): thresholds +
    precision/recall/TPR/FPR by threshold, downsampled to ≤ max_points."""
    labels = np.asarray(labels, dtype=np.float64)
    scores = np.asarray(scores, dtype=np.float64)
    if len(labels) == 0:
        return {"thresholds": [], "precisionByThreshold": [],
                "recallByThreshold": [], "falsePositiveRateByThreshold": []}
    tp, fp, p, n = _curve_points(labels, scores)
    order = np.argsort(-scores, kind="stable")
    s = scores[order]
    idx = np.concatenate([np.nonzero(np.diff(s))[0], [len(s) - 1]])
    thresholds = s[idx]
    precision = tp / np.maximum(tp + fp, 1e-12)
    recall = tp / max(p, 1e-12)
    fpr = fp / max(n, 1e-12)
    if thresholds.size > max_points:
        pick = np.linspace(0, thresholds.size - 1, max_points).astype(int)
        thresholds, precision, recall, fpr = (
            thresholds[pick], precision[pick], recall[pick], fpr[pick])
    return {"thresholds": thresholds.tolist(),
            "precisionByThreshold": precision.tolist(),
            "recallByThreshold": recall.tolist(),
            "falsePositiveRateByThreshold": fpr.tolist()}


def multiclass_threshold_metrics(labels: np.ndarray, probabilities: np.ndarray,
                                 top_ns: Tuple[int, ...] = (1, 3),
                                 thresholds: Optional[np.ndarray] = None
                                 ) -> Dict[str, object]:
    """Top-N threshold metrics — exact port of
    ``OpMultiClassificationEvaluator.calculateThresholdMetrics``
    (``core/.../evaluators/OpMultiClassificationEvaluator.scala:154-229``).

    Per row with true-class score ``s_true = probs[label]`` and top score
    ``s_max = max(probs)``, at each threshold t:

    * label within the top-N indices and ``t ≤ s_true`` → **correct**;
    * otherwise ``t ≤ s_max`` → **incorrect** (note: a topN hit whose
      true-class score falls below t while the top score stays above is
      *incorrect*, not merely unpredicted — the serving-threshold
      semantics the round-3 draft got wrong);
    * ``t > s_max`` → **no prediction**.

    correct + incorrect + noPrediction = n at every (topN, threshold).
    Defaults match the reference: topNs (1, 3), thresholds 0.00..1.00
    step 0.01 (``setDefault(thresholds, (0 to 100).map(_ / 100.0))``).
    Vectorized: one argsort + cumulative histograms per topN.
    """
    labels = np.asarray(labels).astype(np.int64)
    probs = np.asarray(probabilities, dtype=np.float64)
    if thresholds is None:
        thresholds = np.linspace(0.0, 1.0, 101)
    # per-threshold counts are order-independent; sort so the cutoff
    # searches are well-defined for any user-supplied order (the output
    # reports the sorted thresholds)
    thresholds = np.sort(np.asarray(thresholds, dtype=np.float64))
    n_t = len(thresholds)
    out: Dict[str, object] = {"topNs": list(top_ns),
                              "thresholds": thresholds.tolist(),
                              "correctCounts": {}, "incorrectCounts": {},
                              "noPredictionCounts": {}}
    if probs.size == 0:
        for k in top_ns:
            out["correctCounts"][k] = [0] * n_t
            out["incorrectCounts"][k] = [0] * n_t
            out["noPredictionCounts"][k] = [0] * n_t
        return out
    n_rows, n_cls = probs.shape
    safe_lab = np.clip(labels, 0, n_cls - 1)
    true_score = probs[np.arange(n_rows), safe_lab]
    top_score = probs.max(axis=1)
    rank_order = np.argsort(-probs, axis=1, kind="stable")   # [n, K]
    # cutoff index: first threshold STRICTLY above the score — the row
    # counts (as correct/predicted) at indices < cutoff
    true_cut = np.searchsorted(thresholds, true_score, side="right")
    max_cut = np.searchsorted(thresholds, top_score, side="right")

    def below_counts(cuts):
        """[n_t] array: c[i] = #rows with cutoff > i (i.e. counted at i)."""
        h = np.bincount(cuts, minlength=n_t + 1)[:n_t + 1]
        ge = np.cumsum(h[::-1])[::-1]                 # ge[j] = #cuts ≥ j
        return ge[1:]                                 # #cuts > i = ge[i+1]

    for k in top_ns:
        in_topk = (rank_order[:, :min(k, n_cls)]
                   == labels[:, None]).any(axis=1)
        cor_at = below_counts(true_cut[in_topk])
        # topN hits turn incorrect between the true-score and top-score
        # cutoffs; misses are incorrect up to the top-score cutoff
        inc_at = (below_counts(max_cut[in_topk])
                  - below_counts(true_cut[in_topk])
                  + below_counts(max_cut[~in_topk]))
        out["correctCounts"][k] = cor_at.tolist()
        out["incorrectCounts"][k] = inc_at.tolist()
        out["noPredictionCounts"][k] = (n_rows - cor_at - inc_at).tolist()
    return out
