"""Evaluators — typed metric computation over scored stores.

Parity: ``core/.../evaluators/*``: ``OpEvaluatorBase.evaluateAll`` returns a
full typed metrics bundle; ``evaluate`` returns the single selection metric;
``Evaluators.BinaryClassification.auPR()``-style factories pick the metric
(``Evaluators.scala:40``). Each evaluator reads the label column and the
Prediction struct column (flattening pred/raw/prob —
``OpEvaluatorBase.scala:168-193`` — is free here: PredictionColumn is
already a struct of arrays).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..columns import ColumnStore, NumericColumn, PredictionColumn
from ..features import Feature
from .metrics import (aupr, auroc, binary_metrics, binary_threshold_curves,
                      multiclass_metrics, multiclass_threshold_metrics,
                      regression_metrics)

__all__ = ["OpEvaluatorBase", "BinaryClassificationEvaluator",
           "MultiClassificationEvaluator", "RegressionEvaluator",
           "BinScoreEvaluator", "Evaluators",
           "binary_metrics", "multiclass_metrics", "regression_metrics",
           "multiclass_threshold_metrics", "binary_threshold_curves"]


class OpEvaluatorBase:
    """Reads (label, prediction) columns; computes metrics."""

    #: metric names where larger is better
    large_better_metrics = frozenset({
        "AuROC", "AuPR", "Precision", "Recall", "F1", "R2"})

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None,
                 metric_name: Optional[str] = None):
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.metric_name = metric_name or self.default_metric
        self.is_larger_better = self.metric_name in self.large_better_metrics

    default_metric = "AuROC"
    name = "evaluator"

    def set_columns(self, label: Any, prediction: Any) -> "OpEvaluatorBase":
        self.label_col = label.name if isinstance(label, Feature) else label
        self.prediction_col = (prediction.name if isinstance(prediction, Feature)
                               else prediction)
        return self

    def _extract(self, store: ColumnStore):
        label = store[self.label_col]
        pred_col = store[self.prediction_col]
        y = np.asarray(label.values, dtype=np.float64)
        if isinstance(pred_col, PredictionColumn):
            return y, pred_col
        raise TypeError(
            f"Prediction column {self.prediction_col!r} is "
            f"{type(pred_col).__name__}, expected PredictionColumn")

    def evaluate_all(self, store: ColumnStore) -> Dict[str, float]:
        raise NotImplementedError

    def evaluate(self, store: ColumnStore) -> float:
        return self.evaluate_all(store)[self.metric_name]


class BinaryClassificationEvaluator(OpEvaluatorBase):
    name = "binEval"
    default_metric = "AuROC"

    def __init__(self, threshold_curves: bool = False, **kw):
        super().__init__(**kw)
        #: include precision/recall/FPR-by-threshold curves in the bundle
        #: (BinaryClassificationMetrics parity; off by default — the
        #: curves are lists, not scalars)
        self.threshold_curves = threshold_curves

    def evaluate_all(self, store: ColumnStore) -> Dict[str, Any]:
        y, pred = self._extract(store)
        scores = (pred.probability[:, 1] if pred.probability.shape[1] >= 2
                  else pred.prediction)
        out: Dict[str, Any] = binary_metrics(y, pred.prediction, scores)
        if self.threshold_curves:
            out["ThresholdCurves"] = binary_threshold_curves(y, scores)
        return out


class MultiClassificationEvaluator(OpEvaluatorBase):
    """Weighted P/R/F1/Error + topN × confidence-threshold metrics
    (``OpMultiClassificationEvaluator.scala:120-229``)."""

    name = "multiEval"
    default_metric = "F1"

    def __init__(self, top_ns=(1, 3), thresholds=None, **kw):
        super().__init__(**kw)
        self.top_ns = tuple(top_ns)
        self.thresholds = thresholds

    def evaluate_all(self, store: ColumnStore) -> Dict[str, Any]:
        y, pred = self._extract(store)
        out: Dict[str, Any] = multiclass_metrics(y, pred.prediction)
        if pred.probability.ndim == 2 and pred.probability.shape[1] >= 2:
            out["ThresholdMetrics"] = multiclass_threshold_metrics(
                y, pred.probability, top_ns=self.top_ns,
                thresholds=self.thresholds)
        return out


class RegressionEvaluator(OpEvaluatorBase):
    name = "regEval"
    default_metric = "RootMeanSquaredError"

    def evaluate_all(self, store: ColumnStore) -> Dict[str, float]:
        y, pred = self._extract(store)
        return regression_metrics(y, pred.prediction)


class BinScoreEvaluator(OpEvaluatorBase):
    """Calibration bins + Brier score (OpBinScoreEvaluator.scala)."""

    name = "binScoreEval"
    default_metric = "BrierScore"

    def __init__(self, num_bins: int = 100, **kw):
        super().__init__(**kw)
        self.num_bins = num_bins

    def evaluate_all(self, store: ColumnStore) -> Dict[str, Any]:
        y, pred = self._extract(store)
        scores = (pred.probability[:, 1] if pred.probability.shape[1] >= 2
                  else pred.prediction)
        brier = float(np.mean((scores - y) ** 2)) if len(y) else 0.0
        edges = np.linspace(0.0, 1.0, self.num_bins + 1)
        idx = np.clip(np.digitize(scores, edges) - 1, 0, self.num_bins - 1)
        counts = np.bincount(idx, minlength=self.num_bins)
        sum_scores = np.bincount(idx, weights=scores, minlength=self.num_bins)
        sum_labels = np.bincount(idx, weights=y, minlength=self.num_bins)
        nonzero = counts > 0
        return {
            "BrierScore": brier,
            "BinCenters": ((edges[:-1] + edges[1:]) / 2)[nonzero].tolist(),
            "NumberOfDataPoints": counts[nonzero].tolist(),
            "AverageScore": (sum_scores[nonzero] / counts[nonzero]).tolist(),
            "AverageConversionRate": (sum_labels[nonzero] / counts[nonzero]).tolist(),
        }


class _EvalFactory:
    def __init__(self, cls):
        self._cls = cls

    def __call__(self, **kw):
        return self._cls(**kw)

    def __getattr__(self, metric: str):
        # Evaluators.BinaryClassification.auPR() style
        canonical = {"aupr": "AuPR", "auroc": "AuROC", "precision": "Precision",
                     "recall": "Recall", "f1": "F1", "error": "Error",
                     "rmse": "RootMeanSquaredError", "mse": "MeanSquaredError",
                     "mae": "MeanAbsoluteError", "r2": "R2"}
        m = canonical.get(metric.lower())
        if m is None:
            raise AttributeError(metric)
        cls = self._cls
        return lambda **kw: cls(metric_name=m, **kw)


class Evaluators:
    """Factory (Evaluators.scala:40)."""

    BinaryClassification = _EvalFactory(BinaryClassificationEvaluator)
    MultiClassification = _EvalFactory(MultiClassificationEvaluator)
    Regression = _EvalFactory(RegressionEvaluator)
    BinScore = _EvalFactory(BinScoreEvaluator)
