"""DAG computation — layering stages for staged fit/transform.

Mirrors ``FitStagesUtil.computeDAG`` (``core/.../utils/stages/FitStagesUtil.scala:173-198``):
collect all ancestor stages of the result features, group them into layers by
**max distance from the results** (deepest layer first), dedup stages that
feed multiple results. Each layer's stages are independent given previous
layers' outputs — the workflow runtime fits a layer's estimators together
and fuses its transforms into one pass.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from .features import Feature
from .stages.base import OpPipelineStage
from .stages.generator import FeatureGeneratorStage

__all__ = ["compute_dag", "StagesDAG"]

StagesDAG = List[List[OpPipelineStage]]


def compute_dag(result_features: Sequence[Feature],
                include_generators: bool = False) -> StagesDAG:
    """Layers of stages, deepest (closest to raw data) first."""
    distances: Dict[str, int] = {}
    stages: Dict[str, OpPipelineStage] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            key = stage.uid
            existing = stages.get(key)
            if existing is not None and existing is not stage:
                # two DISTINCT stages sharing one uid used to silently
                # collapse into a single DAG node here (the dict
                # overwrite), dropping one of them from the fit plan.
                # Surfaced statically as lint rule TMG102.
                raise ValueError(
                    f"duplicate stage uid {key!r}: "
                    f"{existing.stage_name()} and {stage.stage_name()} "
                    f"are distinct stages sharing one uid — every stage "
                    "needs its own uid (pass uid=None to autogenerate)")
            stages[key] = stage
            if distances.get(key, -1) < d:
                distances[key] = d

    if not include_generators:
        for key in [k for k, s in stages.items()
                    if isinstance(s, FeatureGeneratorStage)]:
            del stages[key]
            del distances[key]

    if not stages:
        return []

    max_d = max(distances.values())
    layers: StagesDAG = [[] for _ in range(max_d + 1)]
    # deepest first: distance max_d → layer 0
    for key, stage in stages.items():
        layers[max_d - distances[key]].append(stage)
    # deterministic order within layer
    for layer in layers:
        layer.sort(key=lambda s: s.uid)
    return [l for l in layers if l]


def all_stages(result_features: Sequence[Feature],
               include_generators: bool = False) -> List[OpPipelineStage]:
    return [s for layer in compute_dag(result_features, include_generators)
            for s in layer]


def cut_dag(result_features: Sequence[Feature]):
    """Split the DAG around the ModelSelector for leak-free workflow CV.

    Mirrors ``FitStagesUtil.cutDAG`` (:305-358): find the single
    ModelSelector (max 1 enforced, :313), then split into

    * ``before`` — stages safe to fit ONCE on the full training split:
      everything not downstream of a label-aware ("mixing": consumes both
      response and predictor inputs) ancestor of the selector;
    * ``during`` — the selector's ancestor layers from the first mixing
      layer onward (SanityChecker, DecisionTreeBucketizer, …): these see
      the label, so each CV fold must re-fit them on in-fold data;
    * ``after`` — layers shallower than the selector.

    Returns ``(selector, before, during, after)``; selector is None when
    the DAG has no ModelSelector (during/after empty).
    """
    from .models.selector import ModelSelector

    dag = compute_dag(result_features)
    selectors = [s for layer in dag for s in layer
                 if isinstance(s, ModelSelector)]
    if len(selectors) > 1:
        raise ValueError(
            f"Workflow can contain at most 1 ModelSelector, found "
            f"{len(selectors)}: {[s.uid for s in selectors]}")
    if not selectors:
        return None, dag, [], []
    ms = selectors[0]
    ms_layer = next(i for i, layer in enumerate(dag) if ms in layer)
    after = dag[ms_layer + 1:]

    # selector's ancestor DAG (deepest first), selector's own layer dropped
    ms_dag = compute_dag(list(ms.input_features))

    def mixes(stage) -> bool:
        ins = stage.input_features
        return (any(f.is_response for f in ins)
                and any(not f.is_response for f in ins))

    first = next((i for i, layer in enumerate(ms_dag)
                  if any(mixes(s) for s in layer)), None)
    during_uids = (set() if first is None else
                   {s.uid for layer in ms_dag[first:] for s in layer})

    def depends_on_during(stage) -> bool:
        if not during_uids:
            return False
        try:
            out = stage.get_output()
        except ValueError:
            return False
        return any(p.uid in during_uids for p in out.parent_stages())

    before: StagesDAG = []
    during: StagesDAG = []
    for layer in dag[:ms_layer + 1]:
        b = [s for s in layer if s is not ms and s.uid not in during_uids
             and not depends_on_during(s)]
        d = [s for s in layer if s is not ms and
             (s.uid in during_uids or depends_on_during(s))]
        if b:
            before.append(b)
        if d:
            during.append(d)
    return ms, before, during, after
