"""DAG computation — layering stages for staged fit/transform.

Mirrors ``FitStagesUtil.computeDAG`` (``core/.../utils/stages/FitStagesUtil.scala:173-198``):
collect all ancestor stages of the result features, group them into layers by
**max distance from the results** (deepest layer first), dedup stages that
feed multiple results. Each layer's stages are independent given previous
layers' outputs — the workflow runtime fits a layer's estimators together
and fuses its transforms into one pass.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from .features import Feature
from .stages.base import OpPipelineStage
from .stages.generator import FeatureGeneratorStage

__all__ = ["compute_dag", "StagesDAG"]

StagesDAG = List[List[OpPipelineStage]]


def compute_dag(result_features: Sequence[Feature],
                include_generators: bool = False) -> StagesDAG:
    """Layers of stages, deepest (closest to raw data) first."""
    distances: Dict[str, int] = {}
    stages: Dict[str, OpPipelineStage] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            key = stage.uid
            stages[key] = stage
            if distances.get(key, -1) < d:
                distances[key] = d

    if not include_generators:
        for key in [k for k, s in stages.items()
                    if isinstance(s, FeatureGeneratorStage)]:
            del stages[key]
            del distances[key]

    if not stages:
        return []

    max_d = max(distances.values())
    layers: StagesDAG = [[] for _ in range(max_d + 1)]
    # deepest first: distance max_d → layer 0
    for key, stage in stages.items():
        layers[max_d - distances[key]].append(stage)
    # deterministic order within layer
    for layer in layers:
        layer.sort(key=lambda s: s.uid)
    return [l for l in layers if l]


def all_stages(result_features: Sequence[Feature],
               include_generators: bool = False) -> List[OpPipelineStage]:
    return [s for layer in compute_dag(result_features, include_generators)
            for s in layer]
