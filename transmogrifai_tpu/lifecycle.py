"""Model lifecycle tier — versioned registry + serving-time drift sentinel.

PR 8's model server holds a static fleet: no versions, no safe way to
swap a retrained model under load, and nothing watching whether live
traffic still resembles the training data. This module supplies the two
stateful halves of the production train→validate→deploy loop (the
TFX/TensorFlow-paper continuous-deployment story, PAPERS.md); the
shadow/canary rollout controller that consumes them lives in
``server.py``.

* :class:`ModelRegistry` — an on-disk versioned store of exported
  models. A version id is the AOT manifest's fitted-state digest (the
  same ``state_digest`` the bank loader verifies, so "version" and
  "the weights actually served" can never diverge); each version
  records its model dir, bank path, params digest, train metrics and
  plan report. ``promote``/``rollback`` move an atomic ``CURRENT``
  pointer (temp + ``os.replace``, the cost-db discipline): a crashed
  promote leaves the OLD pointer intact — never a half-switched fleet.
  ``promote`` passes through the ``lifecycle.promote`` fault site so
  chaos plans can score the rollout path deterministically.

* :class:`DriftSentinel` — streaming per-feature
  :class:`~transmogrifai_tpu.filters.distribution.FeatureDistribution`
  sketches accumulated on the server's score path (host-only numpy,
  no device work) and compared each window against the train-time
  distributions persisted with the model
  (``RawFeatureFilterResults.training_distributions`` — the
  RawFeatureFilter's batch pre-check, now continuous). A ring of
  sub-window sketches makes the comparison window slide. Threshold
  crossings emit the TMG6xx advisory family through the existing
  failOn/lintSuppress machinery, an ``on_drift`` RunListener hook and
  ``drift.*`` gauges.

The always-on :func:`lifecycle_stats` tallies follow the
``engine_cache_stats`` discipline: stamped on every runner/bench
metrics doc, telemetry on or off.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import resilience, telemetry
from .utils import locks

logger = logging.getLogger(__name__)

__all__ = ["ModelRegistry", "RegistryError", "DriftSentinel",
           "version_of_export", "lifecycle_stats", "reset_lifecycle_stats",
           "DEFAULT_DRIFT_WINDOW_ROWS", "DEFAULT_DRIFT_SUBWINDOWS",
           "DEFAULT_JS_THRESHOLD", "DEFAULT_FILL_DELTA_THRESHOLD",
           "DEFAULT_FILL_RATIO_THRESHOLD"]

#: rows in one sliding comparison window (the sentinel compares the
#: merged ring against the train-time distributions once this many live
#: rows are in the ring)
DEFAULT_DRIFT_WINDOW_ROWS = 2048

#: sub-window sketches in the ring — the window slides by 1/N of its
#: span instead of tumbling
DEFAULT_DRIFT_SUBWINDOWS = 4

#: train↔live JS divergence (log2, bounded [0,1]) above which a feature
#: is drifting (TMG601). Tighter than RawFeatureFilter's 0.90 exclusion
#: gate: serving wants an early advisory, not a blacklist.
DEFAULT_JS_THRESHOLD = 0.25

#: |train fill − live fill| above which a feature's fill rate shifted
#: (TMG602)
DEFAULT_FILL_DELTA_THRESHOLD = 0.25

#: max(fill)/min(fill) ratio above which TMG602 also fires (catches a
#: 1%→20% shift the absolute delta misses)
DEFAULT_FILL_RATIO_THRESHOLD = 20.0


# ---------------------------------------------------------------------------
# always-on tallies (runner/bench docs stamp these; telemetry mirrors)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"registered": 0, "promotions": 0, "rollbacks": 0,
          "deploys": 0, "auto_promotions": 0, "auto_rollbacks": 0,
          "drift_windows": 0, "drift_advisories": 0,
          "drift_dropped_batches": 0, "sentinel_errors": 0,
          "shadow_requests": 0, "shadow_parity_ok": 0,
          "shadow_parity_mismatch": 0, "canary_requests": 0}


def lifecycle_stats() -> Dict[str, int]:
    """Snapshot of the process-wide lifecycle tallies (always on, the
    ``engine_cache_stats`` discipline): registry traffic, rollout
    deploys/promotions/rollbacks, drift windows compared + advisories
    raised, shadow parity evidence and canary routing counts."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_lifecycle_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def tally(key: str, n: int = 1) -> None:
    """Bump one lifecycle tally (server.py's rollout controller shares
    this table so every lifecycle fact lands in ONE stamped block)."""
    with _TALLY_LOCK:
        _TALLY[key] += n
    telemetry.counter(f"lifecycle.{key}").inc(n)  # lint: metric-name — keys are the fixed lifecycle_stats tally catalog


# ---------------------------------------------------------------------------
# version identity
# ---------------------------------------------------------------------------


def _file_digest(h, path: str) -> None:
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)


def _artifact_digest(model_dir: str) -> str:
    """blake2b-128 over a saved model's ``model.json`` + its referenced
    weights archive — the bankless fallback identity."""
    from .model_io import MODEL_JSON, WEIGHTS_NPZ
    h = hashlib.blake2b(digest_size=16)
    mj = os.path.join(model_dir, MODEL_JSON)
    _file_digest(h, mj)
    with open(mj) as fh:
        doc = json.load(fh)
    weights = os.path.join(model_dir, doc.get("weightsFile", WEIGHTS_NPZ))
    if os.path.exists(weights):
        _file_digest(h, weights)
    return h.hexdigest()


def version_of_export(model_dir: str, bank_dir: Optional[str] = None) -> str:
    """The version id for a saved model: the AOT manifest's
    ``stateDigest`` when an export directory ships one (also recorded in
    the bankless StableHLO metadata), else a digest of the saved
    artifact bytes. Using the state digest means a version NAMES the
    fitted weights: the bank loader already refuses to serve a model
    whose arrays differ from its manifest, so registry version and
    served weights cannot silently diverge."""
    if bank_dir:
        from . import aot, serving
        manifest, _ = aot.read_manifest(bank_dir)
        if manifest and manifest.get("stateDigest"):
            return str(manifest["stateDigest"])
        meta_path = os.path.join(bank_dir, serving._SCORE_META)
        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
            if meta.get("stateDigest"):
                return str(meta["stateDigest"])
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            pass
    return _artifact_digest(model_dir)


def _params_digest(model_dir: str) -> Optional[str]:
    """blake2b-128 over the saved model's run parameters block (the
    OpParams the model trained under) — a cheap "same config?" probe
    between versions."""
    from .model_io import MODEL_JSON
    try:
        with open(os.path.join(model_dir, MODEL_JSON)) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    blob = json.dumps(doc.get("parameters") or {}, sort_keys=True,
                      default=str).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


# ---------------------------------------------------------------------------
# ModelRegistry
# ---------------------------------------------------------------------------


class RegistryError(Exception):
    """Registry misuse: unknown model/version, no rollback target."""


_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,128}$")
_VID_RE = re.compile(r"^[A-Za-z0-9._-]{1,200}$")

VERSIONS_DIR = "versions"
POINTER_FILE = "CURRENT.json"
REGISTRY_FORMAT_VERSION = 1


class ModelRegistry:
    """On-disk versioned model store with an atomic ``current`` pointer.

    Layout (one subdirectory per model name)::

        <root>/<name>/versions/<vid>.json   # one file per version record
        <root>/<name>/CURRENT.json          # {"current": vid, "previous": vid}

    Every file is written tmp + ``os.replace`` (the cost-db
    discipline), so readers always see a complete document and a
    promote that dies at ANY instant leaves either the old pointer or
    the new one — never a torn mix. One file PER VERSION (not one
    versions.json) means concurrent registrations from different
    processes — the CLI, a training runner and the serve tier share one
    registry directory — can never lose each other's records to a
    read-modify-write race: each register is a single atomic write of
    its own file. The registry stores metadata and paths; the artifacts
    themselves stay where the exporter wrote them (a registry is a
    routing table, not a blob store)."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = locks.witness_lock("lifecycle.ModelRegistry._lock")

    # -- paths / io --------------------------------------------------------
    def _mdir(self, name: str, create: bool = False) -> str:
        if not _NAME_RE.match(name or ""):
            raise RegistryError(
                f"invalid model name {name!r} (alnum . _ - only)")
        d = os.path.join(self.root, name)
        if create:
            os.makedirs(d, exist_ok=True)
        return d

    @staticmethod
    def _read_json(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as fh:
                return json.load(fh)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
            raise RegistryError(f"registry file unreadable at {path!r}: "
                                f"{e}") from e

    @staticmethod
    def _write_json_atomic(path: str, doc: Dict[str, Any]) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, default=str)
        os.replace(tmp, path)

    def _vdir(self, name: str, create: bool = False) -> str:
        d = os.path.join(self._mdir(name, create=create), VERSIONS_DIR)
        if create:
            os.makedirs(d, exist_ok=True)
        return d

    def _vpath(self, name: str, version: str) -> str:
        if not _VID_RE.match(str(version) or ""):
            raise RegistryError(
                f"invalid version id {version!r} (alnum . _ - only)")
        return os.path.join(self._vdir(name), f"{version}.json")

    def _pointer_doc(self, name: str) -> Dict[str, Any]:
        doc = self._read_json(os.path.join(self._mdir(name), POINTER_FILE))
        return doc or {"current": None, "previous": None}

    def pointer_lock_path(self, name: str) -> str:
        """The persistent flock file that serializes pointer writers
        across processes — exposed so the fleet chaos suite can prove
        the discipline's crash story: a SIGKILLed holder's kernel lock
        releases automatically (no staleness heuristic to mis-steal
        from a merely-slow holder), so a dead fleet worker can never
        wedge a sibling's promote (tests/test_fleet.py)."""
        return os.path.join(self._mdir(name, create=True),
                            POINTER_FILE + ".lock")

    @contextlib.contextmanager
    def _pointer_mutation(self, name: str, timeout_s: float = 10.0):
        """Cross-process mutual exclusion for the pointer's
        read-modify-write (promote/rollback compute ``previous`` from
        the pointer they read — two processes racing would leave the
        loser's version recorded in neither field). A kernel
        ``flock`` on a persistent lock file serializes writers across
        processes: a crashed holder's lock releases automatically (no
        staleness heuristic to mis-steal from a merely-slow holder),
        and a live contender that can't acquire within ``timeout_s``
        fails LOUDLY instead of proceeding unlocked. Readers never
        take it — the pointer file itself stays a single atomic
        document."""
        import fcntl
        path = self.pointer_lock_path(name)
        fd = os.open(path, os.O_CREAT | os.O_WRONLY, 0o644)
        t0 = time.monotonic()
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except OSError:
                    if time.monotonic() - t0 > timeout_s:
                        raise RegistryError(
                            f"pointer lock for {name!r} held elsewhere "
                            f"for > {timeout_s:g}s ({path})")
                    time.sleep(0.01)  # lint: lock-blocking — backoff after a FAILED flock attempt; nothing is held here (the analyzer scopes flocks to the whole function)
            locks.witness_acquire("lifecycle.pointer.flock")
            yield
        finally:
            locks.witness_release("lifecycle.pointer.flock")
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                pass
            os.close(fd)

    # -- queries -----------------------------------------------------------
    def models(self) -> List[str]:
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, d,
                                                    VERSIONS_DIR)))

    def versions(self, name: str) -> List[Dict[str, Any]]:
        """All version records for ``name``, oldest first."""
        vdir = self._vdir(name)
        try:
            files = [f for f in os.listdir(vdir) if f.endswith(".json")]
        except FileNotFoundError:
            return []
        recs = [r for r in (self._read_json(os.path.join(vdir, f))
                            for f in files) if r is not None]
        recs.sort(key=lambda r: (r.get("registeredAt", 0.0), r["version"]))
        return recs

    def record(self, name: str, version: str) -> Dict[str, Any]:
        rec = self._read_json(self._vpath(name, str(version)))
        if rec is None:
            raise RegistryError(
                f"model {name!r} has no version {version!r} (have: "
                f"{[r['version'] for r in self.versions(name)]})")
        return rec

    def current(self, name: str) -> Optional[str]:
        return self._pointer_doc(name).get("current")

    def previous(self, name: str) -> Optional[str]:
        return self._pointer_doc(name).get("previous")

    def resolve(self, name: str) -> Dict[str, Any]:
        """The record the ``current`` pointer names — what a serving
        tenant should load. Raises when nothing was ever promoted."""
        cur = self.current(name)
        if cur is None:
            raise RegistryError(
                f"model {name!r} has no current version (promote one)")
        return self.record(name, cur)

    def status(self, name: str) -> Dict[str, Any]:
        ptr = self._pointer_doc(name)
        return {"name": name, "current": ptr.get("current"),
                "previous": ptr.get("previous"),
                "versions": self.versions(name)}

    # -- mutations ---------------------------------------------------------
    def register(self, name: str, model_dir: str,
                 bank_dir: Optional[str] = None,
                 train_metrics: Optional[Dict[str, Any]] = None,
                 plan_report: Optional[Any] = None,
                 version: Optional[str] = None,
                 promote: bool = False) -> str:
        """Record one exported model as a version of ``name``; returns
        the version id (derived from the artifacts unless given).
        Re-registering an existing version updates its record in place
        (same artifacts → same id — registration is idempotent).
        ``promote=True`` additionally moves the ``current`` pointer."""
        vid = str(version or version_of_export(model_dir, bank_dir))
        rec = {"version": vid,
               "formatVersion": REGISTRY_FORMAT_VERSION,
               "modelDir": os.path.abspath(model_dir),
               "bankDir": (os.path.abspath(bank_dir) if bank_dir
                           else None),
               "paramsDigest": _params_digest(model_dir),
               "trainMetrics": train_metrics,
               "planReport": plan_report,
               # wall-clock by design: registration times are compared
               # across processes and displayed, never used as durations
               "registeredAt": time.time()}   # lint: wall-clock
        with self._lock:
            self._vdir(name, create=True)
            # ONE atomic file per version: concurrent registers from
            # other processes can never be lost to a read-modify-write
            self._write_json_atomic(self._vpath(name, vid), rec)
        tally("registered")
        logger.info("registry: %s version %s registered (%s)", name, vid,
                    model_dir)
        if promote:
            self.promote(name, vid)
        return vid

    def promote(self, name: str, version: str) -> Dict[str, Any]:
        """Point ``current`` at ``version`` (which must be registered).
        The pointer swap is ONE atomic ``os.replace``: a crash before it
        leaves the old pointer, a crash after it leaves the new one —
        there is no in-between state a reader can observe. The
        ``lifecycle.promote`` fault site fires before the swap, so an
        injected fault models the worst-case crash (pointer untouched).
        Writers serialize across processes via the pointer lock file —
        ``previous`` is computed from the pointer read, so a lost update
        would leave the loser's version recorded in neither field."""
        with self._pointer_mutation(name), self._lock:
            self.record(name, version)          # must exist
            ptr = self._pointer_doc(name)
            if ptr.get("current") == str(version):
                return ptr                      # idempotent
            resilience.inject("lifecycle.promote", model=name,
                              version=version)
            new_ptr = {"current": str(version),
                       "previous": ptr.get("current"),
                       "updatedAt": time.time()}   # lint: wall-clock
            self._write_json_atomic(
                os.path.join(self._mdir(name), POINTER_FILE), new_ptr)
        tally("promotions")
        logger.info("registry: %s current -> %s (was %s)", name,
                    new_ptr["current"], new_ptr["previous"])
        return new_ptr

    def rollback(self, name: str) -> str:
        """Swing ``current`` back to ``previous`` (the version serving
        before the last promote). Same atomic pointer discipline; the
        rolled-back-from version stays registered (and becomes the new
        ``previous``, so rollback is its own undo)."""
        with self._pointer_mutation(name), self._lock:
            ptr = self._pointer_doc(name)
            prev = ptr.get("previous")
            if prev is None:
                raise RegistryError(
                    f"model {name!r} has no previous version to roll "
                    "back to")
            self.record(name, prev)             # still registered?
            new_ptr = {"current": str(prev),
                       "previous": ptr.get("current"),
                       "updatedAt": time.time()}   # lint: wall-clock
            self._write_json_atomic(
                os.path.join(self._mdir(name), POINTER_FILE), new_ptr)
        tally("rollbacks")
        logger.info("registry: %s rolled back to %s", name, prev)
        return str(prev)


# ---------------------------------------------------------------------------
# DriftSentinel
# ---------------------------------------------------------------------------


class DriftSentinel:
    """Streaming train↔live distribution comparison on the score path.

    Feed every scored batch through :meth:`observe` (host-side numpy
    only — masks, one ``np.histogram``/hash pass per feature; no device
    work, bounded by the ring size). Rows accumulate into the current
    sub-window sketch; each completed sub-window joins a ring of
    ``subwindows`` sketches whose monoid sum (``FeatureDistribution.
    __add__``) is the sliding comparison window. Once the ring holds a
    full window, every flush compares the merged live distributions
    against the train-time baseline:

    * JS divergence above ``js_threshold``            → TMG601
    * |fill delta| above ``fill_delta_threshold`` or
      fill ratio above ``fill_ratio_threshold``       → TMG602

    Findings flow through the standard machinery: ``lintSuppress``
    rule muting, :func:`~transmogrifai_tpu.lint.emit_findings`
    telemetry mirroring, plus the dedicated ``on_drift`` RunListener
    hook and ``drift.*`` gauges. The sentinel never raises into the
    score path — it reports."""

    def __init__(self, baseline: Sequence[Any], raw_features: Sequence[Any],
                 window_rows: int = DEFAULT_DRIFT_WINDOW_ROWS,
                 subwindows: int = DEFAULT_DRIFT_SUBWINDOWS,
                 js_threshold: float = DEFAULT_JS_THRESHOLD,
                 fill_delta_threshold: float = DEFAULT_FILL_DELTA_THRESHOLD,
                 fill_ratio_threshold: float = DEFAULT_FILL_RATIO_THRESHOLD,
                 bins: Optional[int] = None,
                 suppress: Sequence[str] = (),
                 model_name: str = ""):
        from .filters.distribution import Summary
        self.window_rows = max(int(window_rows), 1)
        self.subwindows = max(int(subwindows), 1)
        self.subwindow_rows = max(self.window_rows // self.subwindows, 1)
        self.js_threshold = float(js_threshold)
        self.fill_delta_threshold = float(fill_delta_threshold)
        self.fill_ratio_threshold = float(fill_ratio_threshold)
        self.suppress = tuple(suppress)
        self.model_name = model_name
        #: (name, key) -> train-time FeatureDistribution
        self._baseline = {(d.name, d.key): d for d in baseline}
        names = {d.name for d in baseline}
        #: only features with a baseline are sketched (a feature the
        #: filter excluded at train time has nothing to compare against)
        self._features = [f for f in raw_features if f.name in names]
        #: shared bin space: every baseline was binned under ONE filter
        #: config, so one bins value reproduces the train edges
        self.bins = int(bins) if bins else self._infer_bins(baseline)
        #: (name, key) -> Summary carrying the train-time bin range, so
        #: live numeric histograms share the baseline's exact edges
        self._summaries: Dict[Tuple[str, Optional[str]], Summary] = {}
        for d in baseline:
            if len(d.summary_info) >= 3:        # numeric: [lo, hi, bins]
                self._summaries[(d.name, d.key)] = Summary(
                    min=float(d.summary_info[0]),
                    max=float(d.summary_info[1]))
        self._lock = locks.witness_lock("lifecycle.DriftSentinel._lock")
        self._pending: Dict[Tuple[str, Optional[str]], Any] = {}
        self._pending_rows = 0
        #: window subscribers: fn(findings, report) called after EVERY
        #: completed comparison window (clean ones included — a
        #: hysteresis consumer needs the resets too). The continual
        #: tier's RetrainController subscribes here.
        self._subscribers: List[Any] = []
        #: ring of (rows, {key: FeatureDistribution}) sub-window sketches
        self._ring: "deque[Tuple[int, Dict[Tuple[str, Optional[str]], Any]]]" \
            = deque(maxlen=self.subwindows)
        self.rows_seen = 0
        self.windows_compared = 0
        self.advisories = 0
        self.last_findings: List[Any] = []
        self.last_report: Optional[Dict[str, Any]] = None

    @staticmethod
    def _infer_bins(baseline: Sequence[Any]) -> int:
        for d in baseline:
            if d.distribution.size:
                return int(d.distribution.size)
        return 100

    # -- construction from a model -----------------------------------------
    @classmethod
    def for_model(cls, model, model_name: str = "",
                  **kw) -> Optional["DriftSentinel"]:
        """Sentinel over a fitted/loaded model's persisted train-time
        distributions. Returns None — with a TMG603 advisory — when the
        model carries no ``RawFeatureFilterResults`` baseline (it
        trained without a RawFeatureFilter, or predates the persistence
        satellite)."""
        from . import lint
        from .workflow import _raw_features_of
        rff = getattr(model, "rff_results", None)
        baseline = list(getattr(rff, "training_distributions", ()) or ())
        if not baseline:
            f = lint.Finding(
                "TMG603", "drift sentinel inactive: the model carries no "
                "train-time feature distributions (train with a "
                "RawFeatureFilter to persist them)",
                feature=model_name or None)
            lint.emit_findings([f])
            logger.info("lifecycle: %s", f.format())
            return None
        raw = [f for f in _raw_features_of(model.result_features)
               if not f.is_response]
        bins = None
        cfg = getattr(rff, "config", None) or {}
        if cfg.get("bins"):
            bins = int(cfg["bins"])
        return cls(baseline, raw, bins=bins, model_name=model_name, **kw)

    # -- accumulation ------------------------------------------------------
    def _sketch(self, store) -> Dict[Tuple[str, Optional[str]], Any]:
        from .filters.distribution import distributions_of_column
        out: Dict[Tuple[str, Optional[str]], Any] = {}
        for f in self._features:
            col = store.get(f.name)
            if col is None:
                continue
            for d in distributions_of_column(f.name, col, self.bins,
                                             self._summaries):
                if (d.name, d.key) in self._baseline:
                    out[(d.name, d.key)] = d
        return out

    def _raw_store(self, data):
        from .columns import ColumnStore, column_of_empty
        from .workflow import _generate_raw_store
        if isinstance(data, ColumnStore):
            missing = {f.name: column_of_empty(f.ftype, data.n_rows)
                       for f in self._features if f.name not in data}
            store = data.with_columns(missing) if missing else data
            return store.select([f.name for f in self._features])
        return _generate_raw_store(data, self._features)

    def subscribe(self, fn) -> None:
        """Register a window callback ``fn(findings, report)`` invoked
        after every completed comparison window — including CLEAN ones
        (findings empty), so a hysteresis consumer (the continual
        tier's retrain controller) sees its streak resets. Callbacks
        run on the observing thread and must be cheap; a raising
        callback is logged and skipped, never kills observation."""
        self._subscribers.append(fn)

    def observe(self, data) -> List[Any]:
        """Fold one scored batch (records or a raw ColumnStore) into the
        current sub-window sketch; returns the findings of any window
        comparison this batch completed (empty most of the time)."""
        if not self._features:
            return []
        n = (data.n_rows if hasattr(data, "n_rows") else len(data))
        if not n:
            return []
        store = self._raw_store(data)
        sketch = self._sketch(store)
        findings: List[Any] = []
        compared = False
        with self._lock:
            self.rows_seen += n
            for k, d in sketch.items():
                prev = self._pending.get(k)
                self._pending[k] = d if prev is None else prev + d
            self._pending_rows += n
            if self._pending_rows >= self.subwindow_rows:
                self._ring.append((self._pending_rows, dict(self._pending)))
                self._pending = {}
                self._pending_rows = 0
                ring_rows = sum(r for r, _ in self._ring)
                if ring_rows >= min(self.window_rows,
                                    self.subwindow_rows * self.subwindows):
                    findings = self._compare_locked(ring_rows)
                    compared = True
        if findings:
            self._emit(findings)
        if compared:
            report = self.last_report
            for fn in list(self._subscribers):
                try:
                    fn(list(findings), report)
                except Exception:  # lint: broad-except — a subscriber must never take down drift observation
                    logger.exception(
                        "drift window subscriber %r failed", fn)
        return findings

    # -- comparison --------------------------------------------------------
    def _merged_locked(self) -> Dict[Tuple[str, Optional[str]], Any]:
        merged: Dict[Tuple[str, Optional[str]], Any] = {}
        for _, sketch in self._ring:
            for k, d in sketch.items():
                prev = merged.get(k)
                merged[k] = d if prev is None else prev + d
        return merged

    def _compare_locked(self, ring_rows: int) -> List[Any]:
        from . import lint
        findings: List[Any] = []
        report: Dict[str, Any] = {"rows": ring_rows, "features": {}}
        for k, live in self._merged_locked().items():
            base = self._baseline.get(k)
            if base is None:
                continue
            js = base.js_divergence(live)
            # the binned histogram only covers the TRAIN range: live
            # mass that landed outside it is invisible to the in-range
            # JS term (a fully out-of-support feature would read 0.0).
            # Out-of-range fraction is itself a divergence lower bound.
            present = live.count - live.nulls
            if present > 0 and base.distribution.size:
                out_frac = 1.0 - min(float(live.distribution.sum())
                                     / present, 1.0)
                js = max(js, out_frac)
            fill_delta = base.relative_fill_rate(live)
            fill_ratio = base.relative_fill_ratio(live)
            fname = live.full_name
            report["features"][fname] = {
                "js": round(js, 4), "fillDelta": round(fill_delta, 4),
                "liveFill": round(live.fill_rate(), 4),
                "trainFill": round(base.fill_rate(), 4)}
            if js > self.js_threshold:
                findings.append(lint.Finding(
                    "TMG601",
                    f"serving-time drift: train↔live JS divergence "
                    f"{js:.3f} > {self.js_threshold:g} over the last "
                    f"{ring_rows} rows", feature=fname))
            if (fill_delta > self.fill_delta_threshold
                    or fill_ratio > self.fill_ratio_threshold):
                findings.append(lint.Finding(
                    "TMG602",
                    f"serving-time drift: fill rate "
                    f"{base.fill_rate():.3f} (train) vs "
                    f"{live.fill_rate():.3f} (live) — delta "
                    f"{fill_delta:.3f}, ratio {fill_ratio:.2f} over the "
                    f"last {ring_rows} rows", feature=fname))
        findings = lint._apply_suppress(findings, self.suppress)
        self.windows_compared += 1
        tally("drift_windows")
        report["advisories"] = len(findings)
        self.last_report = report
        self.last_findings = findings
        if findings:
            self.advisories += len(findings)
            tally("drift_advisories", len(findings))
        return findings

    def _emit(self, findings: List[Any]) -> None:
        from . import lint
        lint.emit_findings(findings)
        rows = (self.last_report or {}).get("rows", 0)
        feats = (self.last_report or {}).get("features", {})
        for f in findings:
            logger.warning("drift[%s]: %s", self.model_name, f.format())
            info = feats.get(f.feature, {})
            value = info.get("js" if f.rule == "TMG601" else "fillDelta",
                             0.0)
            threshold = (self.js_threshold if f.rule == "TMG601"
                         else self.fill_delta_threshold)
            telemetry.emit("drift", model=self.model_name,
                           feature=f.feature, rule=f.rule,
                           value=float(value), threshold=float(threshold),
                           window_rows=int(rows))
        if telemetry.enabled():
            for fname, info in feats.items():
                telemetry.gauge(f"drift.js_divergence.{fname}").set(  # lint: metric-name — bounded by the model's persisted feature set
                    info["js"])
                telemetry.gauge(f"drift.fill_rate_delta.{fname}").set(  # lint: metric-name — bounded by the model's persisted feature set
                    info["fillDelta"])

    # -- stats -------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"rowsSeen": self.rows_seen,
                    "windowRows": self.window_rows,
                    "subwindows": self.subwindows,
                    "windowsCompared": self.windows_compared,
                    "advisories": self.advisories,
                    "trackedFeatures": len(self._baseline),
                    "lastWindow": (dict(self.last_report)
                                   if self.last_report else None)}
