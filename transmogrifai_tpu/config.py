"""The declared ``customParams`` knob registry — ONE config surface.

Every knob the system has grown (scoring, server, pipeline, fleet,
temporal, fitstats, workflow, observability) is declared here as a
:class:`Knob` record: name, type, default, bounds/choices, the module
that owns it, whether the offline tuner may search it, and an optional
extra validator.  ``runner``'s ``_numeric_custom_param`` /
``_bool_custom_param`` are registry lookups over this table, ``cli
gen`` emits its ``customParams`` block from :func:`default_custom_params`,
``cli check`` derives its validation sweep from :func:`check_custom_params`,
and the offline tuner *enumerates* its search space from
:func:`tunable_knobs` instead of grepping the tree for ``.get(`` calls.

Every metrics doc stamps :func:`effective_config` — the fully resolved
knob values after defaults — so a result can always answer "what config
produced this?".

Error-message contract: the ``ValueError`` texts raised here are the
exact strings ``cli check`` has always surfaced as TMG001 findings
(``customParams.<key> must be an integer, got ...``); tests and
operators pattern-match them, so they are part of the API.

This module is the home of raw ``customParams[...]`` access: product
code elsewhere must route through these accessors (tmoglint TMG314).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Knob", "REGISTRY", "knob", "iter_knobs", "tunable_knobs",
           "knob_bounds", "numeric_param", "bool_param", "string_param",
           "check_custom_params", "default_custom_params",
           "effective_config", "coerce_numeric", "coerce_bool"]


@dataclass(frozen=True)
class Knob:
    """One declared ``customParams`` entry.

    ``type`` is one of ``int float bool str enum dict list``; ``bool``
    knobs with ``allow_auto`` accept the tri-state ``"auto"``.
    ``default`` is what ``cli gen`` emits and what resolution falls back
    to (``None`` = unset: the owning module applies its own internal
    default, recorded in ``doc``).  ``minimum``/``maximum`` bound
    numeric values at validation time; ``tune_lo``/``tune_hi`` are the
    (possibly narrower) bounds the offline tuner and the online
    controller may move the knob within — only meaningful when
    ``tunable``.  ``validator`` is an extra hook for constraints the
    scalar bounds cannot express (e.g. canaryFraction in (0, 1])."""

    name: str
    type: str
    default: Any
    owner: str
    doc: str
    minimum: Optional[float] = None
    maximum: Optional[float] = None
    choices: Tuple[str, ...] = ()
    allow_auto: bool = False
    tunable: bool = False
    tune_lo: Optional[float] = None
    tune_hi: Optional[float] = None
    validator: Optional[Callable[[Any], Optional[str]]] = field(
        default=None, compare=False)


REGISTRY: Dict[str, Knob] = {}


def _declare(name: str, type: str, default: Any, owner: str, doc: str,
             **kw: Any) -> None:
    if name in REGISTRY:  # declaration bug, fail at import
        raise ValueError(f"duplicate knob declaration: {name}")
    REGISTRY[name] = Knob(name=name, type=type, default=default,
                          owner=owner, doc=doc, **kw)


def _canary_fraction_ok(v: Any) -> Optional[str]:
    if isinstance(v, (int, float)) and not isinstance(v, bool) \
            and not 0 < v <= 1:
        return f"customParams.canaryFraction must be in (0, 1], got {v!r}"
    return None


def _retrain_cmd_ok(v: Any) -> Optional[str]:
    from .continual import ContinualError, validate_retrain_cmd
    try:
        validate_retrain_cmd(v)
    except ContinualError as e:
        return f"customParams.retrainCmd: {e}"
    return None


# --- workflow / preflight ------------------------------------------------
_declare("validate", "bool", True, "runner",
         "run the static pre-flight (TMG1xx/TMG2xx) before Train/Score")
_declare("validateDevice", "bool", None, "runner",
         "include the eval_shape device pass in pre-flight (default on)")
_declare("failOn", "enum", "error", "runner",
         "findings severity that halts the run", choices=("error", "warning"))
_declare("lintSuppress", "list", None, "lint",
         "lint rule ids to suppress, e.g. [\"TMG301\"]")
_declare("plan", "bool", True, "planner",
         "build the cost-based whole-DAG ExecutionPlan before execution")
_declare("costDb", "str", None, "planner",
         "persisted CostDatabase path (priors for planning and tuning)")
_declare("compileCacheDir", "str", None, "runner",
         "persistent JAX compilation cache directory")
# --- batch scoring / streaming -------------------------------------------
_declare("maxBatches", "int", None, "runner",
         "StreamingScore: stop after N batches", minimum=1)
_declare("timeoutS", "float", None, "runner",
         "StreamingScore: idle-source exit timeout (seconds)", minimum=0)
_declare("batchSize", "int", None, "runner",
         "StreamingScore: rows per scored batch", minimum=1,
         tunable=True, tune_lo=256, tune_hi=16384)
_declare("onBatchError", "enum", None, "runner",
         "StreamingScore poison-batch policy (default quarantine)",
         choices=("halt", "quarantine"))
_declare("overlap", "bool", "auto", "pipeline",
         "overlap host ingest with device compute (tri-state)",
         allow_auto=True)
_declare("pipeline", "bool", True, "pipeline",
         "use the staged prefetch input pipeline")
_declare("pipelineWorkers", "int", None, "pipeline",
         "parallel ingest workers (default: cores-capped auto)",
         minimum=1, tunable=True, tune_lo=1, tune_hi=8)
_declare("pipelineDepth", "int", None, "pipeline",
         "prefetch ring depth (staging buffers in flight)",
         minimum=1, tunable=True, tune_lo=1, tune_hi=8)
# --- mesh / parallel ------------------------------------------------------
_declare("meshDevices", "int", None, "parallel",
         "data-parallel mesh axis size", minimum=1)
_declare("meshGridSize", "int", None, "parallel",
         "grid (model) mesh axis size", minimum=1)
# --- out-of-core training -------------------------------------------------
_declare("streamFit", "bool", None, "runner",
         "multi-pass streaming fit over directory sources (tri-state: "
         "null = auto)", allow_auto=True)
_declare("streamFitPasses", "int", None, "runner",
         "directory re-scan budget for streaming fits", minimum=1)
_declare("featureShards", "int", None, "models",
         "shard tree-fit feature columns over the mesh grid axis",
         minimum=1)
_declare("rssCapMb", "float", None, "pipeline",
         "advisory host-memory budget the ingest planner routes against",
         minimum=1)
# --- temporal -------------------------------------------------------------
_declare("aggregateColumnar", "bool", None, "temporal",
         "columnar aggregation engine (tri-state: null = auto, "
         "true/false force/forbid)", allow_auto=True)
_declare("joinPartitions", "int", None, "temporal",
         "streaming hash-join build-side partitions", minimum=1)
_declare("joinTableMaxRows", "int", None, "temporal",
         "per-partition hash-table row bound (overflow quarantines)",
         minimum=1)
# --- model server (docs/serving.md) ---------------------------------------
_declare("servePort", "int", None, "server",
         "HTTP port (0 = ephemeral)", minimum=0)
_declare("serveBatchDeadlineMs", "float", None, "server",
         "micro-batching hold: higher = more coalescing + that much p50",
         minimum=0, tunable=True, tune_lo=0.0, tune_hi=50.0)
_declare("serveMaxQueue", "int", None, "server",
         "bounded per-model queue (beyond = 429)", minimum=1)
_declare("serveMaxModels", "int", None, "server",
         "loaded models before LRU eviction", minimum=1)
_declare("serveCapacityMB", "float", None, "server",
         "summed bank-weight bound for loaded models", minimum=1)
_declare("serveSloMs", "float", None, "server",
         "per-request latency SLO; attainment in server_stats()",
         minimum=0)
_declare("serveBucketCap", "int", None, "server",
         "engine bucket cap for served models (match the export's)",
         minimum=8)
_declare("serveModels", "dict", None, "server",
         "multi-tenant roster: {name: dir} or {name: {model, bank}}")
_declare("serveBank", "str", None, "server",
         "AOT export dir for the default tenant")
_declare("serveMetrics", "bool", None, "server",
         "expose /metrics Prometheus plane on the serve worker")
_declare("adaptDeadline", "bool", None, "server",
         "online batch-deadline adaptation (AIMD within registry "
         "bounds; kill switch TMOG_ADAPT=0; default off)")
# --- lifecycle / drift ----------------------------------------------------
_declare("registryDir", "str", None, "lifecycle",
         "model registry root (versions, promotions)")
_declare("driftWindow", "int", None, "lifecycle",
         "drift-sentinel window size (requests)", minimum=1)
_declare("driftJsThreshold", "float", None, "lifecycle",
         "Jensen-Shannon drift advisory threshold", minimum=0)
_declare("canaryFraction", "float", None, "lifecycle",
         "canary traffic fraction in (0, 1]", minimum=0,
         validator=_canary_fraction_ok)
# --- continual training ---------------------------------------------------
_declare("retrainOnDrift", "bool", None, "continual",
         "arm the drift-triggered retrain controller")
_declare("retrainCmd", "list", None, "continual",
         "trainer argv template (validated shape)",
         validator=_retrain_cmd_ok)
_declare("retrainArmWindows", "int", None, "continual",
         "consecutive drifted windows before trigger", minimum=1)
_declare("retrainCooldownS", "float", None, "continual",
         "seconds between retrain triggers", minimum=0)
_declare("retrainMaxFailures", "int", None, "continual",
         "failed jobs before the controller gives up", minimum=1)
_declare("retrainTimeoutS", "float", None, "continual",
         "retrain job kill timeout (seconds)", minimum=1)
# --- fleet ----------------------------------------------------------------
_declare("fleetWorkers", "int", None, "fleet",
         "serve worker process count", minimum=1)
_declare("fleetBasePort", "int", None, "fleet",
         "first worker port (0 = ephemeral)", minimum=0)
_declare("workerRespawnMax", "int", None, "fleet",
         "crash respawns before a worker is given up", minimum=0)
_declare("routerRetryBudget", "int", None, "fleet",
         "router failover retries per request", minimum=0)
# --- observability --------------------------------------------------------
_declare("telemetry", "bool", None, "telemetry",
         "force run telemetry on without a trace sink")
_declare("traceDir", "str", None, "telemetry",
         "shared trace-shard directory (distributed tracing)")
_declare("workloadDir", "str", None, "workload",
         "workload flight-recorder shard directory")
_declare("workloadMaxMb", "float", None, "workload",
         "per-shard rotation bound (MB)", minimum=0.001)
_declare("workloadPayloads", "bool", None, "workload",
         "record full request payloads (else digests only)")


def iter_knobs() -> List[Knob]:
    """All declared knobs, in declaration order."""
    return list(REGISTRY.values())


def knob(name: str) -> Knob:
    """Registry lookup; an undeclared name is a programming error."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(f"undeclared customParams knob: {name!r}") from None


def tunable_knobs() -> List[Knob]:
    """The searchable space: knobs the offline tuner may move."""
    return [k for k in REGISTRY.values() if k.tunable]


def knob_bounds(name: str) -> Tuple[float, float]:
    """The (lo, hi) interval a tuner/controller may move ``name``
    within.  Falls back to validity bounds when no tuning bounds are
    declared; an unbounded side is ``-inf``/``inf``."""
    k = knob(name)
    lo = k.tune_lo if k.tune_lo is not None else k.minimum
    hi = k.tune_hi if k.tune_hi is not None else k.maximum
    return (float(lo) if lo is not None else float("-inf"),
            float(hi) if hi is not None else float("inf"))


# --- coercion (the one implementation of the error contract) --------------

def coerce_numeric(raw: Any, key: str, cast=float,
                   minimum: Optional[float] = None) -> Any:
    """Validate+cast one numeric value, raising the contract
    ``ValueError`` naming the key.  ``cast=int`` rejects silent float
    truncation; NaN/inf are rejected (NaN slips past any ``v < minimum``
    comparison)."""
    kind = "an integer" if cast is int else "a number"
    try:
        if isinstance(raw, bool):
            raise TypeError
        v = cast(raw)
        if cast is int and float(raw) != v:
            raise TypeError
        if not math.isfinite(v):
            raise TypeError
    except (TypeError, ValueError, OverflowError):
        # OverflowError: int(1e400) — JSON happily parses huge floats
        raise ValueError(
            f"customParams.{key} must be {kind}, got {raw!r}") from None
    if minimum is not None and v < minimum:
        raise ValueError(
            f"customParams.{key} must be >= {minimum:g}, got {raw!r}")
    return v


def coerce_bool(raw: Any, key: str, allow_auto: bool = False) -> Any:
    """Validate one boolean value: JSON true/false, the strings
    "true"/"false" (shell-templated config files), and — with
    ``allow_auto`` — the tri-state ``"auto"``."""
    if isinstance(raw, bool):
        return raw
    if isinstance(raw, str):
        s = raw.strip().lower()
        if s in ("true", "false"):
            return s == "true"
        if allow_auto and s == "auto":
            return "auto"
    kinds = "a boolean (true/false)"
    if allow_auto:
        kinds += ' or "auto"'
    raise ValueError(f"customParams.{key} must be {kinds}, got {raw!r}")


# --- registry-driven accessors -------------------------------------------

def numeric_param(custom_params: Dict[str, Any], name: str,
                  default: Any = None) -> Any:
    """Registry-backed numeric lookup: cast and minimum come from the
    declaration; ``None``/absent returns ``default`` (the caller's
    module-internal fallback, NOT the registry default — an explicit
    JSON null means "use the module default", same as omitting)."""
    k = knob(name)
    if k.type not in ("int", "float"):
        raise KeyError(f"knob {name!r} is {k.type}, not numeric")
    raw = custom_params.get(name)  # lint: knob — the registry accessor
    if raw is None:
        return default
    return coerce_numeric(raw, name, int if k.type == "int" else float,
                          minimum=k.minimum)


def bool_param(custom_params: Dict[str, Any], name: str,
               default: Any = None) -> Any:
    """Registry-backed boolean lookup (tri-state when declared)."""
    k = knob(name)
    if k.type != "bool":
        raise KeyError(f"knob {name!r} is {k.type}, not bool")
    raw = custom_params.get(name)  # lint: knob — the registry accessor
    if raw is None:
        return default
    return coerce_bool(raw, name, allow_auto=k.allow_auto)


def string_param(custom_params: Dict[str, Any], name: str,
                 default: Any = None) -> Any:
    """Registry-backed path/string lookup (validated type)."""
    k = knob(name)
    if k.type != "str":
        raise KeyError(f"knob {name!r} is {k.type}, not str")
    raw = custom_params.get(name)  # lint: knob — the registry accessor
    if raw is None:
        return default
    if not isinstance(raw, str):
        raise ValueError(f"customParams.{name} must be a path string, "
                         f"got {raw!r}")
    return raw


def raw_param(custom_params: Dict[str, Any], name: str,
              default: Any = None) -> Any:
    """Registry-gated passthrough for dict/list/enum knobs whose shape
    checks live with their owner (serveModels roster, retrainCmd)."""
    knob(name)  # existence check: undeclared names fail loudly
    raw = custom_params.get(name)  # lint: knob — the registry accessor
    return default if raw is None else raw


# --- whole-file validation (cli check derives from this) ------------------

def check_custom_params(custom_params: Dict[str, Any]) -> List[Tuple[str, str]]:
    """Sweep every declared knob over one ``customParams`` dict and
    return ``(knob_name, error_message)`` pairs — the registry-derived
    validation ``cli check`` surfaces as TMG001 findings.  Unknown keys
    are NOT errors (apps may carry private keys), but every declared
    knob present must parse."""
    errors: List[Tuple[str, str]] = []
    for k in REGISTRY.values():
        raw = custom_params.get(k.name)  # lint: knob — registry sweep
        if raw is None:
            continue
        try:
            if k.type in ("int", "float"):
                coerce_numeric(raw, k.name,
                               int if k.type == "int" else float,
                               minimum=k.minimum)
            elif k.type == "bool":
                coerce_bool(raw, k.name, allow_auto=k.allow_auto)
            elif k.type == "str":
                if not isinstance(raw, str):
                    raise ValueError(
                        f"customParams.{k.name} must be a path string, "
                        f"got {raw!r}")
            elif k.type == "enum":
                if raw not in k.choices:
                    raise ValueError(
                        f"customParams.{k.name} must be one of "
                        f"{list(k.choices)}, got {raw!r}")
            elif k.type == "dict":
                if not isinstance(raw, dict):
                    raise ValueError(
                        f"customParams.{k.name} must be an object, "
                        f"got {raw!r}")
            elif k.type == "list":
                # str allowed: lintSuppress takes a bare rule id, and
                # a string retrainCmd must reach its validator (which
                # owns the shell-string finding) rather than
                # double-report here
                if not isinstance(raw, (list, tuple, str)):
                    raise ValueError(
                        f"customParams.{k.name} must be a list, "
                        f"got {raw!r}")
        except ValueError as e:
            errors.append((k.name, str(e)))
            continue
        if k.validator is not None:
            msg = k.validator(raw)
            if msg:
                errors.append((k.name, msg))
    return errors


# --- emission / stamping --------------------------------------------------

#: knobs `cli gen` leaves out of the scaffolded params.json (serving /
#: fleet / continual surfaces a generated batch app does not start with;
#: same set the pre-registry scaffold emitted)
_GEN_OMIT = frozenset((
    "validateDevice", "lintSuppress", "compileCacheDir", "maxBatches",
    "timeoutS", "batchSize", "onBatchError", "servePort",
    "serveBatchDeadlineMs", "serveMaxQueue", "serveMaxModels",
    "serveCapacityMB", "serveSloMs", "serveBucketCap", "serveModels",
    "serveBank", "adaptDeadline", "telemetry"))


def default_custom_params() -> Dict[str, Any]:
    """The ``customParams`` block ``cli gen`` scaffolds: every
    non-omitted registry knob at its declared default, in declaration
    order — so a generated project names the whole surface it can
    tune."""
    return {k.name: k.default for k in REGISTRY.values()
            if k.name not in _GEN_OMIT}


def effective_config(custom_params: Dict[str, Any]) -> Dict[str, Any]:
    """The resolved config stamped on every metrics doc: for each
    declared knob, the validated supplied value or the declared default.
    Values that fail validation are stamped as ``{"invalid": raw}`` so
    the doc still records what was asked for."""
    out: Dict[str, Any] = {}
    for k in REGISTRY.values():
        raw = custom_params.get(k.name)  # lint: knob — registry stamp
        if raw is None:
            out[k.name] = k.default
            continue
        try:
            if k.type in ("int", "float"):
                out[k.name] = coerce_numeric(
                    raw, k.name, int if k.type == "int" else float,
                    minimum=k.minimum)
            elif k.type == "bool":
                out[k.name] = coerce_bool(raw, k.name,
                                          allow_auto=k.allow_auto)
            else:
                out[k.name] = raw
        except ValueError:
            out[k.name] = {"invalid": repr(raw)}
    return out
