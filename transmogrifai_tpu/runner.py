"""Workflow runner, app entry and file-driven parameters.

Parity:

* ``OpParams`` (``features/.../OpParams.scala:30-150``): JSON/YAML config
  holding per-stage parameter overrides (keyed by stage class name or uid,
  applied reflectively), reader paths, model/metrics locations and custom
  tags.
* ``OpWorkflowRunner`` (``core/.../OpWorkflowRunner.scala:296,358-366``):
  run types Train / Score / Evaluate / Features wiring readers, model
  persistence and a metrics sink.
* ``OpApp`` (``core/.../OpApp.scala``): abstract main() parsing CLI args
  into a runner config and invoking the runner.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from . import config, resilience, telemetry

logger = logging.getLogger(__name__)

__all__ = ["OpParams", "RunType", "RunnerResult", "OpWorkflowRunner",
           "OpApp"]


def _numeric_custom_param(params: "OpParams", key: str, cast=float,
                          default: Any = None,
                          minimum: Optional[float] = None) -> Any:
    """Validated numeric ``customParams`` lookup — now a registry
    lookup over :mod:`~transmogrifai_tpu.config` (PR 18): the declared
    cast/minimum win when ``key`` is a registered knob, so validation
    can never drift from the one declared surface. A malformed value
    raises a ``ValueError`` NAMING the key instead of an uncaught
    ``float(ts)`` traceback deep in the run. ``None``/absent returns
    ``default`` (an explicit JSON ``null`` means "use the default", same
    as omitting the key); ``cast=int`` additionally rejects silent float
    truncation (``maxBatches: 2.5`` is a config error, not 2). The
    legacy ``cast``/``minimum`` args remain for unregistered keys."""
    raw = params.custom_params.get(key)  # lint: knob — the registry wrapper itself
    if raw is None:
        return default
    try:
        k = config.knob(key)
    except KeyError:
        return config.coerce_numeric(raw, key, cast, minimum=minimum)
    return config.coerce_numeric(raw, key,
                                 int if k.type == "int" else float,
                                 minimum=k.minimum)


def _bool_custom_param(params: "OpParams", key: str, default: Any = None,
                       allow_auto: bool = False) -> Any:
    """Validated boolean ``customParams`` lookup — a registry lookup
    over :mod:`~transmogrifai_tpu.config` (the declared tri-state wins
    for registered knobs): a JSON ``true``/``false``, the strings
    ``"true"``/``"false"`` (config files written by shell templating),
    and — when the declaration allows — the tri-state ``"auto"``.
    Anything else raises a ``ValueError`` NAMING the key, so ``cli
    check`` reports it as TMG001 and a typo'd ``overlap: "yes"`` can no
    longer silently mean "auto"."""
    raw = params.custom_params.get(key)  # lint: knob — the registry wrapper itself
    if raw is None:
        return default
    try:
        k = config.knob(key)
    except KeyError:
        return config.coerce_bool(raw, key, allow_auto=allow_auto)
    return config.coerce_bool(raw, key, allow_auto=k.allow_auto)


@dataclass
class OpParams:
    """File-driven workflow configuration (OpParams.scala:30-150)."""

    #: {stage class name or uid: {param: value}} applied via set_params
    stage_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    #: {reader name: {"path": ..., ...}}
    reader_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics_location: Optional[str] = None
    #: Chrome trace-event JSON sink; setting it turns telemetry on
    trace_location: Optional[str] = None
    #: metrics sink format: "json" (the run doc) or "prometheus" (the
    #: telemetry registry in text exposition + run doc numerics);
    #: "prometheus" turns telemetry on
    metrics_format: str = "json"
    #: poison-record dead-letter sink (JSONL, resilience.Quarantine):
    #: unreadable stream files and failed scoring batches land here with
    #: a reason instead of vanishing; installed run-scoped
    quarantine_location: Optional[str] = None
    custom_params: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_file(path: str) -> "OpParams":
        with open(path) as fh:
            if path.endswith((".yaml", ".yml")):
                import yaml
                doc = yaml.safe_load(fh)
            else:
                doc = json.load(fh)
        return OpParams(
            stage_params=doc.get("stageParams", {}),
            reader_params=doc.get("readerParams", {}),
            model_location=doc.get("modelLocation"),
            write_location=doc.get("writeLocation"),
            metrics_location=doc.get("metricsLocation"),
            trace_location=doc.get("traceLocation"),
            metrics_format=doc.get("metricsFormat", "json"),
            quarantine_location=doc.get("quarantineLocation"),
            custom_params=doc.get("customParams", {}))

    def to_json(self) -> Dict[str, Any]:
        return {"stageParams": self.stage_params,
                "readerParams": self.reader_params,
                "modelLocation": self.model_location,
                "writeLocation": self.write_location,
                "metricsLocation": self.metrics_location,
                "traceLocation": self.trace_location,
                "metricsFormat": self.metrics_format,
                "quarantineLocation": self.quarantine_location,
                "customParams": self.custom_params}

    def telemetry_requested(self) -> bool:
        """True when this config asks for run telemetry (trace sink,
        Prometheus metrics, or ``customParams.telemetry``)."""
        return bool(self.trace_location
                    or self.metrics_format == "prometheus"
                    or self.custom_params.get("telemetry"))  # lint: knob — truthiness gate

    def apply_to_workflow(self, workflow) -> None:
        """Reflectively push stage params into the workflow's DAG stages
        (OpWorkflow.setStageParameters :166-188): keys match stage uid or
        stage class name."""
        from .graph import all_stages
        if not self.stage_params:
            return
        for stage in all_stages(workflow.result_features):
            for key in (stage.uid, type(stage).__name__):
                if key in self.stage_params:
                    stage.set_params(**self.stage_params[key])


def _pipeline_stats() -> Dict[str, Any]:
    from . import pipeline
    return pipeline.pipeline_stats()


def _enable_compile_cache(path: str) -> str:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing). Attacks the cold-run compile tax: the bench measured a
    448 s cumulative compile clock / ~3× cold-vs-warm CV penalty, all of
    it re-payable per process without a persistent cache. Safe to call
    repeatedly; returns the path."""
    import jax
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:  # lint: broad-except — older jax without the knob
        logger.debug("persistent-cache min-compile-time knob unavailable")
    logger.info("persistent XLA compile cache at %s", path)
    return path


class RunType:
    TRAIN = "Train"
    SCORE = "Score"
    STREAMING_SCORE = "StreamingScore"
    EVALUATE = "Evaluate"
    FEATURES = "Features"

    ALL = (TRAIN, SCORE, STREAMING_SCORE, EVALUATE, FEATURES)


@dataclass
class RunnerResult:
    run_type: str
    metrics: Dict[str, Any] = field(default_factory=dict)
    model_location: Optional[str] = None
    scores: Any = None


class OpWorkflowRunner:
    """Run-type entry around a Workflow (OpWorkflowRunner.scala:296).

    ``training_reader`` / ``scoring_reader`` follow the readers API
    (``generate_store`` / ``read_records``); ``evaluator`` is an
    evaluators instance wired to (label, prediction).
    """

    def __init__(self, workflow, training_reader=None, scoring_reader=None,
                 evaluation_reader=None, evaluator=None,
                 features_to_compute=None):
        self.workflow = workflow
        self.training_reader = training_reader
        self.scoring_reader = scoring_reader
        self.evaluation_reader = evaluation_reader or scoring_reader
        self.evaluator = evaluator
        self.features_to_compute = features_to_compute

    # -- pre-flight (lint.py, on by default) -------------------------------
    def _preflight(self, params: "OpParams", workflow=None,
                   model=None, reader=None) -> Optional[Dict[str, Any]]:
        """Static pre-flight check BEFORE any reader I/O: the graph rules
        over an untrained workflow (Train), graph + eval_shape device
        rules over a loaded model (Score/Evaluate/Features/Streaming).

        On by default; ``customParams.validate: false`` disables,
        ``customParams.failOn`` (or CLI ``--fail-on``) picks the gating
        severity (default ``error`` — warnings log but don't block),
        ``customParams.validateDevice: false`` skips the TMG2xx pass and
        ``customParams.lintSuppress: [rule, ...]`` mutes specific rules.
        Findings mirror into telemetry (``lint.*`` counters, ``on_lint``)
        and the returned summary rides in the run's metrics doc."""
        from . import lint
        validate = params.custom_params.get("validate", True)  # lint: knob — gate read before registry accessors exist in this frame
        if validate in (False, 0) or str(validate).lower() == "false":
            return None
        fail_on = str(params.custom_params.get("failOn", "error")).lower()  # lint: knob — enum read, shape-checked by cli check
        suppress = params.custom_params.get("lintSuppress", ())  # lint: knob — list passthrough
        device = params.custom_params.get("validateDevice", True)  # lint: knob — tri-state legacy truthiness
        device = not (device in (False, 0)
                      or str(device).lower() == "false")
        with telemetry.span("run:preflight"):
            if workflow is not None:
                # reader-aware: the temporal cutoff-leakage rules
                # (TMG7xx) inspect the training reader STRUCTURALLY —
                # still zero reader I/O before the gate
                findings = lint.check_workflow(workflow, suppress=suppress,
                                               reader=reader)
            else:
                findings = lint.check_model(model, device=device,
                                            suppress=suppress)
        lint.emit_findings(findings)
        for f in findings:
            log = (logger.error if f.severity == "error" else
                   logger.warning if f.severity == "warning" else
                   logger.info)
            log("pre-flight: %s", f.format())
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        summary = {"findings": len(findings), "failOn": fail_on, **counts}
        if not findings:
            logger.info("pre-flight: workflow graph clean (0 findings)")
        lint.enforce(findings, fail_on=fail_on)
        self._last_preflight = summary
        return summary

    # -- whole-DAG planning (planner.py, on by default) --------------------
    @staticmethod
    def _cost_db_path(params: "OpParams") -> Optional[str]:
        """Where this run's cost database lives: an explicit
        ``customParams.costDb`` wins, else it sits alongside the
        persistent compile cache (``compileCacheDir``), else None —
        an in-memory db whose static estimates still produce a plan."""
        from . import planner
        db = params.custom_params.get("costDb")  # lint: knob — path passthrough
        if db:
            return str(db)
        return planner.default_cost_db_path(
            params.custom_params.get("compileCacheDir"))  # lint: knob — path passthrough

    def _plan_step(self, params: "OpParams", workflow=None, model=None):
        """Build the cost-based ExecutionPlan BEFORE any reader I/O and
        install it so the run follows it: ``Workflow.train`` consults
        the per-phase tiers, score-type runs attach the model plan to
        the scoring engine (CSE, dead-column pruning, measured tier).

        On by default; ``customParams.plan: false`` disables. The
        TMG4xx advisory findings flow through the SAME ``failOn`` /
        ``lintSuppress`` machinery as the pre-flight rules, and the
        plan's JSON form rides in the metrics doc under ``plan``."""
        from . import lint, planner
        enabled = params.custom_params.get("plan", True)  # lint: knob — gate read, legacy truthiness contract
        if enabled in (False, 0) or str(enabled).lower() == "false":
            # a reused workflow must not silently follow a PREVIOUS
            # run's plan while this run stamps plan: null
            if workflow is not None:
                workflow.set_plan(None)
            return None
        fail_on = str(params.custom_params.get("failOn", "error")).lower()  # lint: knob — enum read, shape-checked by cli check
        suppress = params.custom_params.get("lintSuppress", ())  # lint: knob — list passthrough
        db = planner.CostDatabase.load(self._cost_db_path(params))
        try:
            with telemetry.span("run:plan"):
                if model is not None:
                    plan = planner.plan_model(model, cost_db=db)
                    model.attach_plan(plan)
                else:
                    plan = planner.plan_workflow(workflow, cost_db=db)
                    workflow.set_plan(plan)
        except Exception:  # lint: broad-except — the plan is an optimization, never a dependency: a planner failure degrades to the unplanned run
            logger.exception("plan step failed; the run proceeds "
                             "unplanned (gates rule)")
            if workflow is not None:
                workflow.set_plan(None)     # no stale plan from run N-1
            if model is not None:
                model.attach_plan(None)
            return None
        findings = list(plan.findings())
        # measured columnar-vs-rowwise aggregation route (the cost db's
        # phase:temporal.route_aggregate observations): install the hint
        # the readers' auto-route consults for THIS run (the run-scoped
        # set_run_defaults restore clears it). An explicit
        # aggregateColumnar knob always wins — a contradiction between
        # the knob and the measurement surfaces as a TMG405 advisory.
        agg_tier = planner.aggregate_route_tier(db)
        if agg_tier is not None:
            from . import temporal as _temporal
            _temporal.set_aggregate_tier_hint(agg_tier)
            forced = _bool_custom_param(params, "aggregateColumnar",
                                        allow_auto=True)
            if (forced is True and agg_tier == "rowwise") \
                    or (forced is False and agg_tier == "columnar"):
                findings.append(lint.Finding(
                    "TMG405",
                    f"aggregateColumnar={str(bool(forced)).lower()} is "
                    f"pinned but the cost database measured the "
                    f"{agg_tier} tier faster "
                    "(phase:temporal.route_aggregate) — the knob wins; "
                    "drop it to let the auto-route follow the "
                    "measurement"))
        # measured stream-vs-materialize ingest route (the cost db's
        # phase:workflow.ingest observations): install the hint the
        # ``streamFit: null`` auto mode consults for THIS run (the
        # runner's run-scoped set_stream_fit restore clears it). An
        # explicit streamFit knob always wins — a contradiction between
        # the knob and the measurement surfaces as a TMG405 advisory,
        # exactly like the aggregate route above.
        ingest_tier = planner.ingest_route_tier(db)
        if ingest_tier is not None:
            from . import workflow as _workflow
            _workflow.set_stream_fit(ingest_hint=ingest_tier)
            forced_sf = _bool_custom_param(params, "streamFit",
                                           allow_auto=True)
            if (forced_sf is True and ingest_tier == "materialize") \
                    or (forced_sf is False and ingest_tier == "stream"):
                findings.append(lint.Finding(
                    "TMG405",
                    f"streamFit={str(bool(forced_sf)).lower()} is "
                    f"pinned but the cost database measured the "
                    f"{ingest_tier} ingest tier faster "
                    "(phase:workflow.ingest) — the knob wins; drop it "
                    "to let the auto-route follow the measurement"))
        findings = lint._apply_suppress(findings, suppress)
        lint.emit_findings(findings)
        for f in findings:
            (logger.warning if f.severity == "warning"
             else logger.info)("plan: %s", f.format())
        lint.enforce(findings, fail_on=fail_on)
        self._plan_db = db
        self._last_plan = plan.to_json()
        return plan

    def _record_plan_costs(self, model) -> None:
        """After a fresh fit: fold the measured per-stage costs (and the
        link bandwidth, when the run probed it) into the cost database
        and persist it atomically, then re-plan the now-fitted model so
        the stamped ``plan`` block carries the full model plan (pruning
        + CSE + tiers) instead of the graph-only pre-fit plan."""
        from . import planner
        from . import workflow as _wf
        db = getattr(self, "_plan_db", None)
        if db is None:
            return
        try:
            planner.record_fit_costs(model, db)
            planner.drain_phase_observations(db)
            if _wf._DEVICE_BW_MBPS is not None:
                # sustained (the tier-deciding number) + the cold probe
                # beside it — see CostDatabase.record_bandwidth
                db.record_bandwidth(
                    _wf._DEVICE_BW_MBPS,
                    probe_mbps=_wf._DEVICE_BW_PROBE_MBPS)
            db.save()
            self._last_plan = planner.plan_model(model,
                                                 cost_db=db).to_json()
        except Exception:  # lint: broad-except — cost recording must never fail a finished train
            logger.exception("cost-db recording failed; the pre-fit "
                             "plan stamp stands")

    def _record_score_costs(self) -> None:
        """After a score-type run: fold the buffered per-phase
        observations (scoring transforms, pipeline ingest, temporal
        aggregation) into the cost database and persist it — the
        serving-path priors the offline tuner seeds its search from.
        Train-only draining left the db blind to exactly the phases
        tuning cares about (docs/tuning.md)."""
        from . import planner
        db = getattr(self, "_plan_db", None)
        # a corrupt db keeps raising TMG404 until a TRAIN regenerates
        # it — a score run saving over it would silently clear the
        # finding (and destroy the evidence) between runs
        if db is None or getattr(db, "corrupt", False):
            return
        try:
            planner.drain_phase_observations(db)
            db.save()
        except Exception:  # lint: broad-except — cost recording must never fail a finished score
            logger.exception("cost-db recording failed on the score "
                             "path; the run's result stands")

    @staticmethod
    def _shard_role(run_type: str) -> str:
        """This run's row name in merged traces: an explicit
        TMOG_TRACE_ROLE (the retrain controller sets ``retrain``) wins;
        the default names the run type."""
        role = telemetry.trace_role()
        return role if role != "proc" else f"run-{run_type.lower()}"

    # -- metrics sink ------------------------------------------------------
    @staticmethod
    def _write_metrics(location: Optional[str], doc: Dict[str, Any],
                       fmt: str = "json") -> None:
        """Crash-consistent metrics sink: write a sibling temp file and
        ``os.replace`` it in (the ``_atomic_checkpoint`` discipline), so
        a kill mid-write can never leave a truncated metrics file.
        ``fmt="prometheus"`` writes the telemetry registry in text
        exposition format with the run doc's numeric scalars appended as
        ``run_*`` gauges; the default writes the run doc as JSON."""
        # multi-host: every process computes identical metrics; one writer
        from .parallel.multihost import is_coordinator
        if not location or not is_coordinator():
            return
        os.makedirs(os.path.dirname(location) or ".", exist_ok=True)
        tmp = f"{location}.tmp"
        with open(tmp, "w") as fh:
            if fmt == "prometheus":
                extra = {f"run_{k}": float(v) for k, v in doc.items()
                         if isinstance(v, (int, float))
                         and not isinstance(v, bool)}
                fh.write(telemetry.render_prometheus(extra))
            else:
                json.dump(doc, fh, indent=1, default=str)
        os.replace(tmp, location)

    def run(self, run_type: str, params: Optional[OpParams] = None
            ) -> RunnerResult:
        params = params or OpParams()
        if run_type not in RunType.ALL:
            raise ValueError(
                f"Unknown run type {run_type!r}; expected one of "
                f"{RunType.ALL}")
        # run-scoped enablement: a config that asks for telemetry turns it
        # on for THIS run only — recording must not stay sticky for later
        # runs of a long-lived process that never asked (a user-level
        # telemetry.enable() before the run stays in force, untouched)
        run_scoped = False
        # cross-process trace shards (docs/observability.md
        # "Distributed tracing"): customParams.traceDir — or the
        # TMOG_TRACE_DIR a supervising process (fleet worker, retrain
        # controller) handed down — asks this run to record spans and
        # drop one atomic shard into the shared merge directory; the
        # TMOG_TRACE_PARENT env (if any) joins its spans to the
        # originating trace automatically (telemetry.current_trace).
        trace_dir = params.custom_params.get(  # lint: knob — path read, type-checked below
            "traceDir") or os.environ.get("TMOG_TRACE_DIR")
        if trace_dir is not None and not isinstance(trace_dir, str):
            raise ValueError("customParams.traceDir must be a path "
                             f"string, got {trace_dir!r}")
        if (params.telemetry_requested() or trace_dir) \
                and not telemetry.enabled():
            telemetry.enable()
            run_scoped = True
        # persistent XLA compile cache (OpParams.customParams
        # .compileCacheDir / CLI --compile-cache-dir): repeat cold runs
        # reload compiled executables instead of re-paying the compile
        # clock; its presence is stamped into the metrics doc below
        cache_dir = params.custom_params.get("compileCacheDir")  # lint: knob — path passthrough
        if cache_dir:
            _enable_compile_cache(str(cache_dir))
        # run-scoped mesh shape (customParams.meshDevices/meshGridSize,
        # CLI --mesh-devices): bound the (data, grid) mesh the run's
        # heavy phases shard over; the previous process mesh is restored
        # on exit. Validated up front — a malformed value names its key
        # now, and an impossible split fails before any data is read
        # (meshGridSize is the EXPLICIT grid axis: a non-dividing value
        # raises rather than silently rounding down).
        from .parallel import mesh as _mesh
        mesh_devices = _numeric_custom_param(params, "meshDevices", int,
                                             minimum=1)
        mesh_grid = _numeric_custom_param(params, "meshGridSize", int,
                                          minimum=1)
        run_mesh_obj = None
        if mesh_devices is not None or mesh_grid is not None:
            run_mesh_obj = _mesh.make_mesh(n_devices=mesh_devices,  # lint: explicit-mesh — the run-scoped meshDevices/meshGridSize override IS the sanctioned explicit construction
                                           grid_axis=mesh_grid)
        prev_mesh = None
        run_mesh = False
        # run-scoped dead-letter sink (quarantineLocation / CLI
        # --quarantine-out): poison files/batches route there for THIS
        # run; the previous sink is restored on exit (a user-level
        # resilience.set_quarantine stays in force otherwise)
        # run-scoped temporal-tier knobs (docs/readers.md):
        # aggregateColumnar tri-state forces/forbids the columnar
        # aggregation engine (auto = columnar when the source yields a
        # columnar batch), joinPartitions/joinTableMaxRows bound the
        # streaming join's build tables. Validated up front — a
        # malformed value names its key now (TMG001 via `cli check`) —
        # and validated BEFORE any run-scoped installs below, so a bad
        # knob can never leak a half-installed run configuration.
        from . import temporal as _temporal
        temporal_knobs = dict(
            columnar=_bool_custom_param(params, "aggregateColumnar",
                                        allow_auto=True),
            join_partitions=_numeric_custom_param(
                params, "joinPartitions", int, minimum=1),
            join_table_max_rows=_numeric_custom_param(
                params, "joinTableMaxRows", int, minimum=1))
        # run-scoped out-of-core knobs (docs/performance.md "Out-of-core
        # training"): streamFit tri-state forces/forbids the multi-pass
        # streaming ingest (auto = stream when the source is a directory
        # reader, deferring to the planner's measured ingest tier),
        # streamFitPasses bounds the directory re-scan budget, rssCapMb
        # is the advisory host-memory budget (auto mode streams when a
        # cap is declared), featureShards shards tree-fit columns over
        # the mesh grid axis. Validated up front like every knob above.
        from . import workflow as _workflow
        from .models import _treefit as _treefit
        stream_knobs = dict(
            stream=_bool_custom_param(params, "streamFit",
                                      allow_auto=True),
            passes=_numeric_custom_param(params, "streamFitPasses", int,
                                         minimum=1),
            rss_cap_mb=_numeric_custom_param(params, "rssCapMb", float,
                                             minimum=1))
        feature_shards = _numeric_custom_param(params, "featureShards",
                                               int, minimum=1)
        qloc = (params.quarantine_location
                or params.custom_params.get("quarantineLocation"))  # lint: knob — sink path, not a registry knob
        prev_sink = (resilience.set_quarantine(str(qloc)) if qloc
                     else None)
        prev_temporal = _temporal.set_run_defaults(**temporal_knobs)
        prev_stream = _workflow.set_stream_fit(**stream_knobs)
        prev_shards = (_treefit.set_feature_shards(feature_shards)
                       if feature_shards is not None else None)
        # one collecting listener per run (OpSparkListener analog): its
        # AppMetrics summary rides in the metrics doc/sink below
        collector = None
        if telemetry.enabled():
            collector = telemetry.add_listener(
                telemetry.CollectingRunListener())
        logger.info("run type=%s model=%s write=%s", run_type,
                    params.model_location, params.write_location)
        # the tallies are process-cumulative; the run doc must report
        # THIS run's events, not a predecessor's quarantines
        self._last_preflight = None
        self._last_plan = None
        res_before = resilience.resilience_stats()
        # install the run-scoped mesh LAST, immediately before the
        # try/finally that restores it — an exception in the setup above
        # must not leak the run's mesh into the process default
        if run_mesh_obj is not None:
            prev_mesh = _mesh.set_process_mesh(run_mesh_obj)
            run_mesh = True
        t0 = time.perf_counter()
        telemetry.emit("run_start", run_type=run_type)
        ok = False
        try:
            with telemetry.span(f"run:{run_type}"):
                result = self._execute(run_type, params, t0)
            ok = True
        finally:
            telemetry.emit("run_end", run_type=run_type,
                           seconds=time.perf_counter() - t0)
            if collector is not None:
                telemetry.remove_listener(collector)
            if qloc:
                resilience.set_quarantine(prev_sink)
            _temporal.set_run_defaults(**prev_temporal)
            _workflow.set_stream_fit(**prev_stream)
            if prev_shards is not None:
                _treefit.set_feature_shards(prev_shards)
            try:
                if ok:
                    # compile-cache presence rides in every metrics doc
                    # (None when no persistent cache was configured)
                    result.metrics["compileCacheDir"] = (
                        str(cache_dir) if cache_dir else None)
                    # the resolved knob surface rides in every metrics
                    # doc (PR 18): every registry knob at its supplied-
                    # or-default value, so a result can always answer
                    # "what config produced this?" (config.py)
                    result.metrics["effectiveConfig"] = \
                        config.effective_config(params.custom_params)
                    # the mesh topology every heavy phase ran on rides in
                    # every metrics doc (PR 6: multichip is mainline —
                    # a benched number must say how many chips it used)
                    result.metrics["mesh"] = _mesh.mesh_topology()
                    # pre-flight verdict rides in every metrics doc
                    # (None = validation disabled for this run)
                    result.metrics["preflight"] = self._last_preflight
                    # the execution plan the run followed rides too
                    # (None = planning disabled; see planner.py and
                    # docs/static-analysis.md for the block's schema)
                    result.metrics["plan"] = self._last_plan
                    # quarantine / retry / breaker evidence rides too —
                    # the always-on tallies make silent data loss
                    # visible in every run doc, telemetry on or off
                    result.metrics["resilience"] = {
                        k: v - res_before.get(k, 0)
                        for k, v in
                        resilience.resilience_stats().items()}
                    # serving-tier tallies ride too (AOT bank traffic +
                    # model-server coalescing/SLO evidence — zeros on
                    # runs that never touch the serving tier; always-on
                    # like the resilience block, docs/serving.md)
                    from . import aot as _aot
                    from . import server as _server
                    result.metrics["aot"] = _aot.aot_stats()
                    result.metrics["server"] = _server.server_stats()
                    # model-lifecycle tallies ride on every doc too:
                    # registry traffic, rollout promotions/rollbacks,
                    # drift windows + advisories (lifecycle.py)
                    from . import lifecycle as _lifecycle
                    result.metrics["lifecycle"] = \
                        _lifecycle.lifecycle_stats()
                    # continuous-training tallies ride on every doc
                    # too: drift windows seen, retrain triggers vs
                    # storm suppression, job outcomes, warm-start vs
                    # full-refit split (continual.py, docs/lifecycle.md
                    # "Continuous training")
                    from . import continual as _continual
                    result.metrics["continual"] = \
                        _continual.continual_stats()
                    # serving-fleet tallies ride on every doc too:
                    # spawns/respawns, routed requests, failovers and
                    # load shedding (fleet.py, docs/fleet.md) — zeros
                    # on runs that never touch the fleet tier
                    from . import fleet as _fleet
                    result.metrics["fleet"] = _fleet.fleet_stats()
                    # input-pipeline tallies ride on every doc too:
                    # converged prefetch depth, worker count, buffer
                    # reuse and the sustained-bandwidth measurement
                    # behind the fusion gate (pipeline.py)
                    result.metrics["pipeline"] = _pipeline_stats()
                    # temporal-tier tallies ride on every doc too:
                    # columnar-vs-rowwise aggregation split, join
                    # traffic, bounded-table spills (temporal.py,
                    # docs/readers.md) — zeros on runs that never
                    # touch the temporal tier
                    result.metrics["temporal"] = \
                        _temporal.temporal_stats()
                    # tree-engine kernel tallies ride on every doc too:
                    # per-kernel trace counts, mesh-sharded histogram
                    # builds, gate state and fallback flips
                    # (models/_pallas_hist.py, docs/performance.md
                    # "Tree training engine")
                    from .models import _pallas_hist as _ph
                    result.metrics["trees"] = _ph.tree_kernel_stats()
                    # executed-FLOP device cost attribution rides on
                    # every doc too: per-phase flops/seconds and the
                    # derived achieved-TFLOP/s + MFU percentages
                    # (None off-TPU) — the instrumentation half of the
                    # "confirm the MFU jump on hardware" stretch
                    # (telemetry.device_cost_stats, docs/observability
                    # .md "MFU")
                    result.metrics["mfu"] = telemetry.device_cost_stats()
                    # workload flight-recorder tallies ride on every
                    # doc too: records written/dropped, payload
                    # capture-vs-digest split, shard rotations, replay
                    # and score-parity outcomes (workload.py,
                    # docs/observability.md "Workload capture &
                    # replay") — zeros on runs that never record
                    from . import workload as _workload
                    result.metrics["workload"] = \
                        _workload.workload_stats()
                    # peak RSS (self + reaped children) rides on every
                    # doc too — the out-of-core streaming tier's memory
                    # evidence (telemetry.peak_rss_mb, docs/performance
                    # .md "Out-of-core training")
                    result.metrics["peak_rss_mb"] = telemetry.peak_rss_mb()
                    if collector is not None:
                        result.metrics["telemetry"] = collector.summary()
                        result.metrics["telemetryMetrics"] = \
                            telemetry.metrics_json()
                    self._write_metrics(params.metrics_location,
                                        result.metrics,
                                        fmt=params.metrics_format)
                    if params.trace_location:
                        telemetry.write_trace(params.trace_location)
                    if trace_dir:
                        telemetry.write_trace_shard(
                            str(trace_dir), role=self._shard_role(
                                run_type))
                elif params.trace_location or trace_dir:
                    # a crashed run is the run you most want the trace
                    # of: flush the spans recorded up to the failure
                    # (best-effort — never mask the run's exception)
                    try:
                        if params.trace_location:
                            telemetry.write_trace(params.trace_location)
                        if trace_dir:
                            telemetry.write_trace_shard(
                                str(trace_dir), role=self._shard_role(
                                    run_type))
                    except Exception:  # lint: broad-except — best-effort crash trace, never mask the run error
                        logger.exception("trace write failed")
            finally:
                if run_mesh:
                    # run-scoped mesh teardown (after the topology stamp
                    # above, which must reflect THIS run's mesh)
                    _mesh.set_process_mesh(prev_mesh)
                if run_scoped:
                    # run-scoped teardown, even when a sink write fails:
                    # recording stops AND this run's events/metrics are
                    # dropped, so the next requested run gets a clean
                    # per-run trace (user-registered listeners survive)
                    telemetry.disable()
                    telemetry.reset(keep_listeners=True)
        return result

    def _execute(self, run_type: str, params: OpParams,
                 t0: float) -> RunnerResult:
        if run_type == RunType.TRAIN:
            params.apply_to_workflow(self.workflow)
            # the compile-time-type-safety analog: a mis-wired DAG (or a
            # leaky cutoff configuration, TMG7xx) is rejected HERE,
            # before the reader touches a byte
            self._preflight(params, workflow=self.workflow,
                            reader=self.training_reader)
            # cost-based plan (graph-only pre-fit): train follows its
            # per-phase tier decisions
            wf_plan = self._plan_step(params, workflow=self.workflow)
            if self.training_reader is not None:
                self.workflow.set_reader(self.training_reader)
            model = self.workflow.train()
            if wf_plan is not None:
                # measured fit costs feed the persisted db; the stamped
                # plan upgrades to the full fitted-model plan
                self._record_plan_costs(model)
            # multi-host: every process computes the identical model;
            # only the coordinator touches the shared filesystem
            from .parallel.multihost import is_coordinator, process_summary
            if params.model_location and is_coordinator():
                model.save(params.model_location, overwrite=True)
            metrics = model.summary()
            metrics["appSeconds"] = round(time.perf_counter() - t0, 3)
            metrics["process"] = process_summary()
            # RawFeatureFilter verdict (None = no filter configured):
            # exclusions + whether the train-time distributions the
            # serving-time drift sentinel compares against were
            # persisted with the model (docs/lifecycle.md)
            metrics["rawFeatureFilter"] = (
                model.rff_results.summary()
                if model.rff_results is not None else None)
            return RunnerResult(run_type, metrics=metrics,
                                model_location=params.model_location)

        from .workflow import WorkflowModel
        if params.model_location is None:
            raise ValueError(f"{run_type} requires modelLocation")
        model = WorkflowModel.load(params.model_location)
        # graph + eval_shape device pre-flight on the loaded model,
        # before the scoring/evaluation reader does any I/O
        self._preflight(params, model=model)
        # cost-based plan, attached so the scoring engine follows its
        # CSE/pruning/tier decisions (still before any reader I/O)
        self._plan_step(params, model=model)

        if run_type == RunType.SCORE:
            reader = self.scoring_reader
            data = reader.read_records()
            scores = model.score(data)
            if params.write_location:
                _write_store_csv(scores, params.write_location)
            # serving-path costs feed the persisted db too (PR 18): the
            # tuner's priors must see Score-phase observations, not just
            # the post-Train drain
            self._record_score_costs()
            metrics = {"rowsScored": scores.n_rows,
                       "appSeconds": round(time.perf_counter() - t0, 3)}
            return RunnerResult(run_type, metrics=metrics, scores=scores)

        if run_type == RunType.STREAMING_SCORE:
            # incremental batch scoring (OpWorkflowRunner StreamingScore /
            # StreamingReaders analog): fixed-size record batches through
            # readers.stream_score; each batch is written to the sink and
            # DROPPED, so peak memory is one batch — not the dataset
            from .readers import stream_score
            reader = self.scoring_reader
            # maxBatches/timeoutS bound the directory-stream loop for
            # non-daemon runs. Validated up front WHATEVER the reader —
            # a malformed value must name its key now, not crash as an
            # uncaught float(ts) mid-stream (or pass silently until the
            # reader is swapped for a streaming one)
            mb = _numeric_custom_param(params, "maxBatches", int,
                                       minimum=1)
            ts = _numeric_custom_param(params, "timeoutS", float,
                                       minimum=0)
            # staged input pipeline (pipeline.py): parallel decode/prep
            # workers, autotuned prefetch, double-buffered uploads.
            # customParams.overlap true/false force/forbid the pipelined
            # engine path (default auto); pipeline false drops back to
            # single-thread ingest; pipelineWorkers/pipelineDepth bound
            # the pool and the prefetch ceiling (null = module
            # defaults). ALL validated up front — a malformed value
            # names its key now (TMG001 via `cli check`), not deep in
            # the stream.
            overlap = _bool_custom_param(params, "overlap",
                                         default="auto", allow_auto=True)
            pipe_on = _bool_custom_param(params, "pipeline", default=True)
            pipe_workers = _numeric_custom_param(
                params, "pipelineWorkers", int, minimum=1)
            pipe_depth = _numeric_custom_param(
                params, "pipelineDepth", int, minimum=1)
            restore_columnar = None
            if not pipe_on:
                # the run-scoped kill switch mirrors TMOG_PIPELINE=0:
                # single-thread decode/prep AND the pre-pipeline scoring
                # path — no staged uploads (overlap wins over an
                # explicit true), per-record Python decode. The reader's
                # columnar flag is saved and restored in the finally
                # below: run-scoped like the knob itself, so a later
                # pipelined run on the SAME reader instance keeps the
                # vectorized decode.
                pipe_workers, pipe_depth = 1, 1
                overlap = False
                if hasattr(reader, "columnar"):
                    restore_columnar = bool(reader.columnar)
                    reader.columnar = False
            try:
                if hasattr(reader, "stream"):
                    # directory-watching reader (StreamingReaders
                    # analog): each NEW file is one micro-batch, decoded
                    # on the pipeline's worker pool when one is
                    # configured
                    batch = "per-file"
                    import inspect

                    from .pipeline import resolve_workers
                    kw: Dict[str, Any] = {"max_batches": mb,
                                          "timeout_s": ts}
                    # the reader contract predates the pipeline: a
                    # duck-typed stream(max_batches, timeout_s) without
                    # the workers knob keeps streaming serially instead
                    # of crashing on an unexpected kwarg
                    try:
                        sig = inspect.signature(reader.stream).parameters
                        if "workers" in sig or any(
                                p.kind is inspect.Parameter.VAR_KEYWORD
                                for p in sig.values()):
                            kw["workers"] = resolve_workers(pipe_workers)
                    except (TypeError, ValueError):
                        pass        # unintrospectable callable: old contract
                    batches = reader.stream(**kw)
                else:
                    data = reader.read_records()
                    batch = _numeric_custom_param(params, "batchSize",
                                                  int, default=1024,
                                                  minimum=1)
                    batches = (data[i:i + batch]
                               for i in range(0, len(data), batch))
                # sink-aware default (resilience.resolve_on_error): with
                # a quarantineLocation configured, poison batches
                # quarantine; without one their records would land
                # nowhere, so the run fails loudly instead.
                # customParams.onBatchError overrides.
                on_error = params.custom_params.get("onBatchError")  # lint: knob — enum passthrough, resilience.resolve_on_error validates
                rows = 0
                n_batches = 0
                q_before = resilience.resilience_stats()
                pipe_before = _pipeline_stats()
                sink = (_make_sink(params.write_location)
                        if params.write_location else None)
                try:
                    for scored in stream_score(model, batches,
                                               overlap=overlap,
                                               on_error=on_error,
                                               workers=pipe_workers,
                                               prefetch=pipe_depth):
                        rows += scored.n_rows
                        n_batches += 1
                        if sink is not None:
                            sink.write(scored)
                    if sink is not None and n_batches == 0:
                        # header-only output (as SCORE produces on
                        # empty input)
                        sink.write_header(
                            [f.name for f in model.result_features])
                finally:
                    if sink is not None:
                        sink.close()
            finally:
                if restore_columnar is not None:
                    reader.columnar = restore_columnar
            self._record_score_costs()
            q_after = resilience.resilience_stats()
            pipe_after = _pipeline_stats()
            pipe_streams = (pipe_after["streams"]
                            - pipe_before["streams"])
            metrics = {"rowsScored": rows, "batches": n_batches,
                       "batchSize": batch, "overlap": overlap,
                       # THIS run's pipeline evidence: the converged
                       # prefetch depth + worker count + starvation and
                       # buffer-churn deltas (docs/performance.md
                       # "Input pipeline"). last_* tallies are
                       # process-global, so they only count as this
                       # run's facts when this run actually streamed
                       # pipelined — null otherwise (plain path)
                       "pipelineWorkers":
                           (pipe_after["last_workers"]
                            if pipe_streams else None),
                       "prefetchDepth":
                           (pipe_after["last_prefetch_depth"]
                            if pipe_streams else None),
                       "pipelineStarvations":
                           pipe_after["starvations"]
                           - pipe_before["starvations"],
                       "bufferReuses":
                           pipe_after["buffer_reuses"]
                           - pipe_before["buffer_reuses"],
                       "quarantinedBatches":
                           q_after["quarantined_batches"]
                           - q_before["quarantined_batches"],
                       "quarantinedFiles":
                           q_after["quarantined_files"]
                           - q_before["quarantined_files"],
                       "appSeconds": round(time.perf_counter() - t0, 3)}
            return RunnerResult(run_type, metrics=metrics)

        if run_type == RunType.EVALUATE:
            reader = self.evaluation_reader
            data = reader.read_records()
            metrics = model.evaluate(data, self.evaluator)
            metrics = dict(metrics)
            metrics["appSeconds"] = round(time.perf_counter() - t0, 3)
            return RunnerResult(run_type, metrics=metrics)

        # FEATURES: materialize the engineered features only.
        # features_to_compute may be one Feature or a list; transform's
        # up_to prunes the DAG for a single target, several targets
        # compute the full DAG (their union).
        reader = self.training_reader or self.scoring_reader
        data = reader.read_records()
        ftc = self.features_to_compute
        if isinstance(ftc, (list, tuple)):
            ftc = ftc[0] if len(ftc) == 1 else None
        store = model.transform(data, up_to=ftc)
        if params.write_location:
            _write_store_csv(store, params.write_location)
        metrics = {"rows": store.n_rows,
                   "appSeconds": round(time.perf_counter() - t0, 3)}
        return RunnerResult(run_type, metrics=metrics, scores=store)


class _CsvSink:
    """Incremental CSV sink (saveScores analog): header from the first
    store, batches appended as they arrive. On non-coordinator processes
    of a multi-host run the sink is a no-op — every host computes the
    identical scores and one writer owns the shared file."""

    def __init__(self, path: str):
        import csv

        from .parallel.multihost import is_coordinator
        self._active = is_coordinator()
        self._names = None
        if not self._active:
            self._fh = self._writer = None
            return
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "w", newline="")
        self._writer = csv.writer(self._fh)

    def write_header(self, names) -> None:
        if self._names is None:
            self._names = list(names)
            if self._active:
                self._writer.writerow(self._names)

    def write(self, store) -> None:
        self.write_header(store.names())
        if not self._active:
            return
        for i in range(store.n_rows):
            self._writer.writerow([store[n].get_raw(i)
                                   for n in self._names])

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()


class _AvroSink:
    """Incremental Avro container sink (``saveScores``/``saveAvro``,
    ``OpWorkflowModel.scala:376-421``): schema inferred from the first
    batch, each batch appended as one sync-delimited block. Score stores
    are already column-pruned to the result features (+ keys) by
    ``WorkflowModel.score``. Coordinator-only, like the CSV sink."""

    def __init__(self, path: str):
        from .parallel.multihost import is_coordinator
        self._active = is_coordinator()
        self._path = path
        self._names = None
        self._writer = None

    def write_header(self, names) -> None:
        if self._names is None:
            self._names = list(names)

    @staticmethod
    def _pyify(v):
        import numpy as np
        if isinstance(v, np.ndarray):
            return v.tolist()
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, dict):
            return {k: _AvroSink._pyify(x) for k, x in v.items()}
        if isinstance(v, (set, frozenset)):
            return sorted(_AvroSink._pyify(x) for x in v)
        if isinstance(v, (list, tuple)):
            return [_AvroSink._pyify(x) for x in v]
        return v

    def write(self, store) -> None:
        self.write_header(store.names())
        if not self._active:
            return
        records = [{n: self._pyify(store[n].get_raw(i))
                    for n in self._names}
                   for i in range(store.n_rows)]
        if not records:
            return      # empty store: close() writes the header-only file
        if self._writer is None:
            from .readers.avro import AvroWriter, infer_avro_schema
            self._writer = AvroWriter(
                self._path, infer_avro_schema(records))
        self._writer.append(records)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
        elif self._active and self._names is not None:
            # header-only output on empty input (schema from names alone)
            from .readers.avro import AvroWriter
            AvroWriter(self._path, {
                "type": "record", "name": "ScoreRecord",
                "fields": [{"name": n, "type": ["null", "string"]}
                           for n in self._names]}).close()


def _make_sink(path: str):
    """Sink by extension: ``.avro`` → Avro container, else CSV
    (the reference's saveScores writes Avro; CSV stays the default)."""
    return _AvroSink(path) if path.endswith(".avro") else _CsvSink(path)


def _write_store_csv(store, path: str) -> None:
    """One-shot sink over a single store (CSV or Avro by extension)."""
    sink = _make_sink(path)
    try:
        sink.write(store)
    finally:
        sink.close()


class OpApp:
    """Abstract application entry (OpApp.scala): subclass provides a
    runner; ``main(argv)`` parses ``--run-type`` + ``--params`` and runs."""

    def runner(self, params: OpParams) -> OpWorkflowRunner:
        raise NotImplementedError

    def main(self, argv: Optional[Sequence[str]] = None) -> RunnerResult:
        ap = argparse.ArgumentParser(description=type(self).__name__)
        ap.add_argument("--run-type", required=True, choices=RunType.ALL)
        ap.add_argument("--params", help="OpParams json/yaml file")
        ap.add_argument("--model-location")
        ap.add_argument("--write-location")
        ap.add_argument("--metrics-location")
        ap.add_argument("--trace-out", metavar="PATH",
                        help="enable telemetry and write a Chrome "
                             "trace-event JSON here (Perfetto-loadable)")
        ap.add_argument("--metrics-format", choices=("json", "prometheus"),
                        help="metrics sink format; prometheus enables "
                             "telemetry and writes the registry in text "
                             "exposition format")
        ap.add_argument("--compile-cache-dir", metavar="DIR",
                        help="persistent XLA compilation cache directory "
                             "(jax_compilation_cache_dir): repeat cold "
                             "runs reload compiled programs instead of "
                             "re-paying the compile clock")
        ap.add_argument("--mesh-devices", type=int, metavar="N",
                        help="devices in the run's (data, grid) mesh "
                             "(customParams.meshDevices): bound the "
                             "multichip substrate the heavy phases shard "
                             "over; default = all visible devices "
                             "(docs/performance.md 'Multichip execution')")
        ap.add_argument("--quarantine-out", metavar="PATH",
                        help="poison-record dead-letter sink (JSONL): "
                             "unreadable stream files and failed "
                             "scoring batches land here with a reason "
                             "instead of being dropped (see "
                             "docs/robustness.md)")
        ap.add_argument("--fail-on", choices=("error", "warning"),
                        help="pre-flight gating severity (lint.py): "
                             "'error' (default) blocks only on errors, "
                             "'warning' blocks on warnings too")
        ap.add_argument("--no-validate", action="store_true",
                        help="skip the on-by-default static pre-flight "
                             "check (customParams.validate: false)")
        ap.add_argument("--quiet", action="store_true",
                        help="suppress INFO progress logging")
        args = ap.parse_args(argv)
        if not args.quiet:
            from . import enable_logging
            enable_logging()
        params = (OpParams.from_file(args.params) if args.params
                  else OpParams())
        if args.model_location:
            params.model_location = args.model_location
        if args.write_location:
            params.write_location = args.write_location
        if args.metrics_location:
            params.metrics_location = args.metrics_location
        if args.trace_out:
            params.trace_location = args.trace_out
        if args.metrics_format:
            params.metrics_format = args.metrics_format
        if args.compile_cache_dir:
            params.custom_params["compileCacheDir"] = args.compile_cache_dir
        if args.mesh_devices is not None:
            params.custom_params["meshDevices"] = args.mesh_devices
        if args.quarantine_out:
            params.quarantine_location = args.quarantine_out
        if args.fail_on:
            params.custom_params["failOn"] = args.fail_on
        if args.no_validate:
            params.custom_params["validate"] = False
        return self.runner(params).run(args.run_type, params)
