"""Multi-tenant model server — scoring at traffic scale, not batch scale.

The Clipper analog (PAPERS.md): a serving tier that holds N loaded
models behind a capacity-bounded LRU, gives each model its own request
queue with **dynamic micro-batching** — concurrent requests coalesce up
to a deadline into one engine dispatch padded to the nearest
power-of-two ladder bucket, results scattered back per request — and
reports per-model latency/throughput/queue-depth SLO instruments. The
AOT program bank (aot.py) supplies the cold-start story: a freshly
loaded model answers its first request without a single XLA compile.

Correctness contract
--------------------

* **Co-batching is bit-identical.** Every fused stage is row-independent
  (the scoring-engine contract), so a request's rows compute the same
  values whether padded with zeros or with another tenant's rows. The
  chaos test pins the solo oracle to the coalesced dispatch's bucket
  (``ScoringEngine.score_store(bucket_min=...)``) and asserts
  ``np.array_equal`` — the same program, byte-for-byte the same answers.
* **Failure is contained.** Each model carries its own device-tier
  circuit breaker (the per-model ``WorkflowModel._engine_breaker``); a
  failed micro-batch dispatch retries per request on the host path; a
  request that BOTH tiers reject is quarantined (resilience dead-letter
  sink) and its future carries the error — the server never dies with
  traffic in flight. ``server.dispatch`` is a registered fault site, so
  chaos plans can score the whole path deterministically.
* **Backpressure is explicit.** Queues are bounded; a full queue rejects
  the request with :class:`ServerBusy` (HTTP 429) instead of buffering
  without bound. Graceful shutdown drains every queued request before
  workers exit.

The HTTP front end is stdlib-only (``http.server``)::

    POST /v1/models/<name>:score   {"records": [...]}  → scored rows
                                   (504 once request_timeout_s elapses)
    GET  /v1/models                → model table + stats
    GET  /healthz                  → liveness (503 once shutdown began)
    GET  /readyz                   → readiness: loadable tenants +
                                     queue headroom (docs/fleet.md)
    GET  /stats                    → server_stats() + per-model stats

Run it with ``python -m transmogrifai_tpu serve params.json`` (knobs:
``customParams.serve*`` — see docs/serving.md).
"""
from __future__ import annotations

import hashlib
import json
import logging
import queue
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from . import aot, lifecycle, resilience, telemetry, workload
from .utils import locks
from .lifecycle import RegistryError

logger = logging.getLogger(__name__)

__all__ = ["ModelServer", "RequestResult", "ServerError", "ModelNotFound",
           "ServerBusy", "ServerClosed", "RolloutError", "serve_http",
           "server_stats", "reset_server_stats", "READY_MIN_HEADROOM",
           "DEFAULT_BATCH_DEADLINE_MS", "DEFAULT_MAX_QUEUE",
           "DEFAULT_MAX_MODELS", "DEFAULT_CANARY_FRACTION",
           "DEFAULT_ROLLOUT_WINDOW_REQUESTS", "DEFAULT_PROMOTE_WINDOWS"]

#: how long the micro-batcher holds the first queued request open for
#: co-riders before dispatching (ms). 0 = dispatch immediately.
DEFAULT_BATCH_DEADLINE_MS = 2.0

#: bounded per-model queue — beyond it, submit() raises ServerBusy
DEFAULT_MAX_QUEUE = 256

#: loaded models held before the LRU evicts
DEFAULT_MAX_MODELS = 4

#: online deadline adaptation (PR 18, docs/tuning.md "Online
#: adaptation"): hysteresis window — completed requests a tenant must
#: accumulate between controller decisions, so one noisy request can
#: never flap the deadline
ADAPT_WINDOW_REQUESTS = 64

#: AIMD shape: additive-increase step (seconds) toward more
#: coalescing, multiplicative-decrease factor when queue-wait
#: dominates, and the dead-band ratio between the two phase medians
#: inside which the controller holds still
ADAPT_STEP_S = 0.0005
ADAPT_MD_FACTOR = 0.5
ADAPT_DEADBAND = 1.25

#: converged-deadline-vs-configured ratio past which the controller
#: raises the TMG406 advisory: live telemetry contradicts the tuned
#: params file (re-run `python -m transmogrifai_tpu tune`)
ADAPT_ADVISORY_RATIO = 2.0

#: per-model latency reservoir for exact p50/p95/p99 in stats
_LATENCY_WINDOW = 4096

#: per-request latency-decomposition phases (docs/observability.md
#: "Latency decomposition"): time in the bounded queue before first
#: worker pickup, time held open for co-riding requests, the device
#: (or host-fallback) dispatch itself, and result scatter-back
_LATENCY_PHASES = ("queueWait", "coalesceHold", "deviceDispatch",
                   "scatter")

#: telemetry histogram suffix per phase (server.<suffix>.<model>)
_PHASE_METRIC = {"queueWait": "queue_wait_seconds",
                 "coalesceHold": "coalesce_hold_seconds",
                 "deviceDispatch": "device_dispatch_seconds",
                 "scatter": "scatter_seconds"}

#: default request fraction a canary rollout routes to the candidate
DEFAULT_CANARY_FRACTION = 0.1

#: completed requests that make one rollout evaluation window
DEFAULT_ROLLOUT_WINDOW_REQUESTS = 64

#: consecutive clean windows before a rollout auto-promotes
DEFAULT_PROMOTE_WINDOWS = 3

#: readiness gate: the server reports NOT ready once its summed queue
#: depth leaves less than this fraction of total queue capacity free —
#: a router keeps sending to a busy-but-ready worker and stops before
#: the queues actually overflow into 429s
READY_MIN_HEADROOM = 0.1

#: record batches the off-path drift queue holds before it starts
#: dropping (dropped batches are tallied, never block a worker)
DRIFT_QUEUE_DEPTH = 64

#: rows the sentinel thread coalesces into one sketch pass when a
#: backlog builds. Large passes amortize the histogram fixed costs AND
#: the GIL convoy tax of waking next to busy workers — fewer, longer
#: passes beat many short ones for serving throughput, at the price of
#: a few ms of worker stall per pass.
DRIFT_COALESCE_ROWS = 2048

#: ceiling on the fraction of host CPU (GIL time) the sentinel thread
#: may consume: after each sketch pass of ``dt`` seconds it sleeps
#: ``dt * (1/duty - 1)``, capped at 2 s. Under saturated Python-bound
#: serving the queue overflows and DROPS observations (a sampling
#: sentinel) rather than slowing the score path — drift detection
#: needs a statistically representative window, not every row. The
#: nominal duty badly under-states the real cost for GIL-heavy
#: workers (convoy/switch latency rides on top of the work share), so
#: it is set far below the drift_canary bench's 5% overhead gate:
#: with the cap this works out to one coalesced few-ms sketch pass
#: every ~2 s under saturation. On accelerator-backed serving the
#: workers hold the GIL far less, so the same throttle admits far
#: more observation.
DRIFT_DUTY_CYCLE = 0.002


# ---------------------------------------------------------------------------
# always-on tallies (bench docs stamp these; telemetry mirrors when enabled)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"requests": 0, "requests_failed": 0, "rows": 0, "batches": 0,
          "coalesced_requests": 0, "bank_hit_batches": 0, "rejected": 0,
          "quarantined_requests": 0, "model_loads": 0, "model_evictions": 0,
          "bank_loads": 0, "slo_met": 0, "slo_missed": 0,
          "requests_timed_out": 0, "timed_out_completions": 0,
          "deadline_adapt_windows": 0, "deadline_increases": 0,
          "deadline_decreases": 0, "deadline_holds": 0,
          "deadline_clamped": 0, "deadline_advisories": 0}


def server_stats() -> Dict[str, Any]:
    """Process-wide serving tallies (always on, the
    ``engine_cache_stats`` discipline) plus the derived headline
    numbers: ``batch_coalescing_factor`` (requests per dispatch),
    ``bank_hit_rate`` (dispatches served by an AOT-banked program) and
    ``slo_attainment`` (fraction of SLO-tracked requests under the
    deadline; None when no SLO is configured)."""
    with _TALLY_LOCK:
        out: Dict[str, Any] = dict(_TALLY)
    out["batch_coalescing_factor"] = (
        round(out["requests"] / out["batches"], 3) if out["batches"]
        else None)
    out["bank_hit_rate"] = (
        round(out["bank_hit_batches"] / out["batches"], 3)
        if out["batches"] else None)
    tracked = out["slo_met"] + out["slo_missed"]
    out["slo_attainment"] = (round(out["slo_met"] / tracked, 4)
                             if tracked else None)
    return out


def reset_server_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


# ---------------------------------------------------------------------------
# request plumbing
# ---------------------------------------------------------------------------


class ServerError(Exception):
    """Base class for serving-tier rejections."""


class ModelNotFound(ServerError):
    pass


class ServerBusy(ServerError):
    """Admission control: the model's bounded queue is full — explicit
    backpressure instead of unbounded buffering (HTTP 429)."""


class ServerClosed(ServerError):
    pass


class RolloutError(ServerError):
    """Rollout misuse: no registry attached, unknown version, a rollout
    already in flight, or an invalid deploy mode/fraction."""


@dataclass
class RequestResult:
    """One request's scored slice plus its dispatch provenance."""

    store: Any                  # ColumnStore of the result columns
    rows: int
    bucket: int                 # padded ladder bucket the dispatch used
    coalesced: int              # requests sharing that dispatch
    seconds: float              # queue-to-completion latency
    engine_tier: bool           # True = compiled engine, False = host
    canary: bool = False        # True = scored by a canary candidate
    #: this request's per-phase latency decomposition (queueWait /
    #: coalesceHold / deviceDispatch / scatter seconds — partial when a
    #: phase was skipped); the HTTP front end surfaces it in the
    #: response body and the workload flight recorder persists it
    decomp: Optional[Dict[str, float]] = None


class _Rollout:
    """One in-flight shadow/canary rollout on a served model.

    Mutated only by the model's single worker thread (window counters)
    and read by stats; installation/clearing happens under the entry
    lock. ``clean_windows`` consecutive clean evaluation windows — no
    candidate failure, no SLO miss on candidate traffic, no new drift
    advisory, no shadow parity mismatch — trigger automated promotion;
    a breaker trip / dispatch failure / SLO breach triggers automated
    rollback immediately."""

    def __init__(self, mode: str, version: Optional[str], fraction: float,
                 model: Any, engine: Any, bank_buckets: List[int],
                 bank_report: Optional[Dict[str, Any]],
                 model_dir: Optional[str], bank_dir: Optional[str],
                 window_requests: int, promote_windows: int,
                 drift_gate: bool = True):
        self.mode = mode
        self.version = version
        self.fraction = float(fraction)
        #: False = new drift advisories do NOT block promotion — set by
        #: the continual tier, whose candidate was trained ON the
        #: drifted window: the stable baseline's TMG601 is the trigger
        #: that launched this rollout, not evidence against it
        self.drift_gate = bool(drift_gate)
        self.model = model
        self.engine = engine
        self.bank_buckets = list(bank_buckets)
        self.bank_report = bank_report
        self.model_dir = model_dir
        self.bank_dir = bank_dir
        self.window_requests = max(int(window_requests), 1)
        self.promote_windows = max(int(promote_windows), 1)
        # window-scoped evidence (reset each evaluation window)
        self.win_requests = 0
        self.win_failures = 0
        self.win_slo_missed = 0
        self.win_parity_mismatch = 0
        #: candidate-touching requests this window (canary scored or
        #: shadow compared) — a window with NONE proves nothing and
        #: must not advance the promotion count
        self.win_evidence = 0
        # rollout-cumulative evidence
        self.windows = 0
        self.clean_windows = 0
        self.canary_requests = 0
        self.shadow_requests = 0
        self.shadow_batches = 0
        self.parity_ok = 0
        self.parity_mismatch = 0
        self.shadow_seconds = 0.0
        self.primary_seconds = 0.0
        self.drift_seen = 0          # entry sentinel advisories at window start

    def status(self) -> Dict[str, Any]:
        compared = self.parity_ok + self.parity_mismatch
        return {"mode": self.mode, "version": self.version,
                "fraction": self.fraction,
                "windowRequests": self.window_requests,
                "promoteWindows": self.promote_windows,
                "windows": self.windows,
                "cleanWindows": self.clean_windows,
                "canaryRequests": self.canary_requests,
                "shadowRequests": self.shadow_requests,
                "parityOk": self.parity_ok,
                "parityMismatch": self.parity_mismatch,
                "parityRate": (round(self.parity_ok / compared, 4)
                               if compared else None),
                "shadowLatencyDeltaMs": (
                    round((self.shadow_seconds - self.primary_seconds)
                          / max(self.shadow_batches, 1) * 1e3, 3)
                    if self.shadow_batches else None)}


class _Request:
    __slots__ = ("records", "future", "t_enqueued", "rows", "trace",
                 "t_dequeued", "t_dispatch0", "t_dispatch1",
                 "dispatch_s")

    def __init__(self, records: List[Dict[str, Any]],
                 trace: Optional[tuple] = None):
        self.records = list(records)
        self.rows = len(self.records)
        self.future: "Future[RequestResult]" = Future()
        self.t_enqueued = time.perf_counter()
        #: (trace_id, span_id) of the request span that enqueued this —
        #: the micro-batcher links it from the batch span
        #: (docs/observability.md "Distributed tracing")
        self.trace = trace
        #: latency-decomposition marks (docs/observability.md "Latency
        #: decomposition"): first worker pickup, dispatch start/end and
        #: this request's share of device-dispatch time
        self.t_dequeued: Optional[float] = None
        self.t_dispatch0: Optional[float] = None
        self.t_dispatch1: Optional[float] = None
        self.dispatch_s: Optional[float] = None


_SENTINEL = object()


class _ModelEntry:
    """One registered model: its queue, worker, loaded state and stats."""

    def __init__(self, name: str, model_dir: Optional[str],
                 bank_dir: Optional[str], model: Any,
                 max_queue: int):
        self.name = name
        self.model_dir = model_dir
        self.bank_dir = bank_dir
        #: a model registered as a live object (no directory) cannot be
        #: reloaded after eviction, so the LRU pins it
        self.pinned = model is not None and model_dir is None
        self.model = model
        self.engine = None
        self.bank_buckets: List[int] = []
        self.bank_report: Optional[Dict[str, Any]] = None
        #: True = model_dir/bank_dir re-resolve through the registry's
        #: ``current`` pointer on every (re)load, so an evicted tenant
        #: picks up a promote when it comes back
        self.via_registry = False
        #: lifecycle.DriftSentinel over live traffic (None = drift off)
        self.sentinel: Any = None
        #: in-flight shadow/canary rollout (_Rollout), None otherwise
        self.rollout: Optional["_Rollout"] = None
        self.weight_bytes = 0
        self.queue: "queue.Queue[Any]" = queue.Queue(maxsize=max_queue)
        # guards load/unload; order-witnessed under chaos tests
        self.lock = locks.witness_lock("server._ModelEntry.lock")
        self.worker: Optional[threading.Thread] = None
        self.latencies: "deque[float]" = deque(maxlen=_LATENCY_WINDOW)
        #: per-phase latency reservoirs — the end-to-end number above,
        #: decomposed: where did this request's milliseconds go?
        #: (docs/observability.md "Latency decomposition")
        self.decomp: Dict[str, "deque[float]"] = {
            ph: deque(maxlen=_LATENCY_WINDOW) for ph in _LATENCY_PHASES}
        #: per-tenant telemetry metric names, formatted ONCE (the
        #: completion path observes several per request)
        self.metric_names = {
            "request": f"server.request_seconds.{name}",
            "queue": f"server.queue_depth.{name}",
            **{ph: f"server.{_PHASE_METRIC[ph]}.{name}"
               for ph in _LATENCY_PHASES}}
        self.requests = 0
        self.failures = 0
        self.rows = 0
        self.batches = 0
        self.bank_hit_batches = 0
        self.loads = 0
        #: online deadline adaptation (PR 18): the tenant's effective
        #: micro-batching hold. None = adaptation never touched it and
        #: the worker reads the server-wide ``batch_deadline_s``
        #: directly — the disabled path is bit-inert by construction.
        #: Only the tenant's own worker thread writes it, and only
        #: BETWEEN dispatches (never mid-request).
        self.deadline_s: Optional[float] = None
        self.adapt_seen = 0          # requests consumed by past windows
        self.adapt_increases = 0
        self.adapt_decreases = 0
        self.adapt_clamped = 0
        self.deadline_advised = False

    @staticmethod
    def _pct(values) -> Dict[str, float]:
        lat = np.asarray(values, dtype=np.float64)
        if not lat.size:
            return {}
        return {"p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
                "p95_ms": round(float(np.percentile(lat, 95)) * 1e3, 3),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3)}

    def stats(self) -> Dict[str, Any]:
        pct = self._pct(self.latencies)
        rollout = self.rollout
        sentinel = self.sentinel
        return {"loaded": self.model is not None, "pinned": self.pinned,
                "requests": self.requests, "failures": self.failures,
                "rows": self.rows, "batches": self.batches,
                "bankBuckets": list(self.bank_buckets),
                "bankHitBatches": self.bank_hit_batches,
                "weightBytes": self.weight_bytes,
                "queueDepth": self.queue.qsize(), "loads": self.loads,
                "viaRegistry": self.via_registry,
                "rollout": rollout.status() if rollout else None,
                "drift": sentinel.stats() if sentinel else None,
                "adaptiveDeadlineMs": (
                    None if self.deadline_s is None
                    else round(self.deadline_s * 1e3, 4)),
                "deadlineAdaptations": {
                    "increases": self.adapt_increases,
                    "decreases": self.adapt_decreases,
                    "clamped": self.adapt_clamped},
                "latency": {"e2e": pct,
                            **{ph: self._pct(self.decomp[ph])
                               for ph in _LATENCY_PHASES}},
                **pct}


class ModelServer:
    """N models behind a weighted LRU, one micro-batching worker each.

    ``capacity_bytes`` bounds the summed program-bank weight of loaded
    models (``max_models`` bounds their count); the least-recently-used
    reloadable model is unloaded when either bound is crossed and
    transparently reloaded on its next request. ``batch_deadline_s`` is
    the micro-batching hold; ``slo_ms`` (optional) scores each request
    against a latency SLO in stats and telemetry."""

    def __init__(self, max_models: int = DEFAULT_MAX_MODELS,
                 capacity_bytes: Optional[int] = None,
                 batch_deadline_s: float = DEFAULT_BATCH_DEADLINE_MS / 1e3,
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 slo_ms: Optional[float] = None,
                 bucket_cap: Optional[int] = None,
                 registry: Optional["lifecycle.ModelRegistry"] = None,
                 drift_window: Optional[int] = None,
                 drift_js_threshold: float = lifecycle.DEFAULT_JS_THRESHOLD,
                 drift_fill_delta: float =
                 lifecycle.DEFAULT_FILL_DELTA_THRESHOLD,
                 canary_fraction: float = DEFAULT_CANARY_FRACTION,
                 adapt_deadline: bool = False):
        if max_models < 1:
            raise ValueError(f"max_models must be >= 1, got {max_models}")
        self.max_models = int(max_models)
        self.capacity_bytes = (None if capacity_bytes is None
                               else int(capacity_bytes))
        self.batch_deadline_s = max(float(batch_deadline_s), 0.0)
        #: online deadline adaptation (PR 18, docs/tuning.md): a
        #: bounded AIMD controller nudges each tenant's micro-batching
        #: hold against its measured queue-wait/coalesce-hold split,
        #: BETWEEN dispatches only, clamped to the registry-declared
        #: serveBatchDeadlineMs tuning bounds. TMOG_ADAPT=0 is the
        #: process-wide kill switch; disabled (the default) the worker
        #: reads ``batch_deadline_s`` exactly as before — bit-inert.
        import os as _os
        if _os.environ.get("TMOG_ADAPT", "").strip() == "0":
            adapt_deadline = False
        self.adapt_deadline = bool(adapt_deadline)
        from . import config as _config
        lo, hi = _config.knob_bounds("serveBatchDeadlineMs")
        self._adapt_bounds_s = (max(lo, 0.0) / 1e3, hi / 1e3)
        self.max_queue = int(max_queue)
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.bucket_cap = bucket_cap
        #: model lifecycle wiring (lifecycle.py): the registry resolves
        #: versioned tenants + receives promote/rollback; drift_window
        #: (rows) turns the serving-time drift sentinel on per tenant
        self._registry = registry
        self.drift_window = (None if drift_window is None
                             else int(drift_window))
        self.drift_js_threshold = float(drift_js_threshold)
        self.drift_fill_delta = float(drift_fill_delta)
        self.canary_fraction = float(canary_fraction)
        #: LRU order: oldest first; touched on every submit
        self._entries: "OrderedDict[str, _ModelEntry]" = OrderedDict()
        self._lock = locks.witness_lock("server.ModelServer._lock")
        self._closed = False
        #: per-tenant drift-window subscribers (continual.py's retrain
        #: controller): re-attached every time a tenant's sentinel is
        #: rebuilt (reload, eviction, promote), so a subscription
        #: survives the sentinel's lifecycle
        self._drift_subscribers: Dict[str, List[Any]] = {}
        #: off-path drift accumulation: dispatch workers enqueue scored
        #: record batches O(1) and ONE shared sentinel thread folds them
        #: into the tenants' sketches — observation never rides a
        #: request's latency, and under saturation the bounded queue
        #: DROPS batches (tallied) instead of slowing serving
        self._drift_queue: Optional["queue.Queue[Any]"] = None
        self._drift_thread: Optional[threading.Thread] = None
        if self.drift_window:
            self._drift_queue = queue.Queue(maxsize=DRIFT_QUEUE_DEPTH)
            self._drift_thread = threading.Thread(
                target=self._drift_loop, name="serve-drift", daemon=True)
            self._drift_thread.start()

    @property
    def registry(self) -> Optional["lifecycle.ModelRegistry"]:
        return self._registry

    # -- registration / LRU ------------------------------------------------
    def register(self, name: str, model_dir: Optional[str] = None,
                 bank_dir: Optional[str] = None,
                 model: Any = None, preload: bool = False,
                 via_registry: bool = False) -> None:
        """Register a tenant: either a saved-model directory (evictable,
        reloaded on demand) or a live ``WorkflowModel`` (pinned).
        ``bank_dir`` names the export directory carrying the AOT program
        bank (aot.py); ``preload`` loads immediately instead of on first
        request. ``via_registry`` resolves model/bank dirs through the
        attached registry's ``current`` pointer on EVERY (re)load —
        eviction + reload transparently picks up a promote."""
        if via_registry:
            if self._registry is None:
                raise RolloutError(
                    "register(via_registry=True) needs a registry "
                    "attached to the server")
            rec = self._registry.resolve(name)
            model_dir, bank_dir = rec["modelDir"], rec.get("bankDir")
        if model is None and model_dir is None:
            raise ValueError("register() needs model_dir or model")
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            entry = _ModelEntry(name, model_dir, bank_dir, model,
                                self.max_queue)
            entry.via_registry = via_registry
            entry.worker = threading.Thread(
                target=self._worker_loop, args=(entry,),
                name=f"serve-{name}", daemon=True)
            self._entries[name] = entry
        entry.worker.start()
        if preload or model is not None:
            self._ensure_loaded(entry)

    def register_from_registry(self, name: str,
                               preload: bool = False) -> None:
        """Register a tenant that serves whatever the registry's
        ``current`` pointer names (and keeps re-resolving it)."""
        self.register(name, via_registry=True, preload=preload)

    def models(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def _ensure_loaded(self, entry: _ModelEntry):
        """Load (or reload) the entry's model + engine + bank; evict LRU
        models over capacity. Engine is built ``gate_bandwidth=False``
        (a serving loop amortizes every compile immediately) and
        ``mesh=False`` (banked executables are unsharded — see aot.py).

        Returns ``(model, engine, bank_buckets)`` captured UNDER the
        entry lock: a dispatch must score through these locals, never
        through ``entry.model``/``entry.engine``, because a concurrent
        LRU eviction may null the entry's slots mid-dispatch — the
        captured references keep the objects alive until the batch
        completes."""
        with entry.lock:
            if entry.model is None:
                if entry.via_registry and self._registry is not None:
                    # an evicted/reloaded registry tenant re-resolves
                    # the CURRENT pointer — a promote that happened
                    # while it was out takes effect on reload
                    try:
                        rec = self._registry.resolve(entry.name)
                        entry.model_dir = rec["modelDir"]
                        entry.bank_dir = rec.get("bankDir")
                    except RegistryError:
                        pass        # pointer gone: last-known dirs serve
                from .workflow import WorkflowModel
                with telemetry.span("server:load_model",
                                    model=entry.name):
                    entry.model = WorkflowModel.load(entry.model_dir)
                entry.loads += 1
                entry.sentinel = None       # rebuilt for the new model
                _tally("model_loads")
                telemetry.counter("server.model_loads").inc()
            if entry.engine is None:
                (entry.engine, entry.bank_buckets,
                 entry.bank_report) = self._build_engine(entry.model,
                                                         entry.bank_dir)
                entry.weight_bytes = self._entry_weight(entry)
            if entry.sentinel is None and self.drift_window:
                entry.sentinel = self._build_sentinel(entry.model,
                                                      entry.name)
            captured = (entry.model, entry.engine,
                        list(entry.bank_buckets))
        self._evict_over_capacity(keep=entry.name)
        return captured

    def _build_engine(self, model, bank_dir: Optional[str]):
        """(engine, bank_buckets, bank_report) for one loaded model —
        shared by tenant loading and rollout candidate loading so the
        two can never disagree on engine construction."""
        kw: Dict[str, Any] = {"gate_bandwidth": False, "mesh": False}
        if self.bucket_cap:
            kw["bucket_cap"] = int(self.bucket_cap)
        engine = model.scoring_engine(**kw)
        bank_buckets: List[int] = []
        bank_report: Optional[Dict[str, Any]] = None
        if engine is not None and bank_dir:
            bank_report = aot.load_program_bank(engine, bank_dir)
            bank_buckets = list(bank_report["loaded"])
            if bank_report["loaded"]:
                _tally("bank_loads")
        return engine, bank_buckets, bank_report

    def _build_sentinel(self, model, name: str):
        """The tenant's serving-time drift sentinel (None when the
        server runs driftless or the model has no persisted baseline).
        Registered drift subscribers re-attach to every rebuild — a
        promote/eviction swaps the sentinel, never the subscription."""
        if not self.drift_window:
            return None
        sentinel = lifecycle.DriftSentinel.for_model(
            model, model_name=name, window_rows=self.drift_window,
            js_threshold=self.drift_js_threshold,
            fill_delta_threshold=self.drift_fill_delta)
        if sentinel is not None:
            for fn in self._drift_subscribers.get(name, ()):
                sentinel.subscribe(fn)
        return sentinel

    def subscribe_drift(self, name: str, fn) -> None:
        """Subscribe ``fn(findings, report)`` to tenant ``name``'s
        completed drift-comparison windows (clean windows included).
        The subscription survives sentinel rebuilds (reload / eviction
        / promote) — the continual tier's retrain trigger seam."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise ModelNotFound(f"no model {name!r} registered "
                                f"(have: {self.models()})")
        # under the ENTRY lock: sentinel rebuilds (load/promote/
        # rollback) happen under it too, so the append and the
        # attach-to-current-sentinel are atomic against a rebuild — a
        # racing rebuild either sees the new subscriber in the list or
        # we attach to the sentinel it just installed, never neither
        with entry.lock:
            self._drift_subscribers.setdefault(name, []).append(fn)
            sentinel = entry.sentinel
            if sentinel is not None:
                sentinel.subscribe(fn)

    def _entry_weight(self, entry: _ModelEntry) -> int:
        """LRU weight: the bank's serialized-program bytes (the dominant
        resident cost of a served model — compiled executables), else a
        1 MiB floor so bankless models still count against capacity."""
        manifest, _ = (aot.read_manifest(entry.bank_dir)
                       if entry.bank_dir else (None, []))
        return max(aot.bank_bytes(manifest), 1 << 20)

    def _evict_over_capacity(self, keep: str) -> None:
        while True:
            victim = None
            with self._lock:
                loaded = [e for e in self._entries.values()
                          if e.model is not None and not e.pinned]
                n_loaded = sum(1 for e in self._entries.values()
                               if e.model is not None)
                total = sum(e.weight_bytes for e in self._entries.values()
                            if e.model is not None)
                over = (n_loaded > self.max_models
                        or (self.capacity_bytes is not None
                            and total > self.capacity_bytes))
                if over:
                    for e in loaded:         # LRU order: oldest first
                        if e.name != keep and e.queue.qsize() == 0:
                            victim = e
                            break
            if victim is None:
                return
            with victim.lock:
                if victim.model is None:
                    continue
                logger.info("server: evicting %s (LRU, %d bytes)",
                            victim.name, victim.weight_bytes)
                victim.model = None
                victim.engine = None
                victim.bank_buckets = []
                # the reload may resolve a DIFFERENT version (registry
                # pointer moved while evicted): the sentinel's baseline
                # belongs to the old model, rebuild on reload
                victim.sentinel = None
                _tally("model_evictions")
                telemetry.counter("server.model_evictions").inc()

    # -- request entry -----------------------------------------------------
    def submit(self, name: str, records: List[Dict[str, Any]],
               trace: Optional[tuple] = None):
        """Enqueue a scoring request; returns a
        ``concurrent.futures.Future[RequestResult]``. Raises
        :class:`ModelNotFound` / :class:`ServerBusy` /
        :class:`ServerClosed` synchronously (admission control).
        ``trace`` is the submitting request span's (trace_id, span_id)
        — the coalesced batch span links it."""
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            entry = self._entries.get(name)
            if entry is not None:
                self._entries.move_to_end(name)    # LRU touch
        if entry is None:
            raise ModelNotFound(f"no model {name!r} registered "
                                f"(have: {self.models()})")
        req = _Request(records, trace=trace)
        try:
            entry.queue.put_nowait(req)
        except queue.Full:
            _tally("rejected")
            telemetry.counter("server.rejected").inc()
            raise ServerBusy(
                f"model {name!r} queue is full ({self.max_queue} "
                "pending) — back off and retry") from None
        if telemetry.enabled():
            telemetry.gauge(f"server.queue_depth.{name}").set(  # lint: metric-name — per-tenant gauge, bounded by the registered roster
                entry.queue.qsize())
        return req.future

    def score(self, name: str, records: List[Dict[str, Any]],
              timeout_s: Optional[float] = 30.0) -> RequestResult:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(name, records).result(timeout=timeout_s)

    # -- micro-batching worker ---------------------------------------------
    def _worker_loop(self, entry: _ModelEntry) -> None:
        from .scoring import DEFAULT_BUCKET_CAP
        cap = int(self.bucket_cap or DEFAULT_BUCKET_CAP)
        stop = False
        while not stop:
            item = entry.queue.get()
            if item is _SENTINEL:
                break
            item.t_dequeued = time.perf_counter()
            batch: List[_Request] = [item]
            rows = item.rows
            # the effective hold: the tenant's adapted deadline once
            # the controller has moved it, else the configured one —
            # read ONCE per batch, so an adaptation between dispatches
            # can never change a batch already being coalesced
            deadline = item.t_dequeued + (
                entry.deadline_s if entry.deadline_s is not None
                else self.batch_deadline_s)
            # dynamic micro-batching: hold the dispatch open until the
            # deadline (or the bucket cap) for co-riding requests
            while rows < cap:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    break
                try:
                    nxt = entry.queue.get(timeout=wait)
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    stop = True        # drain this batch, then exit
                    break
                nxt.t_dequeued = time.perf_counter()
                batch.append(nxt)
                rows += nxt.rows
            self._dispatch(entry, batch)
            if self.adapt_deadline:
                # between dispatches, never mid-request: the next
                # batch reads whatever the controller decided here
                self._adapt_deadline(entry)
        # drain anything still queued after the sentinel (shutdown
        # promises no request is dropped)
        leftovers: List[_Request] = []
        while True:
            try:
                item = entry.queue.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL:
                item.t_dequeued = time.perf_counter()
                leftovers.append(item)
        if leftovers:
            self._dispatch(entry, leftovers)

    def _dispatch(self, entry: _ModelEntry, batch: List[_Request]) -> None:
        """Route one coalesced micro-batch — the worker's never-raises
        boundary. Any exception a routing branch leaks (the scoring tier
        ladder has its own quarantine path) fails THIS batch's futures
        and leaves the worker alive: a poison request must never kill a
        tenant's serve thread."""
        try:
            self._dispatch_routed(entry, batch)
        except Exception as e:  # lint: broad-except — the worker thread must survive any dispatch path
            logger.exception("server: dispatch for %s failed past the "
                             "tier ladder", entry.name)
            for req in batch:
                f = req.future
                if f.done():
                    continue
                try:
                    f.set_running_or_notify_cancel()
                except Exception:  # lint: broad-except — racing a concurrent resolution
                    pass
                try:
                    f.set_exception(e)
                except Exception:  # lint: broad-except — already resolved: nothing to fail
                    pass

    def _dispatch_routed(self, entry: _ModelEntry,
                         batch: List[_Request]) -> None:
        """Route one coalesced micro-batch: the plain path scores it on
        the tenant's model; an active rollout splits it (canary) or
        duplicates it (shadow) against the candidate."""
        try:
            # model/engine captured under the entry lock: a concurrent
            # LRU eviction nulling entry.model mid-dispatch must not
            # touch THIS batch (the locals keep the objects alive)
            model, eng, bank_buckets = self._ensure_loaded(entry)
        except Exception as e:  # lint: broad-except — a model that cannot load must fail ITS requests, not the server
            logger.exception("server: loading %s failed", entry.name)
            for req in batch:
                if not req.future.set_running_or_notify_cancel():
                    continue
                req.future.set_exception(e)
            return
        rollout = entry.rollout
        if rollout is None:
            self._dispatch_group(entry, batch, model, eng, bank_buckets)
        elif rollout.mode == "canary":
            flags = [self._canaried(req, rollout.fraction)
                     for req in batch]
            stable = [r for r, c in zip(batch, flags) if not c]
            canary = [r for r, c in zip(batch, flags) if c]
            if stable:
                # the stable sub-batch runs the EXACT solo code path —
                # non-canaried traffic stays bit-identical to a
                # rollout-free server (asserted in tests)
                self._dispatch_group(entry, stable, model, eng,
                                     bank_buckets)
            if canary:
                rollout.canary_requests += len(canary)
                lifecycle.tally("canary_requests", len(canary))
                if not self._dispatch_candidate(entry, canary, rollout):
                    # candidate failed: its requests fall back to the
                    # stable tier (zero drops) and the rollout rolls
                    # back automatically
                    self._rollback_rollout(
                        entry, rollout,
                        "candidate dispatch failure / breaker open")
                    self._dispatch_group(entry, canary, model, eng,
                                         bank_buckets)
        else:                                   # shadow
            primary = self._dispatch_group(entry, batch, model, eng,
                                           bank_buckets)
            self._shadow_observe(entry, batch, rollout, primary)
        # off-path drift accumulation over ALL live records: hand the
        # batch to the shared sentinel thread (O(1) enqueue — the
        # worker, and therefore every future, never pays the sketch)
        if entry.sentinel is not None and self._drift_queue is not None:
            try:
                self._drift_queue.put_nowait(
                    (entry, [r for req in batch for r in req.records]))
            except queue.Full:
                # saturated: drop the observation, never the request
                lifecycle.tally("drift_dropped_batches")
        if entry.rollout is not None:
            self._rollout_tick(entry, entry.rollout, len(batch))

    def _dispatch_group(self, entry: _ModelEntry, batch: List[_Request],
                        model, eng, bank_buckets: List[int]):
        """Score one group of requests and scatter results back.
        Tier ladder: compiled engine (breaker-governed) → per-request
        host fallback → quarantine + per-future error. Never raises.
        Returns ``(store, bucket, seconds)`` of the engine dispatch
        (store None when the engine tier did not serve) for the shadow
        comparer."""
        from .scoring import DEFAULT_BUCKET_CAP, bucket_for
        records = [r for req in batch for r in req.records]
        n = len(records)
        cap = eng.bucket_cap if eng is not None \
            else (self.bucket_cap or DEFAULT_BUCKET_CAP)
        bucket = bucket_for(n, int(cap)) if n else 0
        # trace stitching (docs/observability.md "Distributed
        # tracing"): the batch span adopts the FIRST traced member's
        # trace id and links every member request's span id — one batch
        # span referencing the request spans it coalesced
        member_traces = [req.trace for req in batch if req.trace]
        t0 = time.perf_counter()
        for req in batch:
            req.t_dispatch0 = t0
        store = None
        engine_tier = False
        brk = model._engine_breaker()
        if n and eng is not None and brk.allow():
            try:
                resilience.inject("server.dispatch", model=entry.name,
                                  rows=n, requests=len(batch))
                # the decomposition rides in the trace too: the span's
                # own duration IS device-dispatch; queue-wait and
                # coalesce-hold (worst member) stamp as args — computed
                # only while recording, the hot path pays nothing off
                span_kw: Dict[str, Any] = {}
                if telemetry.enabled():
                    span_kw["queue_wait_s"] = round(max(
                        (req.t_dequeued - req.t_enqueued
                         for req in batch
                         if req.t_dequeued is not None),
                        default=0.0), 6)
                    span_kw["coalesce_hold_s"] = round(max(
                        (t0 - req.t_dequeued for req in batch
                         if req.t_dequeued is not None),
                        default=0.0), 6)
                with telemetry.trace_scope(
                        member_traces[0] if member_traces else None):
                    with telemetry.span(
                            "server:dispatch", model=entry.name,
                            rows=n, requests=len(batch), bucket=bucket,
                            links=[t[1] for t in member_traces],
                            **span_kw):
                        store = eng.score_store(records, use_cache=False)
                brk.record_success()
                engine_tier = True
            except Exception:  # lint: broad-except — breaker-governed device-tier fallback (per-request host retry follows)
                brk.record_failure()
                logger.exception(
                    "server: engine dispatch for %s failed; batch "
                    "retries per request on the host path", entry.name)
                store = None
        disp_s = time.perf_counter() - t0
        self._account_batch(entry, n, len(batch),
                            engine_tier and bucket in bank_buckets)
        if store is not None:
            t1 = time.perf_counter()
            for req in batch:
                req.t_dispatch1 = t1
                req.dispatch_s = disp_s
            self._scatter_store(entry, batch, store, bucket, engine_tier)
            return store, bucket, disp_s
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                continue
            # per-request host fallback: the dispatch site fires again
            # (a solo retry IS a dispatch), so chaos plans can poison
            # individual requests deterministically
            try:
                req.t_dispatch0 = time.perf_counter()
                resilience.inject("server.dispatch", model=entry.name,
                                  rows=req.rows, requests=1)
                sub = model.score(req.records, engine=False)
                req.t_dispatch1 = time.perf_counter()
                req.dispatch_s = req.t_dispatch1 - req.t_dispatch0
            except Exception as e:  # lint: broad-except — both tiers rejected: the request is poison, quarantined not fatal
                resilience.quarantine(
                    "server.dispatch", repr(e), kind="batches",
                    model=entry.name, rows=req.rows,
                    records=req.records)
                _tally("quarantined_requests")
                _tally("requests_failed")
                entry.failures += 1
                telemetry.counter("server.requests_failed").inc()
                seconds = time.perf_counter() - req.t_enqueued
                telemetry.emit("request", model=entry.name,
                               rows=req.rows, seconds=seconds, ok=False,
                               coalesced=len(batch), bucket=bucket,
                               slo_met=self._slo(seconds))
                req.future.set_exception(e)
                continue
            self._complete(entry, req, sub, bucket, len(batch), False)
        return store, bucket, disp_s

    def _account_batch(self, entry: _ModelEntry, n: int, n_requests: int,
                       bank_hit: bool) -> None:
        """One dispatched micro-batch's tallies — shared by the stable
        and canary-candidate paths so their accounting can never
        diverge."""
        entry.batches += 1
        _tally("batches")
        _tally("rows", n)
        if bank_hit:
            entry.bank_hit_batches += 1
            _tally("bank_hit_batches")
        if n_requests > 1:
            _tally("coalesced_requests", n_requests)
        telemetry.counter("server.batches").inc()

    def _scatter_store(self, entry: _ModelEntry, batch: List[_Request],
                       store, bucket: int, engine_tier: bool,
                       canary: bool = False,
                       rollout: Optional[_Rollout] = None) -> None:
        """Slice one scored store back onto its requests' futures —
        shared by the stable and canary-candidate paths so the
        row-offset bookkeeping can never diverge."""
        lo = 0
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                lo += req.rows
                continue
            sub = store.take(np.arange(lo, lo + req.rows))
            lo += req.rows
            self._complete(entry, req, sub, bucket, len(batch),
                           engine_tier, canary=canary, rollout=rollout)

    # -- shadow / canary rollout -------------------------------------------
    @staticmethod
    def _canaried(req: _Request, fraction: float) -> bool:
        """Deterministic request routing: a stable hash of the request's
        FIRST record lands in the canary fraction or not — the SAME
        request always routes the same way, across workers and
        processes. Hashing one record instead of the whole payload keeps
        the routing decision O(1) on the dispatch hot path; a request is
        routed atomically either way. Empty or unserializable payloads
        ride the stable path — routing must never fail a request."""
        if not req.records:
            return False
        try:
            blob = json.dumps(req.records[0], sort_keys=True,
                              default=str).encode()
        except (TypeError, ValueError):
            return False
        h = int.from_bytes(hashlib.blake2b(blob, digest_size=8).digest(),
                           "big")
        return (h % 10_000) < int(round(fraction * 10_000))

    def _dispatch_candidate(self, entry: _ModelEntry,
                            batch: List[_Request],
                            rollout: _Rollout) -> bool:
        """Score one canary sub-batch on the rollout candidate. On ANY
        failure (engine missing, breaker open, dispatch error) returns
        False WITHOUT touching the futures — the caller re-dispatches
        the sub-batch on the stable tier, so a broken candidate can
        never drop a request."""
        from .scoring import bucket_for
        records = [r for req in batch for r in req.records]
        n = len(records)
        model, eng = rollout.model, rollout.engine
        if not n or eng is None:
            rollout.win_failures += bool(n)
            return not n
        brk = model._engine_breaker()
        if not brk.allow():
            rollout.win_failures += 1
            return False
        bucket = bucket_for(n, int(eng.bucket_cap))
        member_traces = [req.trace for req in batch if req.trace]
        t0 = time.perf_counter()
        for req in batch:
            req.t_dispatch0 = t0
        try:
            resilience.inject("server.dispatch", model=entry.name,
                              rows=n, requests=len(batch), canary=True)
            with telemetry.trace_scope(
                    member_traces[0] if member_traces else None):
                with telemetry.span(
                        "server:canary_dispatch", model=entry.name,
                        rows=n, version=rollout.version, bucket=bucket,
                        links=[t[1] for t in member_traces]):
                    store = eng.score_store(records, use_cache=False)
            brk.record_success()
        except Exception:  # lint: broad-except — a failing candidate is rollout evidence; its requests re-dispatch on the stable tier
            brk.record_failure()
            rollout.win_failures += 1
            logger.exception(
                "server: canary dispatch for %s@%s failed; sub-batch "
                "re-dispatches on the stable tier", entry.name,
                rollout.version)
            return False
        disp_s = time.perf_counter() - t0
        for req in batch:
            req.t_dispatch1 = t0 + disp_s
            req.dispatch_s = disp_s
        self._account_batch(entry, n, len(batch),
                            bucket in rollout.bank_buckets)
        self._scatter_store(entry, batch, store, bucket, True,
                            canary=True, rollout=rollout)
        rollout.win_evidence += len(batch)
        return True

    def _shadow_observe(self, entry: _ModelEntry, batch: List[_Request],
                        rollout: _Rollout, primary) -> None:
        """Duplicate one already-answered batch to the shadow candidate:
        responses are DISCARDED, prediction parity and the latency delta
        are recorded. Runs after the primary futures resolve, so the
        answered batch never waits on its shadow — but the double
        compute IS shadow's cost: it occupies the tenant's worker before
        the next pickup, so a tenant near saturation loses throughput
        for the rollout's duration (docs/lifecycle.md deploy-mode
        matrix)."""
        store, _bucket, primary_s = primary
        if store is None:
            return                  # host-tier batch: nothing to mirror
        records = [r for req in batch for r in req.records]
        rollout.shadow_requests += len(batch)
        rollout.shadow_batches += 1
        rollout.primary_seconds += primary_s
        lifecycle.tally("shadow_requests", len(batch))
        t0 = time.perf_counter()
        try:
            if rollout.engine is not None:
                cand = rollout.engine.score_store(records, use_cache=False)
            else:
                cand = rollout.model.score(records, engine=False)
        except Exception:  # lint: broad-except — shadow failure is rollout evidence, never a served error
            rollout.win_failures += 1
            logger.exception("server: shadow dispatch for %s@%s failed",
                             entry.name, rollout.version)
            return
        rollout.shadow_seconds += time.perf_counter() - t0
        lo = 0
        for req in batch:
            idx = np.arange(lo, lo + req.rows)
            lo += req.rows
            if _stores_equal(store.take(idx), cand.take(idx)):
                rollout.parity_ok += 1
                lifecycle.tally("shadow_parity_ok")
            else:
                rollout.parity_mismatch += 1
                rollout.win_parity_mismatch += 1
                lifecycle.tally("shadow_parity_mismatch")
            rollout.win_evidence += 1

    def _rollout_tick(self, entry: _ModelEntry, rollout: _Rollout,
                      n_requests: int) -> None:
        """Advance the rollout's evaluation window after one dispatch;
        rolls back on hard failure signals, promotes after
        ``promote_windows`` consecutive clean windows."""
        rollout.win_requests += n_requests
        if rollout.win_failures:
            self._rollback_rollout(entry, rollout,
                                   "candidate failure / breaker trip")
            return
        if rollout.win_slo_missed:
            self._rollback_rollout(entry, rollout,
                                   "SLO breach on candidate traffic")
            return
        if rollout.win_requests < rollout.window_requests:
            return
        sentinel = entry.sentinel
        drift_now = sentinel.advisories if sentinel is not None else 0
        new_drift = drift_now - rollout.drift_seen
        rollout.drift_seen = drift_now  # lint: thread-escape — rollout counters are confined to the entry's single dispatch worker; deploy() initializes a NOT-yet-published rollout under entry.lock
        clean = ((new_drift == 0 or not rollout.drift_gate)
                 and rollout.win_parity_mismatch == 0)
        rollout.windows += 1
        if not clean:
            rollout.clean_windows = 0
        elif rollout.win_evidence > 0:
            rollout.clean_windows += 1
        # else: a window that never touched the candidate (host-tier
        # primaries under shadow, or zero canaried requests) proves
        # NOTHING — it neither advances nor resets the promotion count
        rollout.win_requests = 0
        rollout.win_parity_mismatch = 0
        evidence = rollout.win_evidence
        rollout.win_evidence = 0
        logger.info("server: rollout window %d for %s@%s %s "
                    "(%d/%d clean, %d candidate-touching)",
                    rollout.windows, entry.name,
                    rollout.version, "clean" if clean else "NOT clean",
                    rollout.clean_windows, rollout.promote_windows,
                    evidence)
        if rollout.clean_windows >= rollout.promote_windows:
            self._promote_rollout(entry, rollout)

    def _promote_rollout(self, entry: _ModelEntry,
                         rollout: _Rollout) -> None:
        """Swap the candidate in as the tenant's serving model and move
        the registry's ``current`` pointer (through the
        ``lifecycle.promote`` fault site). The swap happens on the
        tenant's single worker thread between dispatches, so no request
        is in flight across it — zero drops by construction. A failed
        pointer swap rolls the rollout back; the stable model keeps
        serving and the registry still names it. Pointer move + model
        swap happen under ONE hold of the entry lock, re-checking the
        rollout's identity first: a manual ``rollback()`` that raced in
        wins — its abort can never be silently overridden by a promote
        that was already past the clean-window check."""
        promote_err: Optional[BaseException] = None
        with entry.lock:
            if entry.rollout is not rollout:
                return                      # aborted while we decided
            try:
                if self._registry is not None and rollout.version:
                    self._registry.promote(entry.name, rollout.version)
            except Exception as e:  # lint: broad-except — a failed pointer swap must leave the stable fleet serving (chaos-tested)
                logger.exception("server: promote of %s@%s failed; "
                                 "rolling back", entry.name,
                                 rollout.version)
                promote_err = e
            else:
                entry.model = rollout.model
                entry.engine = rollout.engine
                entry.bank_buckets = list(rollout.bank_buckets)
                entry.bank_report = rollout.bank_report
                if rollout.model_dir:
                    entry.model_dir = rollout.model_dir
                    entry.bank_dir = rollout.bank_dir
                entry.weight_bytes = self._entry_weight(entry)
                entry.sentinel = self._build_sentinel(entry.model,
                                                      entry.name)
                entry.rollout = None
        if promote_err is not None:
            self._rollback_rollout(entry, rollout,
                                   f"promote failed: {promote_err!r}")
            return
        lifecycle.tally("auto_promotions")
        telemetry.emit("rollout", model=entry.name, action="promote",
                       version=rollout.version, mode=rollout.mode,
                       windows=rollout.windows)
        logger.info("server: %s promoted to %s after %d clean window(s)",
                    entry.name, rollout.version, rollout.clean_windows)

    def _rollback_rollout(self, entry: _ModelEntry, rollout: _Rollout,
                          reason: str) -> None:
        """Abort the rollout: the candidate is discarded, the stable
        model keeps serving, the registry pointer is untouched."""
        with entry.lock:
            if entry.rollout is rollout:
                entry.rollout = None
        lifecycle.tally("auto_rollbacks")
        telemetry.counter("server.rollbacks").inc()
        telemetry.emit("rollout", model=entry.name, action="rollback",
                       version=rollout.version, mode=rollout.mode,
                       reason=reason)
        logger.warning("server: rollout of %s@%s rolled back: %s",
                       entry.name, rollout.version, reason)

    def deploy(self, name: str, version: str, mode: str = "shadow",
               fraction: Optional[float] = None,
               window_requests: int = DEFAULT_ROLLOUT_WINDOW_REQUESTS,
               promote_windows: int = DEFAULT_PROMOTE_WINDOWS,
               drift_gate: bool = True) -> Dict[str, Any]:
        """Start a shadow or canary rollout of registry ``version`` on
        tenant ``name``.

        ``mode="shadow"`` duplicates every request to the candidate
        (responses discarded; parity + latency delta recorded);
        ``mode="canary"`` routes a deterministic hash-``fraction`` of
        requests to it. After ``promote_windows`` consecutive clean
        evaluation windows of ``window_requests`` requests each — no
        candidate failure, no SLO miss on candidate traffic, no new
        drift advisory, no shadow parity mismatch — the candidate is
        promoted automatically (registry pointer + in-place model swap);
        a breaker trip / dispatch failure / SLO breach rolls back
        automatically. ``drift_gate=False`` removes the new-drift term
        from the clean-window evidence — the continual tier sets it for
        drift-TRIGGERED retrains, whose candidate was trained on the
        very window the stable baseline keeps flagging (the advisory is
        the rollout's cause, not evidence against it; the sentinel
        rebuilds on the candidate's own baseline at promote). Returns
        the rollout status block."""
        if mode not in ("shadow", "canary"):
            raise RolloutError(
                f"deploy mode must be 'shadow' or 'canary', got {mode!r}")
        if self._registry is None:
            raise RolloutError("deploy() needs a registry attached to "
                               "the server (ModelServer(registry=...))")
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise ModelNotFound(f"no model {name!r} registered "
                                f"(have: {self.models()})")
        rec = self._registry.record(name, version)
        frac = float(self.canary_fraction if fraction is None
                     else fraction)
        if mode == "canary" and not 0.0 < frac <= 1.0:
            raise RolloutError(
                f"canary fraction must be in (0, 1], got {frac!r}")
        # candidate loads OUTSIDE the entry lock (slow: model + engine +
        # bank); serving continues on the stable model meanwhile
        from .workflow import WorkflowModel
        with telemetry.span("server:load_candidate", model=name,
                            version=str(version)):
            cand = WorkflowModel.load(rec["modelDir"])
            engine, bank_buckets, bank_report = self._build_engine(
                cand, rec.get("bankDir"))
        if mode == "canary" and engine is None:
            # canary routes LIVE traffic to the candidate and has no
            # host-tier fallback of its own — an engine-less candidate
            # would fail its first routed request and insta-rollback
            # with misleading evidence; shadow supports it instead
            raise RolloutError(
                f"version {version!r} of {name!r} has no compiled "
                "scoring engine; canary needs one — use mode='shadow' "
                "to evaluate a host-tier candidate")
        rollout = _Rollout(mode=mode, version=str(version), fraction=frac,
                           model=cand, engine=engine,
                           bank_buckets=bank_buckets,
                           bank_report=bank_report,
                           model_dir=rec["modelDir"],
                           bank_dir=rec.get("bankDir"),
                           window_requests=window_requests,
                           promote_windows=promote_windows,
                           drift_gate=drift_gate)
        with entry.lock:
            if entry.rollout is not None:
                raise RolloutError(
                    f"model {name!r} already has an active "
                    f"{entry.rollout.mode} rollout of version "
                    f"{entry.rollout.version}")
            sentinel = entry.sentinel
            if sentinel is not None:
                rollout.drift_seen = sentinel.advisories
            entry.rollout = rollout
        lifecycle.tally("deploys")
        telemetry.emit("rollout", model=name, action="deploy",
                       version=str(version), mode=mode, fraction=frac)
        logger.info("server: %s rollout of %s@%s started (fraction=%g)",
                    mode, name, version, frac)
        return rollout.status()

    def rollback(self, name: str) -> Dict[str, Any]:
        """Manual rollback. With a rollout in flight: abort it (the
        stable model was serving all along). Otherwise: swing the
        registry pointer back to ``previous`` and force the tenant to
        reload through it."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise ModelNotFound(f"no model {name!r} registered "
                                f"(have: {self.models()})")
        with entry.lock:
            rollout, entry.rollout = entry.rollout, None
        if rollout is not None:
            # the counter covers automated AND manual aborts
            # (docs/observability.md) — dashboards must see both
            telemetry.counter("server.rollbacks").inc()
            telemetry.emit("rollout", model=name, action="rollback",
                           version=rollout.version, mode=rollout.mode,
                           reason="manual")
            logger.info("server: rollout of %s@%s aborted manually",
                        name, rollout.version)
            return {"model": name, "aborted": rollout.version,
                    "mode": rollout.mode}
        if self._registry is None:
            raise RolloutError("rollback() without a rollout needs a "
                               "registry attached to the server")
        prev = self._registry.rollback(name)
        rec = self._registry.record(name, prev)
        with entry.lock:
            entry.model = None
            entry.engine = None
            entry.bank_buckets = []
            entry.sentinel = None
            entry.model_dir = rec["modelDir"]
            entry.bank_dir = rec.get("bankDir")
        return {"model": name, "rolledBackTo": prev}

    def _drift_loop(self) -> None:
        """The shared sentinel thread: fold enqueued record batches into
        their tenant's sliding sketches. One thread for the whole server,
        coalescing backlog into sub-window-sized passes and throttled to
        ``DRIFT_DUTY_CYCLE`` of host CPU — observation can never crowd
        out the serving workers' GIL time.

        The WHOLE per-item body runs inside one catch-and-tally guard:
        a malformed live record (or a poison queue item) used to be able
        to raise outside the old observe()-only try — in the unpack or
        the backlog-coalescing concat — killing the thread silently
        while the queue kept filling and ``drain_drift`` hung forever.
        Now any failure tallies ``lifecycle.sentinel_errors`` (surfaced
        in ``lifecycle_stats()``), its queue items are still accounted
        (``task_done`` in the finally), and the thread lives."""
        held = None
        while True:
            item = held if held is not None else self._drift_queue.get()
            held = None
            if item is None:                # shutdown sentinel
                self._drift_queue.task_done()
                return
            taken = 1
            stop = False
            t0 = time.perf_counter()
            try:
                entry, records = item
                while len(records) < DRIFT_COALESCE_ROWS:
                    try:
                        nxt = self._drift_queue.get_nowait()
                    except queue.Empty:
                        break
                    # count the take FIRST: a poison item that raises
                    # below is still accounted in the finally — an
                    # uncounted take would wedge drain_drift forever
                    taken += 1
                    if nxt is None:         # shutdown sentinel mid-burst
                        stop = True
                        break
                    if nxt[0] is not entry:
                        held = nxt          # different tenant: next round
                        taken -= 1          # its task_done rides with it
                        break
                    records = records + nxt[1]
                sentinel = entry.sentinel
                if sentinel is not None:
                    with telemetry.span("server:drift_observe",
                                        model=entry.name,
                                        rows=len(records)):
                        sentinel.observe(records)
            except Exception:  # lint: broad-except — drift observation must never take down its thread (satellite: catch-and-tally, keep serving)
                lifecycle.tally("sentinel_errors")
                logger.exception("server: drift observation failed "
                                 "(tallied sentinel_errors; the "
                                 "sentinel thread lives)")
            finally:
                for _ in range(taken):
                    self._drift_queue.task_done()
            if stop:
                return
            dt = time.perf_counter() - t0
            if dt > 0 and held is None:
                time.sleep(min(dt * (1.0 / DRIFT_DUTY_CYCLE - 1.0), 2.0))

    def drain_drift(self) -> None:
        """Block until every enqueued drift observation is folded —
        makes sentinel stats deterministic for tests and benches."""
        if self._drift_queue is not None:
            self._drift_queue.join()

    def lifecycle_status(self, name: str) -> Dict[str, Any]:
        """Registry versions + pointer + live rollout/drift state for one
        tenant — the ``/v1/models/<name>/versions`` document."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise ModelNotFound(f"no model {name!r} registered "
                                f"(have: {self.models()})")
        # locals first: a racing promote/eviction nulls these fields
        # between a truthiness test and the method call
        rollout = entry.rollout
        sentinel = entry.sentinel
        doc: Dict[str, Any] = {
            "model": name,
            "rollout": rollout.status() if rollout else None,
            "drift": sentinel.stats() if sentinel else None}
        if self._registry is not None:
            try:
                doc.update(self._registry.status(name))
            except RegistryError as e:
                doc["registryError"] = str(e)
        return doc

    def _slo(self, seconds: float) -> Optional[bool]:
        if self.slo_ms is None:
            return None
        met = seconds * 1e3 <= self.slo_ms
        _tally("slo_met" if met else "slo_missed")
        return met

    # -- online deadline adaptation (PR 18, docs/tuning.md) ----------------
    def _adapt_deadline(self, entry: _ModelEntry) -> None:
        """Bounded AIMD controller over one tenant's micro-batching
        hold, driven by the measured queue-wait/coalesce-hold split
        (:meth:`_observe_decomp`'s reservoirs). Runs on the tenant's
        own worker thread BETWEEN dispatches; state machine:

        * **hold** until a full hysteresis window of
          ``ADAPT_WINDOW_REQUESTS`` new completed requests has
          accumulated, and whenever the two phase medians sit inside
          the ``ADAPT_DEADBAND`` ratio of each other;
        * **multiplicative decrease** (``* ADAPT_MD_FACTOR``) when
          queue-wait dominates — holding the batch open is starving
          the queue, drain it faster;
        * **additive increase** (``+ ADAPT_STEP_S``) when
          coalesce-hold dominates — the queue keeps up, harvest more
          coalescing per dispatch.

        Every move clamps to the registry-declared
        ``serveBatchDeadlineMs`` tuning bounds (config.knob_bounds) —
        the controller can NEVER leave the declared space. When the
        converged deadline contradicts the configured one by more than
        ``ADAPT_ADVISORY_RATIO`` the tenant raises a one-shot TMG406
        advisory: the tuned params file disagrees with live telemetry,
        re-run the offline tuner."""
        if entry.requests - entry.adapt_seen < ADAPT_WINDOW_REQUESTS:
            return
        entry.adapt_seen = entry.requests
        _tally("deadline_adapt_windows")
        window = ADAPT_WINDOW_REQUESTS
        qw = list(entry.decomp["queueWait"])[-window:]
        ch = list(entry.decomp["coalesceHold"])[-window:]
        if not qw or not ch:
            _tally("deadline_holds")
            return
        qw_med = float(np.median(np.asarray(qw, dtype=np.float64)))
        ch_med = float(np.median(np.asarray(ch, dtype=np.float64)))
        cur = (entry.deadline_s if entry.deadline_s is not None
               else self.batch_deadline_s)
        eps = 1e-9
        if qw_med > ch_med * ADAPT_DEADBAND and qw_med > eps:
            nxt = cur * ADAPT_MD_FACTOR
            direction = "decrease"
        elif ch_med > qw_med * ADAPT_DEADBAND:
            nxt = cur + ADAPT_STEP_S
            direction = "increase"
        else:
            _tally("deadline_holds")
            return
        lo, hi = self._adapt_bounds_s
        clamped = min(max(nxt, lo), hi)
        if clamped != nxt:
            entry.adapt_clamped += 1
            _tally("deadline_clamped")
        if clamped == cur:
            _tally("deadline_holds")
            return
        entry.deadline_s = clamped
        if direction == "increase":
            entry.adapt_increases += 1
            _tally("deadline_increases")
        else:
            entry.adapt_decreases += 1
            _tally("deadline_decreases")
        telemetry.emit("deadline_adapt", model=entry.name,
                       direction=direction,
                       deadline_ms=clamped * 1e3,
                       queue_wait_med_s=qw_med,
                       coalesce_hold_med_s=ch_med)
        base = self.batch_deadline_s
        if not entry.deadline_advised and base > 0 and (
                clamped >= base * ADAPT_ADVISORY_RATIO
                or clamped <= base / ADAPT_ADVISORY_RATIO):
            entry.deadline_advised = True
            _tally("deadline_advisories")
            from . import lint
            finding = lint.Finding(
                "TMG406",
                f"model {entry.name!r}: the online controller "
                f"converged batch_deadline_s to {clamped * 1e3:.3f} ms "
                f"but the params file configured "
                f"{base * 1e3:.3f} ms — live telemetry contradicts the "
                f"tuned config; re-run `python -m transmogrifai_tpu "
                f"tune` against a fresh recording")
            lint.emit_findings([finding])
            logger.warning("serve: %s", finding.format())

    def _observe_decomp(self, entry: _ModelEntry, req: _Request,
                        now: float) -> Dict[str, float]:
        """Fold one completed request's latency decomposition into the
        per-model reservoirs (always on — ``/stats``) and the per-model
        telemetry histograms (``/metrics``): queue-wait → coalesce-hold
        → device-dispatch → scatter. Requests that skipped a phase
        (host fallback, drain path) record what they measured and skip
        the rest — a partial decomposition must never invent time.
        Returns the phases it measured so the completed
        :class:`RequestResult` can carry its own decomposition."""
        phases: Dict[str, float] = {}
        if req.t_dequeued is not None:
            phases["queueWait"] = max(req.t_dequeued - req.t_enqueued,
                                      0.0)
            if req.t_dispatch0 is not None:
                phases["coalesceHold"] = max(
                    req.t_dispatch0 - req.t_dequeued, 0.0)
        if req.dispatch_s is not None:
            phases["deviceDispatch"] = req.dispatch_s
        if req.t_dispatch1 is not None:
            phases["scatter"] = max(now - req.t_dispatch1, 0.0)
        on = telemetry.enabled()
        for ph, v in phases.items():
            entry.decomp[ph].append(v)
            if on:
                telemetry.histogram(  # lint: metric-name — per-tenant decomposition, bounded by the registered roster
                    entry.metric_names[ph]).observe(v)
        return phases

    def _complete(self, entry: _ModelEntry, req: _Request, store,
                  bucket: int, coalesced: int, engine_tier: bool,
                  canary: bool = False,
                  rollout: Optional[_Rollout] = None) -> None:
        now = time.perf_counter()
        seconds = now - req.t_enqueued
        entry.requests += 1
        entry.rows += req.rows
        entry.latencies.append(seconds)
        decomp = self._observe_decomp(entry, req, now)
        _tally("requests")
        telemetry.counter("server.requests").inc()
        telemetry.counter("server.rows_scored").inc(req.rows)
        if telemetry.enabled():
            telemetry.histogram(  # lint: metric-name — per-tenant latency, bounded by the registered roster
                entry.metric_names["request"]).observe(seconds)
            telemetry.gauge(  # lint: metric-name — per-tenant gauge, bounded by the registered roster
                entry.metric_names["queue"]).set(entry.queue.qsize())
        slo_met = self._slo(seconds)
        if rollout is not None and slo_met is False:
            # candidate traffic missing the SLO is a rollback trigger
            rollout.win_slo_missed += 1
        telemetry.emit("request", model=entry.name, rows=req.rows,
                       seconds=seconds, ok=True, coalesced=coalesced,
                       bucket=bucket, slo_met=slo_met)
        req.future.set_result(RequestResult(
            store=store, rows=req.rows, bucket=bucket,
            coalesced=coalesced, seconds=seconds,
            engine_tier=engine_tier, canary=canary, decomp=decomp))

    # -- stats / shutdown --------------------------------------------------
    @property
    def closing(self) -> bool:
        """True once :meth:`shutdown` has begun — liveness (``/healthz``)
        reports 503 from that instant so a supervisor/router never
        routes a request to a draining worker."""
        return self._closed

    def readiness(self) -> Dict[str, Any]:
        """Readiness, distinct from liveness: can this server usefully
        take traffic RIGHT NOW? Ready iff it is not closing, has at
        least one tenant, every tenant is loadable (loaded, or saved on
        disk for a milliseconds bank reload), and the summed queue
        depth leaves at least ``READY_MIN_HEADROOM`` of total capacity
        free. The ``/readyz`` document; reasons name what failed."""
        with self._lock:
            closed = self._closed
            entries = list(self._entries.items())
        depth = sum(e.queue.qsize() for _, e in entries)
        capacity = self.max_queue * len(entries)
        headroom = (1.0 - depth / capacity) if capacity else 0.0
        loaded = [n for n, e in entries if e.model is not None]
        unloadable = [n for n, e in entries
                      if e.model is None and not e.model_dir]
        reasons: List[str] = []
        if closed:
            reasons.append("closing")
        if not entries:
            reasons.append("no models registered")
        if unloadable:
            reasons.append(f"tenants not loadable: {unloadable}")
        if entries and headroom < READY_MIN_HEADROOM:
            reasons.append(
                f"queue headroom {headroom:.3f} < {READY_MIN_HEADROOM}")
        return {"ready": not reasons, "reasons": reasons,
                "models": len(entries), "loadedModels": loaded,
                "queueDepth": depth,
                "queueHeadroom": round(headroom, 4)}

    def stats(self) -> Dict[str, Any]:
        """This server's view: global tallies + per-model stats (incl.
        exact p50/p95/p99 over the latency window)."""
        with self._lock:
            entries = list(self._entries.items())
        return {"server": server_stats(),
                "lifecycle": lifecycle.lifecycle_stats(),
                "sloMs": self.slo_ms,
                "driftWindow": self.drift_window,
                "batchDeadlineMs": self.batch_deadline_s * 1e3,
                "adaptDeadline": self.adapt_deadline,
                "adaptBoundsMs": [
                    round(self._adapt_bounds_s[0] * 1e3, 4),
                    (None if self._adapt_bounds_s[1] == float("inf")
                     else round(self._adapt_bounds_s[1] * 1e3, 4))],
                "models": {name: e.stats() for name, e in entries}}

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = 30.0) -> None:
        """Stop accepting requests and stop the workers. With ``drain``
        (the default) every queued request is scored before its worker
        exits — graceful shutdown never drops accepted work. Without
        it, pending futures fail with :class:`ServerClosed`."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._entries.values())
        for e in entries:
            if not drain:
                # fail queued requests loudly instead of scoring them
                while True:
                    try:
                        item = e.queue.get_nowait()
                    except queue.Empty:
                        break
                    if item is not _SENTINEL and \
                            item.future.set_running_or_notify_cancel():
                        item.future.set_exception(
                            ServerClosed("server shut down (no drain)"))
            e.queue.put(_SENTINEL)
        for e in entries:
            if e.worker is not None:
                e.worker.join(timeout=timeout_s)
        if self._drift_thread is not None:
            if drain:
                self._drift_queue.join()
            # no-drain must stay a fast abort: never block on a full
            # queue — evict pending observations until the sentinel fits
            while True:
                try:
                    self._drift_queue.put_nowait(None)
                    break
                except queue.Full:
                    try:
                        self._drift_queue.get_nowait()
                        self._drift_queue.task_done()
                    except queue.Empty:
                        pass
            self._drift_thread.join(timeout=timeout_s)


def _stores_equal(a, b) -> bool:
    """Bitwise prediction parity between two result stores over their
    shared columns (the shadow comparer's oracle): Prediction columns
    compare all three arrays, value columns compare their payloads."""
    names = [n for n in a.names() if n in b]
    if not names:
        return False
    for n in names:
        ca, cb = a[n], b[n]
        if type(ca) is not type(cb):
            return False
        if hasattr(ca, "prediction"):
            for fld in ("prediction", "raw_prediction", "probability"):
                if not np.array_equal(getattr(ca, fld),
                                      getattr(cb, fld)):
                    return False
        elif hasattr(ca, "mask") and hasattr(ca, "values"):
            if not np.array_equal(ca.mask, cb.mask):
                return False
            va, vb = np.asarray(ca.values), np.asarray(cb.values)
            equal = (np.array_equal(va, vb, equal_nan=True)
                     if va.dtype.kind == "f" and vb.dtype.kind == "f"
                     else np.array_equal(va, vb))
            if not equal:
                return False
        elif hasattr(ca, "values"):
            if list(ca.values) != list(cb.values):
                return False
    return True


# ---------------------------------------------------------------------------
# stdlib HTTP front end
# ---------------------------------------------------------------------------


def _store_rows(store) -> List[Dict[str, Any]]:
    return [{nm: store[nm].get_raw(i) for nm in store.names()}
            for i in range(store.n_rows)]


#: sentinel: the HTTP handler's future timed out (the 504 path) — a
#: marker object so the trace scope can close before the 504 logic runs
_TIMED_OUT = object()


def serve_http(server: ModelServer, host: str = "127.0.0.1",
               port: int = 8000, request_timeout_s: float = 30.0):
    """Start the stdlib HTTP front end on a daemon thread; returns the
    ``ThreadingHTTPServer`` (``.server_address`` carries the bound port;
    ``.shutdown()`` stops it). No dependencies beyond the stdlib."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # route through logging
            logger.debug("http: " + fmt, *args)

        def _send(self, code: int, doc: Optional[Dict[str, Any]],
                  headers: Optional[Dict[str, str]] = None,
                  raw: Optional[bytes] = None) -> None:
            body = (raw if raw is not None
                    else json.dumps(doc, default=str).encode())
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, body: bytes,
                       content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                # the live Prometheus scrape surface (/stats never
                # was): the telemetry registry in text exposition plus
                # the always-on server tallies as server_tally_*
                # gauges, so a scrape is useful even with telemetry
                # off. The tally prefix is DISTINCT from the
                # telemetry counters' server_* namespace on purpose:
                # `server.requests` sanitizes to `server_requests`,
                # and a family emitted twice with conflicting types
                # is invalid exposition a real Prometheus rejects
                # (docs/observability.md "The /metrics plane")
                extra = {f"server_tally_{k}": float(v)
                         for k, v in server_stats().items()
                         if isinstance(v, int)
                         and not isinstance(v, bool)}
                body = telemetry.render_prometheus(extra=extra).encode()
                return self._send_text(
                    200, body, "text/plain; version=0.0.4")
            if self.path == "/healthz":
                # liveness flips 503 the INSTANT shutdown begins — a
                # supervisor/router must stop routing to a draining
                # worker before its queues close (docs/fleet.md)
                if server.closing:
                    return self._send(503, {"status": "draining",
                                            "models": server.models()})
                return self._send(200, {"status": "ok",
                                        "models": server.models()})
            if self.path == "/readyz":
                doc = server.readiness()
                return self._send(200 if doc["ready"] else 503, doc)
            if self.path == "/stats":
                return self._send(200, server.stats())
            if self.path == "/v1/models":
                return self._send(200, {"models": server.stats()["models"]})
            if (self.path.startswith("/v1/models/")
                    and self.path.endswith("/versions")):
                name = self.path[len("/v1/models/"):-len("/versions")]
                try:
                    return self._send(200, server.lifecycle_status(name))
                except ModelNotFound as e:
                    return self._send(404, {"error": str(e)})
                except (RolloutError, RegistryError) as e:
                    return self._send(400, {"error": str(e)})
            return self._send(404, {"error": f"no route {self.path!r}"})

        def _body(self):
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")

        def do_POST(self):
            path = self.path
            if not path.startswith("/v1/models/"):
                return self._send(404, {"error": f"no route {path!r}"})
            # workload flight recorder (workload.py): every accepted
            # :score request leaves one JSONL record — arrival offset,
            # payload, trace id, outcome, phase decomposition — via a
            # bounded queue + writer thread, a no-op when no recorder
            # is installed. Failure outcomes record too (a replay must
            # see the 4xx/5xx mix, not just the successes).
            wl_t0 = time.perf_counter()
            wl_rows = [0]
            wl_trace: List[Optional[str]] = [None]

            def _wl_fail(code: int, exc: BaseException) -> None:
                if (path.endswith(":score")
                        and workload.recording_enabled()):
                    workload.record_request(
                        model=path[len("/v1/models/"):-len(":score")],
                        rows=wl_rows[0], trace_id=wl_trace[0],
                        t_arrival=wl_t0,
                        outcome={"status": code, "ok": False,
                                 "error": type(exc).__name__},
                        phases={"e2e": time.perf_counter() - wl_t0})
            try:
                if path.endswith(":deploy"):
                    name = path[len("/v1/models/"):-len(":deploy")]
                    doc = self._body()
                    kw = {}
                    if doc.get("fraction") is not None:
                        kw["fraction"] = float(doc["fraction"])
                    if doc.get("windowRequests") is not None:
                        kw["window_requests"] = int(doc["windowRequests"])
                    if doc.get("promoteWindows") is not None:
                        kw["promote_windows"] = int(doc["promoteWindows"])
                    return self._send(200, {
                        "model": name,
                        "rollout": server.deploy(
                            name, doc.get("version"),
                            mode=doc.get("mode", "shadow"), **kw)})
                if path.endswith(":rollback"):
                    name = path[len("/v1/models/"):-len(":rollback")]
                    return self._send(200, server.rollback(name))
                if not path.endswith(":score"):
                    return self._send(404, {"error": f"no route {path!r}"})
                name = path[len("/v1/models/"):-len(":score")]
                # the raw body is kept past the parse: the flight
                # recorder captures it as pre-serialized bytes (zero
                # re-serialization on the writer thread)
                length = int(self.headers.get("Content-Length", 0))
                raw_body = self.rfile.read(length) or b"{}"
                doc = json.loads(raw_body)
                records = doc.get("records")
                if not isinstance(records, list) or not records:
                    return self._send(400, {
                        "error": "body must be {\"records\": [..]} with "
                                 "at least one record"})
                # trace adoption (docs/observability.md "Distributed
                # tracing"): a router-minted X-Tmog-Trace header joins
                # this worker's spans to the fleet-wide trace; with
                # telemetry on and no header, the worker is the entry
                # point and mints its own. The request span's identity
                # rides into the micro-batcher via submit(trace=) so
                # the batch span can link it, and echoes back to the
                # client in the response header.
                wl_rows[0] = len(records)
                ctx = telemetry.parse_traceparent(
                    self.headers.get(telemetry.TRACE_HEADER))
                if ctx is None and telemetry.enabled():
                    ctx = telemetry.mint_trace()
                trace_hdr = (telemetry.format_traceparent(*ctx)
                             if ctx else None)
                wl_trace[0] = ctx[0] if ctx else None
                with telemetry.trace_scope(ctx):
                    with telemetry.span("server:request", model=name,
                                        rows=len(records)) as rsp:
                        fut = server.submit(
                            name, records,
                            trace=((rsp.trace_id, rsp.span_id)
                                   if rsp.span_id else ctx))
                        try:
                            res = fut.result(
                                timeout=request_timeout_s)
                        except FuturesTimeout:
                            res = _TIMED_OUT
                if res is _TIMED_OUT:
                    # answer 504, and account for the in-flight future
                    # either way: a successful cancel means the worker
                    # will skip it (set_running_or_notify_cancel), an
                    # unsuccessful one means the dispatch already owns
                    # it — tally its eventual completion and retrieve
                    # its exception so the drop is never silent
                    _tally("requests_timed_out")
                    telemetry.counter("server.requests_timed_out").inc()
                    if not fut.cancel():
                        def _late(f: "Future[RequestResult]") -> None:
                            _tally("timed_out_completions")
                            if not f.cancelled():
                                f.exception()
                        fut.add_done_callback(_late)
                    if workload.recording_enabled():
                        workload.record_request(
                            model=name, rows=len(records),
                            payload_json=raw_body,
                            trace_id=wl_trace[0],
                            t_arrival=wl_t0,
                            outcome={"status": 504, "ok": False,
                                     "error": "timeout"},
                            phases={"e2e": time.perf_counter()
                                    - wl_t0})
                    return self._send(504, {
                        "error": f"timed out after "
                                 f"{request_timeout_s:g}s",
                        "model": name, "rows": len(records)})
            except ModelNotFound as e:
                _wl_fail(404, e)
                return self._send(404, {"error": str(e)})
            except (RolloutError, RegistryError, TypeError,
                    ValueError) as e:
                _wl_fail(400, e)
                if isinstance(e, json.JSONDecodeError):
                    return self._send(400,
                                      {"error": f"bad JSON body: {e}"})
                return self._send(400, {"error": str(e)})
            except ServerBusy as e:
                _wl_fail(429, e)
                return self._send(429, {"error": str(e)})
            except ServerClosed as e:
                _wl_fail(503, e)
                return self._send(503, {"error": str(e)})
            except Exception as e:  # lint: broad-except — HTTP boundary: a poison request answers 500, the server lives
                _wl_fail(500, e)
                return self._send(500, {"error": repr(e)})
            # the response body carries this request's phase
            # decomposition — the replay harness reads it to emit the
            # paired per-phase summary, and it rides the router's raw
            # payload passthrough unchanged (docs/observability.md
            # "Workload capture & replay")
            phases = {k: round(v, 6)
                      for k, v in (res.decomp or {}).items()}
            phases["e2e"] = round(res.seconds, 6)
            outputs = _store_rows(res.store)
            resp_body = json.dumps({
                "model": name, "rows": res.rows, "bucket": res.bucket,
                "coalesced": res.coalesced,
                "latencyMs": round(res.seconds * 1e3, 3),
                "engineTier": res.engine_tier,
                "canary": res.canary,
                "phases": phases,
                "outputs": outputs}, default=str).encode()
            if workload.recording_enabled():
                # zero-copy capture: both bodies were serialized on
                # this request anyway (by the client and by the line
                # above) — the recorder splices the bytes, so the
                # marginal cost is one bounded-queue put
                workload.record_request(
                    model=name, rows=res.rows,
                    payload_json=raw_body, response_json=resp_body,
                    trace_id=wl_trace[0], t_arrival=wl_t0,
                    outcome={"status": 200, "ok": True},
                    phases=phases)
            return self._send(200, None, raw=resp_body,
                              headers=({telemetry.TRACE_HEADER:
                                        trace_hdr}
                                       if trace_hdr else None))

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever,
                         name="serve-http", daemon=True)
    t.start()
    logger.info("model server HTTP front end on %s:%d",
                *httpd.server_address)
    return httpd
