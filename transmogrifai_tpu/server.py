"""Multi-tenant model server — scoring at traffic scale, not batch scale.

The Clipper analog (PAPERS.md): a serving tier that holds N loaded
models behind a capacity-bounded LRU, gives each model its own request
queue with **dynamic micro-batching** — concurrent requests coalesce up
to a deadline into one engine dispatch padded to the nearest
power-of-two ladder bucket, results scattered back per request — and
reports per-model latency/throughput/queue-depth SLO instruments. The
AOT program bank (aot.py) supplies the cold-start story: a freshly
loaded model answers its first request without a single XLA compile.

Correctness contract
--------------------

* **Co-batching is bit-identical.** Every fused stage is row-independent
  (the scoring-engine contract), so a request's rows compute the same
  values whether padded with zeros or with another tenant's rows. The
  chaos test pins the solo oracle to the coalesced dispatch's bucket
  (``ScoringEngine.score_store(bucket_min=...)``) and asserts
  ``np.array_equal`` — the same program, byte-for-byte the same answers.
* **Failure is contained.** Each model carries its own device-tier
  circuit breaker (the per-model ``WorkflowModel._engine_breaker``); a
  failed micro-batch dispatch retries per request on the host path; a
  request that BOTH tiers reject is quarantined (resilience dead-letter
  sink) and its future carries the error — the server never dies with
  traffic in flight. ``server.dispatch`` is a registered fault site, so
  chaos plans can score the whole path deterministically.
* **Backpressure is explicit.** Queues are bounded; a full queue rejects
  the request with :class:`ServerBusy` (HTTP 429) instead of buffering
  without bound. Graceful shutdown drains every queued request before
  workers exit.

The HTTP front end is stdlib-only (``http.server``)::

    POST /v1/models/<name>:score   {"records": [...]}  → scored rows
    GET  /v1/models                → model table + stats
    GET  /healthz                  → liveness
    GET  /stats                    → server_stats() + per-model stats

Run it with ``python -m transmogrifai_tpu serve params.json`` (knobs:
``customParams.serve*`` — see docs/serving.md).
"""
from __future__ import annotations

import json
import logging
import queue
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from . import aot, resilience, telemetry

logger = logging.getLogger(__name__)

__all__ = ["ModelServer", "RequestResult", "ServerError", "ModelNotFound",
           "ServerBusy", "ServerClosed", "serve_http", "server_stats",
           "reset_server_stats", "DEFAULT_BATCH_DEADLINE_MS",
           "DEFAULT_MAX_QUEUE", "DEFAULT_MAX_MODELS"]

#: how long the micro-batcher holds the first queued request open for
#: co-riders before dispatching (ms). 0 = dispatch immediately.
DEFAULT_BATCH_DEADLINE_MS = 2.0

#: bounded per-model queue — beyond it, submit() raises ServerBusy
DEFAULT_MAX_QUEUE = 256

#: loaded models held before the LRU evicts
DEFAULT_MAX_MODELS = 4

#: per-model latency reservoir for exact p50/p95/p99 in stats
_LATENCY_WINDOW = 4096


# ---------------------------------------------------------------------------
# always-on tallies (bench docs stamp these; telemetry mirrors when enabled)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"requests": 0, "requests_failed": 0, "rows": 0, "batches": 0,
          "coalesced_requests": 0, "bank_hit_batches": 0, "rejected": 0,
          "quarantined_requests": 0, "model_loads": 0, "model_evictions": 0,
          "bank_loads": 0, "slo_met": 0, "slo_missed": 0}


def server_stats() -> Dict[str, Any]:
    """Process-wide serving tallies (always on, the
    ``engine_cache_stats`` discipline) plus the derived headline
    numbers: ``batch_coalescing_factor`` (requests per dispatch),
    ``bank_hit_rate`` (dispatches served by an AOT-banked program) and
    ``slo_attainment`` (fraction of SLO-tracked requests under the
    deadline; None when no SLO is configured)."""
    with _TALLY_LOCK:
        out: Dict[str, Any] = dict(_TALLY)
    out["batch_coalescing_factor"] = (
        round(out["requests"] / out["batches"], 3) if out["batches"]
        else None)
    out["bank_hit_rate"] = (
        round(out["bank_hit_batches"] / out["batches"], 3)
        if out["batches"] else None)
    tracked = out["slo_met"] + out["slo_missed"]
    out["slo_attainment"] = (round(out["slo_met"] / tracked, 4)
                             if tracked else None)
    return out


def reset_server_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


# ---------------------------------------------------------------------------
# request plumbing
# ---------------------------------------------------------------------------


class ServerError(Exception):
    """Base class for serving-tier rejections."""


class ModelNotFound(ServerError):
    pass


class ServerBusy(ServerError):
    """Admission control: the model's bounded queue is full — explicit
    backpressure instead of unbounded buffering (HTTP 429)."""


class ServerClosed(ServerError):
    pass


@dataclass
class RequestResult:
    """One request's scored slice plus its dispatch provenance."""

    store: Any                  # ColumnStore of the result columns
    rows: int
    bucket: int                 # padded ladder bucket the dispatch used
    coalesced: int              # requests sharing that dispatch
    seconds: float              # queue-to-completion latency
    engine_tier: bool           # True = compiled engine, False = host


class _Request:
    __slots__ = ("records", "future", "t_enqueued", "rows")

    def __init__(self, records: List[Dict[str, Any]]):
        self.records = list(records)
        self.rows = len(self.records)
        self.future: "Future[RequestResult]" = Future()
        self.t_enqueued = time.perf_counter()


_SENTINEL = object()


class _ModelEntry:
    """One registered model: its queue, worker, loaded state and stats."""

    def __init__(self, name: str, model_dir: Optional[str],
                 bank_dir: Optional[str], model: Any,
                 max_queue: int):
        self.name = name
        self.model_dir = model_dir
        self.bank_dir = bank_dir
        #: a model registered as a live object (no directory) cannot be
        #: reloaded after eviction, so the LRU pins it
        self.pinned = model is not None and model_dir is None
        self.model = model
        self.engine = None
        self.bank_buckets: List[int] = []
        self.bank_report: Optional[Dict[str, Any]] = None
        self.weight_bytes = 0
        self.queue: "queue.Queue[Any]" = queue.Queue(maxsize=max_queue)
        self.lock = threading.Lock()       # guards load/unload
        self.worker: Optional[threading.Thread] = None
        self.latencies: "deque[float]" = deque(maxlen=_LATENCY_WINDOW)
        self.requests = 0
        self.failures = 0
        self.rows = 0
        self.batches = 0
        self.bank_hit_batches = 0
        self.loads = 0

    def stats(self) -> Dict[str, Any]:
        lat = np.asarray(self.latencies, dtype=np.float64)
        pct = {}
        if lat.size:
            pct = {"p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
                   "p95_ms": round(float(np.percentile(lat, 95)) * 1e3, 3),
                   "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3)}
        return {"loaded": self.model is not None, "pinned": self.pinned,
                "requests": self.requests, "failures": self.failures,
                "rows": self.rows, "batches": self.batches,
                "bankBuckets": list(self.bank_buckets),
                "bankHitBatches": self.bank_hit_batches,
                "weightBytes": self.weight_bytes,
                "queueDepth": self.queue.qsize(), "loads": self.loads,
                **pct}


class ModelServer:
    """N models behind a weighted LRU, one micro-batching worker each.

    ``capacity_bytes`` bounds the summed program-bank weight of loaded
    models (``max_models`` bounds their count); the least-recently-used
    reloadable model is unloaded when either bound is crossed and
    transparently reloaded on its next request. ``batch_deadline_s`` is
    the micro-batching hold; ``slo_ms`` (optional) scores each request
    against a latency SLO in stats and telemetry."""

    def __init__(self, max_models: int = DEFAULT_MAX_MODELS,
                 capacity_bytes: Optional[int] = None,
                 batch_deadline_s: float = DEFAULT_BATCH_DEADLINE_MS / 1e3,
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 slo_ms: Optional[float] = None,
                 bucket_cap: Optional[int] = None):
        if max_models < 1:
            raise ValueError(f"max_models must be >= 1, got {max_models}")
        self.max_models = int(max_models)
        self.capacity_bytes = (None if capacity_bytes is None
                               else int(capacity_bytes))
        self.batch_deadline_s = max(float(batch_deadline_s), 0.0)
        self.max_queue = int(max_queue)
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.bucket_cap = bucket_cap
        #: LRU order: oldest first; touched on every submit
        self._entries: "OrderedDict[str, _ModelEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self._closed = False

    # -- registration / LRU ------------------------------------------------
    def register(self, name: str, model_dir: Optional[str] = None,
                 bank_dir: Optional[str] = None,
                 model: Any = None, preload: bool = False) -> None:
        """Register a tenant: either a saved-model directory (evictable,
        reloaded on demand) or a live ``WorkflowModel`` (pinned).
        ``bank_dir`` names the export directory carrying the AOT program
        bank (aot.py); ``preload`` loads immediately instead of on first
        request."""
        if model is None and model_dir is None:
            raise ValueError("register() needs model_dir or model")
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            entry = _ModelEntry(name, model_dir, bank_dir, model,
                                self.max_queue)
            entry.worker = threading.Thread(
                target=self._worker_loop, args=(entry,),
                name=f"serve-{name}", daemon=True)
            self._entries[name] = entry
        entry.worker.start()
        if preload or model is not None:
            self._ensure_loaded(entry)

    def models(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def _ensure_loaded(self, entry: _ModelEntry):
        """Load (or reload) the entry's model + engine + bank; evict LRU
        models over capacity. Engine is built ``gate_bandwidth=False``
        (a serving loop amortizes every compile immediately) and
        ``mesh=False`` (banked executables are unsharded — see aot.py).

        Returns ``(model, engine, bank_buckets)`` captured UNDER the
        entry lock: a dispatch must score through these locals, never
        through ``entry.model``/``entry.engine``, because a concurrent
        LRU eviction may null the entry's slots mid-dispatch — the
        captured references keep the objects alive until the batch
        completes."""
        with entry.lock:
            if entry.model is None:
                from .workflow import WorkflowModel
                with telemetry.span("server:load_model",
                                    model=entry.name):
                    entry.model = WorkflowModel.load(entry.model_dir)
                entry.loads += 1
                _tally("model_loads")
                telemetry.counter("server.model_loads").inc()
            if entry.engine is None:
                kw: Dict[str, Any] = {"gate_bandwidth": False,
                                      "mesh": False}
                if self.bucket_cap:
                    kw["bucket_cap"] = int(self.bucket_cap)
                entry.engine = entry.model.scoring_engine(**kw)
                if entry.engine is not None and entry.bank_dir:
                    report = aot.load_program_bank(entry.engine,
                                                   entry.bank_dir)
                    entry.bank_report = report
                    entry.bank_buckets = list(report["loaded"])
                    if report["loaded"]:
                        _tally("bank_loads")
                entry.weight_bytes = self._entry_weight(entry)
            captured = (entry.model, entry.engine,
                        list(entry.bank_buckets))
        self._evict_over_capacity(keep=entry.name)
        return captured

    def _entry_weight(self, entry: _ModelEntry) -> int:
        """LRU weight: the bank's serialized-program bytes (the dominant
        resident cost of a served model — compiled executables), else a
        1 MiB floor so bankless models still count against capacity."""
        manifest, _ = (aot.read_manifest(entry.bank_dir)
                       if entry.bank_dir else (None, []))
        return max(aot.bank_bytes(manifest), 1 << 20)

    def _evict_over_capacity(self, keep: str) -> None:
        while True:
            victim = None
            with self._lock:
                loaded = [e for e in self._entries.values()
                          if e.model is not None and not e.pinned]
                n_loaded = sum(1 for e in self._entries.values()
                               if e.model is not None)
                total = sum(e.weight_bytes for e in self._entries.values()
                            if e.model is not None)
                over = (n_loaded > self.max_models
                        or (self.capacity_bytes is not None
                            and total > self.capacity_bytes))
                if over:
                    for e in loaded:         # LRU order: oldest first
                        if e.name != keep and e.queue.qsize() == 0:
                            victim = e
                            break
            if victim is None:
                return
            with victim.lock:
                if victim.model is None:
                    continue
                logger.info("server: evicting %s (LRU, %d bytes)",
                            victim.name, victim.weight_bytes)
                victim.model = None
                victim.engine = None
                victim.bank_buckets = []
                _tally("model_evictions")
                telemetry.counter("server.model_evictions").inc()

    # -- request entry -----------------------------------------------------
    def submit(self, name: str, records: List[Dict[str, Any]]):
        """Enqueue a scoring request; returns a
        ``concurrent.futures.Future[RequestResult]``. Raises
        :class:`ModelNotFound` / :class:`ServerBusy` /
        :class:`ServerClosed` synchronously (admission control)."""
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            entry = self._entries.get(name)
            if entry is not None:
                self._entries.move_to_end(name)    # LRU touch
        if entry is None:
            raise ModelNotFound(f"no model {name!r} registered "
                                f"(have: {self.models()})")
        req = _Request(records)
        try:
            entry.queue.put_nowait(req)
        except queue.Full:
            _tally("rejected")
            telemetry.counter("server.rejected").inc()
            raise ServerBusy(
                f"model {name!r} queue is full ({self.max_queue} "
                "pending) — back off and retry") from None
        if telemetry.enabled():
            telemetry.gauge(f"server.queue_depth.{name}").set(
                entry.queue.qsize())
        return req.future

    def score(self, name: str, records: List[Dict[str, Any]],
              timeout_s: Optional[float] = 30.0) -> RequestResult:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(name, records).result(timeout=timeout_s)

    # -- micro-batching worker ---------------------------------------------
    def _worker_loop(self, entry: _ModelEntry) -> None:
        from .scoring import DEFAULT_BUCKET_CAP
        cap = int(self.bucket_cap or DEFAULT_BUCKET_CAP)
        stop = False
        while not stop:
            item = entry.queue.get()
            if item is _SENTINEL:
                break
            batch: List[_Request] = [item]
            rows = item.rows
            deadline = time.perf_counter() + self.batch_deadline_s
            # dynamic micro-batching: hold the dispatch open until the
            # deadline (or the bucket cap) for co-riding requests
            while rows < cap:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    break
                try:
                    nxt = entry.queue.get(timeout=wait)
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    stop = True        # drain this batch, then exit
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._dispatch(entry, batch)
        # drain anything still queued after the sentinel (shutdown
        # promises no request is dropped)
        leftovers: List[_Request] = []
        while True:
            try:
                item = entry.queue.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL:
                leftovers.append(item)
        if leftovers:
            self._dispatch(entry, leftovers)

    def _dispatch(self, entry: _ModelEntry, batch: List[_Request]) -> None:
        """Score one coalesced micro-batch and scatter results back.
        Tier ladder: compiled engine (breaker-governed) → per-request
        host fallback → quarantine + per-future error. Never raises."""
        from .scoring import DEFAULT_BUCKET_CAP, bucket_for
        try:
            # model/engine captured under the entry lock: a concurrent
            # LRU eviction nulling entry.model mid-dispatch must not
            # touch THIS batch (the locals keep the objects alive)
            model, eng, bank_buckets = self._ensure_loaded(entry)
        except Exception as e:  # lint: broad-except — a model that cannot load must fail ITS requests, not the server
            logger.exception("server: loading %s failed", entry.name)
            for req in batch:
                if not req.future.set_running_or_notify_cancel():
                    continue
                req.future.set_exception(e)
            return
        records = [r for req in batch for r in req.records]
        n = len(records)
        cap = eng.bucket_cap if eng is not None \
            else (self.bucket_cap or DEFAULT_BUCKET_CAP)
        bucket = bucket_for(n, int(cap)) if n else 0
        t0 = time.perf_counter()
        store = None
        engine_tier = False
        brk = model._engine_breaker()
        if n and eng is not None and brk.allow():
            try:
                resilience.inject("server.dispatch", model=entry.name,
                                  rows=n, requests=len(batch))
                with telemetry.span("server:dispatch", model=entry.name,
                                    rows=n, requests=len(batch),
                                    bucket=bucket):
                    store = eng.score_store(records, use_cache=False)
                brk.record_success()
                engine_tier = True
            except Exception:  # lint: broad-except — breaker-governed device-tier fallback (per-request host retry follows)
                brk.record_failure()
                logger.exception(
                    "server: engine dispatch for %s failed; batch "
                    "retries per request on the host path", entry.name)
                store = None
        entry.batches += 1
        _tally("batches")
        _tally("rows", n)
        bank_hit = engine_tier and bucket in bank_buckets
        if bank_hit:
            entry.bank_hit_batches += 1
            _tally("bank_hit_batches")
        if len(batch) > 1:
            _tally("coalesced_requests", len(batch))
        telemetry.counter("server.batches").inc()
        lo = 0
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                lo += req.rows
                continue
            if store is not None:
                sub = store.take(np.arange(lo, lo + req.rows))
                lo += req.rows
                self._complete(entry, req, sub, bucket, len(batch),
                               engine_tier)
                continue
            # per-request host fallback: the dispatch site fires again
            # (a solo retry IS a dispatch), so chaos plans can poison
            # individual requests deterministically
            try:
                resilience.inject("server.dispatch", model=entry.name,
                                  rows=req.rows, requests=1)
                sub = model.score(req.records, engine=False)
            except Exception as e:  # lint: broad-except — both tiers rejected: the request is poison, quarantined not fatal
                resilience.quarantine(
                    "server.dispatch", repr(e), kind="batches",
                    model=entry.name, rows=req.rows,
                    records=req.records)
                _tally("quarantined_requests")
                _tally("requests_failed")
                entry.failures += 1
                telemetry.counter("server.requests_failed").inc()
                seconds = time.perf_counter() - req.t_enqueued
                telemetry.emit("request", model=entry.name,
                               rows=req.rows, seconds=seconds, ok=False,
                               coalesced=len(batch), bucket=bucket,
                               slo_met=self._slo(seconds))
                req.future.set_exception(e)
                continue
            self._complete(entry, req, sub, bucket, len(batch), False)

    def _slo(self, seconds: float) -> Optional[bool]:
        if self.slo_ms is None:
            return None
        met = seconds * 1e3 <= self.slo_ms
        _tally("slo_met" if met else "slo_missed")
        return met

    def _complete(self, entry: _ModelEntry, req: _Request, store,
                  bucket: int, coalesced: int, engine_tier: bool) -> None:
        seconds = time.perf_counter() - req.t_enqueued
        entry.requests += 1
        entry.rows += req.rows
        entry.latencies.append(seconds)
        _tally("requests")
        telemetry.counter("server.requests").inc()
        telemetry.counter("server.rows_scored").inc(req.rows)
        if telemetry.enabled():
            telemetry.histogram(
                f"server.request_seconds.{entry.name}").observe(seconds)
            telemetry.gauge(f"server.queue_depth.{entry.name}").set(
                entry.queue.qsize())
        slo_met = self._slo(seconds)
        telemetry.emit("request", model=entry.name, rows=req.rows,
                       seconds=seconds, ok=True, coalesced=coalesced,
                       bucket=bucket, slo_met=slo_met)
        req.future.set_result(RequestResult(
            store=store, rows=req.rows, bucket=bucket,
            coalesced=coalesced, seconds=seconds,
            engine_tier=engine_tier))

    # -- stats / shutdown --------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """This server's view: global tallies + per-model stats (incl.
        exact p50/p95/p99 over the latency window)."""
        with self._lock:
            entries = list(self._entries.items())
        return {"server": server_stats(),
                "sloMs": self.slo_ms,
                "batchDeadlineMs": self.batch_deadline_s * 1e3,
                "models": {name: e.stats() for name, e in entries}}

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = 30.0) -> None:
        """Stop accepting requests and stop the workers. With ``drain``
        (the default) every queued request is scored before its worker
        exits — graceful shutdown never drops accepted work. Without
        it, pending futures fail with :class:`ServerClosed`."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._entries.values())
        for e in entries:
            if not drain:
                # fail queued requests loudly instead of scoring them
                while True:
                    try:
                        item = e.queue.get_nowait()
                    except queue.Empty:
                        break
                    if item is not _SENTINEL and \
                            item.future.set_running_or_notify_cancel():
                        item.future.set_exception(
                            ServerClosed("server shut down (no drain)"))
            e.queue.put(_SENTINEL)
        for e in entries:
            if e.worker is not None:
                e.worker.join(timeout=timeout_s)


# ---------------------------------------------------------------------------
# stdlib HTTP front end
# ---------------------------------------------------------------------------


def _store_rows(store) -> List[Dict[str, Any]]:
    return [{nm: store[nm].get_raw(i) for nm in store.names()}
            for i in range(store.n_rows)]


def serve_http(server: ModelServer, host: str = "127.0.0.1",
               port: int = 8000, request_timeout_s: float = 30.0):
    """Start the stdlib HTTP front end on a daemon thread; returns the
    ``ThreadingHTTPServer`` (``.server_address`` carries the bound port;
    ``.shutdown()`` stops it). No dependencies beyond the stdlib."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # route through logging
            logger.debug("http: " + fmt, *args)

        def _send(self, code: int, doc: Dict[str, Any]) -> None:
            body = json.dumps(doc, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                return self._send(200, {"status": "ok",
                                        "models": server.models()})
            if self.path == "/stats":
                return self._send(200, server.stats())
            if self.path == "/v1/models":
                return self._send(200, {"models": server.stats()["models"]})
            return self._send(404, {"error": f"no route {self.path!r}"})

        def do_POST(self):
            path = self.path
            if not (path.startswith("/v1/models/")
                    and path.endswith(":score")):
                return self._send(404, {"error": f"no route {path!r}"})
            name = path[len("/v1/models/"):-len(":score")]
            try:
                length = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(length) or b"{}")
                records = doc.get("records")
                if not isinstance(records, list) or not records:
                    return self._send(400, {
                        "error": "body must be {\"records\": [..]} with "
                                 "at least one record"})
                res = server.submit(name, records).result(
                    timeout=request_timeout_s)
            except ModelNotFound as e:
                return self._send(404, {"error": str(e)})
            except ServerBusy as e:
                return self._send(429, {"error": str(e)})
            except ServerClosed as e:
                return self._send(503, {"error": str(e)})
            except json.JSONDecodeError as e:
                return self._send(400, {"error": f"bad JSON body: {e}"})
            except Exception as e:  # lint: broad-except — HTTP boundary: a poison request answers 500, the server lives
                return self._send(500, {"error": repr(e)})
            return self._send(200, {
                "model": name, "rows": res.rows, "bucket": res.bucket,
                "coalesced": res.coalesced,
                "latencyMs": round(res.seconds * 1e3, 3),
                "engineTier": res.engine_tier,
                "outputs": _store_rows(res.store)})

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever,
                         name="serve-http", daemon=True)
    t.start()
    logger.info("model server HTTP front end on %s:%d",
                *httpd.server_address)
    return httpd
