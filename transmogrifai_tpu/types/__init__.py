from .feature_types import *  # noqa: F401,F403
from .feature_types import __all__ as _ft_all

__all__ = list(_ft_all)
