"""Feature type system — the typed value layer of the framework.

Re-designs the reference's 45-class ``FeatureType`` hierarchy (52 concrete types)
(``features/src/main/scala/com/salesforce/op/features/types/FeatureType.scala:44-324``)
for a columnar, TPU-first world:

* Each type is a lightweight Python class that *boxes a single row value*
  (used for row-level serving, tests, and semantics) and carries static
  metadata describing its **columnar physical layout** (``ColumnKind``) so
  bulk data lives in dense device arrays + null masks, never in per-row
  boxes.
* ``Option[T]`` nullability becomes ``None`` at the boxed level and a
  ``bool`` validity mask at the columnar level.
* The reference's runtime ``TypeTag`` registry
  (``FeatureType.scala:265-324``) becomes ``FEATURE_TYPE_REGISTRY``.

The hierarchy mirrors the reference exactly in names and subtyping:

    FeatureType
    ├── Numerics: Real (RealNN, Percent, Currency), Integral (Date, DateTime), Binary
    ├── Text: Text (Email, Base64, Phone, ID, URL, TextArea, PickList,
    │          ComboBox, Country, State, City, PostalCode, Street)
    ├── Vector: OPVector
    ├── Lists: TextList, DateList (DateTimeList), Geolocation
    ├── Sets: MultiPickList
    └── Maps: 23 map types + Prediction

Traits (``NonNullable``, ``SingleResponse``, ``Categorical``, ``Location``)
are mixin classes, as in ``FeatureType.scala:173-263``.
"""
from __future__ import annotations

import math
from enum import Enum
from typing import Any, ClassVar, Dict, Iterable, List, Mapping, Optional, Sequence, Set, Tuple, Type

import numpy as np

__all__ = [
    "ColumnKind", "FeatureType", "FeatureTypeError",
    # traits
    "NonNullable", "SingleResponse", "MultiResponse", "Categorical", "Location",
    # numerics
    "OPNumeric", "Real", "RealNN", "Binary", "Integral", "Percent", "Currency",
    "Date", "DateTime",
    # text
    "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea", "PickList",
    "ComboBox", "Country", "State", "City", "PostalCode", "Street",
    # collections
    "OPVector", "OPList", "TextList", "DateList", "DateTimeList", "OPSet",
    "MultiPickList", "Geolocation",
    # maps
    "OPMap", "Base64Map", "BinaryMap", "ComboBoxMap", "CurrencyMap", "DateMap",
    "DateTimeMap", "EmailMap", "IDMap", "IntegralMap", "MultiPickListMap",
    "PercentMap", "PhoneMap", "PickListMap", "RealMap", "TextAreaMap", "TextMap",
    "URLMap", "CountryMap", "StateMap", "CityMap", "PostalCodeMap", "StreetMap",
    "GeolocationMap", "Prediction",
    # registry + helpers
    "FEATURE_TYPE_REGISTRY", "feature_type_by_name", "is_subtype",
]


class FeatureTypeError(TypeError):
    """Raised on invalid feature values (e.g. NaN in a RealNN, bad Prediction keys)."""


class ColumnKind(Enum):
    """Physical columnar layout of a feature type on host/device.

    The TPU compute path only ever sees dense arrays + masks; this enum is
    the single source of truth for how each logical type is stored.
    """

    REAL = "real"            # f32[n] values + bool[n] mask
    INTEGRAL = "integral"    # i64[n] values + bool[n] mask
    BINARY = "binary"        # bool[n] values + bool[n] mask
    TEXT = "text"            # host object[n] of Optional[str]
    TEXT_LIST = "text_list"  # host list[list[str]]
    REAL_LIST = "real_list"  # ragged f64 via offsets (Geolocation is fixed 3)
    INTEGRAL_LIST = "integral_list"  # ragged i64 via offsets (DateList etc.)
    TEXT_SET = "text_set"    # host list[set[str]]
    VECTOR = "vector"        # f32[n, d] dense + OpVectorMetadata
    GEO = "geo"              # f32[n, 3] (lat, lon, accuracy) + bool[n] mask
    MAP = "map"              # dict[key -> subcolumn of element kind]
    PREDICTION = "prediction"  # fixed struct-of-arrays (pred, raw, prob)


class FeatureType:
    """Base boxed value. ``value`` is the payload; emptiness == ``None``/empty.

    Mirrors ``FeatureType.scala:44-171``: equality is on value, ``is_empty``
    tests emptiness, ``non_nullable`` marks types that forbid emptiness.
    """

    __slots__ = ("_value",)

    #: physical layout for bulk storage
    column_kind: ClassVar[ColumnKind] = ColumnKind.REAL
    #: element kind for MAP types
    map_element_kind: ClassVar[Optional[ColumnKind]] = None

    def __init__(self, value: Any = None):
        self._value = self._convert(value)
        # NonNullable forbids a null payload; an empty collection (e.g. a
        # zero-size OPVector) is still legal, matching the reference.
        if self.non_nullable() and self._value is None:
            raise FeatureTypeError(
                f"{type(self).__name__} cannot be empty (NonNullable)")

    # -- value semantics ---------------------------------------------------
    @property
    def value(self) -> Any:
        return self._value

    @property
    def is_empty(self) -> bool:
        v = self._value
        if v is None:
            return True
        if isinstance(v, (list, tuple, set, dict, str)):
            return len(v) == 0
        if isinstance(v, np.ndarray):
            return v.size == 0
        return False

    @property
    def is_non_empty(self) -> bool:
        return not self.is_empty

    @classmethod
    def non_nullable(cls) -> bool:
        return issubclass(cls, NonNullable)

    @classmethod
    def is_categorical(cls) -> bool:
        return issubclass(cls, Categorical)

    @classmethod
    def is_location(cls) -> bool:
        return issubclass(cls, Location)

    @classmethod
    def type_name(cls) -> str:
        return cls.__name__

    @classmethod
    def empty(cls) -> "FeatureType":
        return cls(None)

    def exists(self, pred) -> bool:
        return self.is_non_empty and pred(self._value)

    # -- conversion hook ---------------------------------------------------
    @classmethod
    def _convert(cls, value: Any) -> Any:
        return value

    # -- dunder ------------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, FeatureType):
            return NotImplemented
        if type(self) is not type(other):
            return False
        return self._comparable() == other._comparable()

    def _comparable(self) -> Any:
        return self._value

    def __hash__(self) -> int:
        c = self._comparable()
        if isinstance(c, (list, np.ndarray)):
            c = tuple(np.asarray(c).tolist())
        elif isinstance(c, set):
            c = frozenset(c)
        elif isinstance(c, dict):
            c = tuple(sorted(c.items()))
        return hash((type(self).__name__, c))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._value!r})"


# ---------------------------------------------------------------------------
# Traits (FeatureType.scala:173-263)
# ---------------------------------------------------------------------------

class NonNullable:
    """Marker: the value may never be empty."""


class SingleResponse:
    """Marker: valid single-response label type (RealNN, Binary, ...)."""


class MultiResponse:
    """Marker: valid multi-response label type."""


class Categorical:
    """Marker: categorical-valued (Binary, PickList, ComboBox, MultiPickList, ...)."""


class Location:
    """Marker: geographic types (Geolocation, Country, State, City, ...)."""


# ---------------------------------------------------------------------------
# Numerics (features/.../types/Numerics.scala)
# ---------------------------------------------------------------------------

class OPNumeric(FeatureType):
    """Base for numeric scalars: value is ``Optional[number]``."""

    def to_double(self) -> Optional[float]:
        return None if self._value is None else float(self._value)


class Real(OPNumeric):
    column_kind = ColumnKind.REAL

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, (bool, np.bool_)):
            return 1.0 if value else 0.0
        v = float(value)
        if math.isnan(v):
            return None
        return v


class RealNN(Real, NonNullable, SingleResponse):
    """Non-nullable real — the canonical label type."""


class Percent(Real):
    pass


class Currency(Real):
    pass


class Integral(OPNumeric):
    column_kind = ColumnKind.INTEGRAL

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, float) and math.isnan(value):
            return None
        return int(value)


class Date(Integral):
    """Milliseconds-since-epoch timestamp (day precision by convention)."""


class DateTime(Date):
    pass


class Binary(OPNumeric, SingleResponse, Categorical):
    column_kind = ColumnKind.BINARY

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, str):
            lowered = value.strip().lower()
            if lowered in ("true", "1", "yes"):
                return True
            if lowered in ("false", "0", "no"):
                return False
            raise FeatureTypeError(f"Cannot parse {value!r} as Binary")
        return bool(value)

    def to_double(self) -> Optional[float]:
        return None if self._value is None else (1.0 if self._value else 0.0)


# ---------------------------------------------------------------------------
# Text hierarchy (features/.../types/Text.scala)
# ---------------------------------------------------------------------------

class Text(FeatureType):
    column_kind = ColumnKind.TEXT

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        return str(value)


class Email(Text):
    @property
    def prefix(self) -> Optional[str]:
        parts = self._split()
        return parts[0] if parts else None

    @property
    def domain(self) -> Optional[str]:
        parts = self._split()
        return parts[1] if parts else None

    def _split(self) -> Optional[Tuple[str, str]]:
        if self.is_empty or "@" not in self._value:
            return None
        prefix, _, domain = self._value.partition("@")
        if not prefix or not domain:
            return None
        return (prefix, domain)


class Base64(Text):
    def as_bytes(self) -> Optional[bytes]:
        if self.is_empty:
            return None
        import base64 as _b64
        try:
            return _b64.b64decode(self._value)
        except ValueError:      # binascii.Error: malformed base64
            return None


class Phone(Text):
    pass


class ID(Text):
    pass


class URL(Text):
    def is_valid(self) -> bool:
        """Protocol must be http/https/ftp and host non-empty (RichTextFeature semantics)."""
        if self.is_empty:
            return False
        from urllib.parse import urlparse
        try:
            p = urlparse(self._value)
        except ValueError:
            return False
        return p.scheme in ("http", "https", "ftp") and bool(p.netloc)

    @property
    def domain(self) -> Optional[str]:
        if not self.is_valid():
            return None
        from urllib.parse import urlparse
        return urlparse(self._value).netloc


class TextArea(Text):
    pass


class PickList(Text, SingleResponse, Categorical):
    pass


class ComboBox(Text, Categorical):
    pass


class Country(Text, Location):
    pass


class State(Text, Location):
    pass


class City(Text, Location):
    pass


class PostalCode(Text, Location):
    pass


class Street(Text, Location):
    pass


# ---------------------------------------------------------------------------
# Vector (features/.../types/OPVector.scala)
# ---------------------------------------------------------------------------

class OPVector(FeatureType, NonNullable):
    """Dense feature vector. Value is a float64 numpy array (never None).

    The reference wraps ``ml.linalg.Vector`` (sparse or dense); on TPU we are
    always dense — XLA prefers dense bf16/f32 tiles, and d <= 16384 fits.
    """

    column_kind = ColumnKind.VECTOR

    @classmethod
    def _convert(cls, value):
        if value is None:
            return np.zeros((0,), dtype=np.float64)
        arr = np.asarray(value, dtype=np.float64)
        if arr.ndim != 1:
            raise FeatureTypeError(f"OPVector must be rank-1, got shape {arr.shape}")
        return arr

    @property
    def is_empty(self) -> bool:
        return self._value.size == 0

    def _comparable(self):
        return tuple(self._value.tolist())

    def combine(self, *others: "OPVector") -> "OPVector":
        arrays = [self._value] + [o.value for o in others]
        return OPVector(np.concatenate(arrays))


# ---------------------------------------------------------------------------
# Lists & sets (features/.../types/Lists.scala, Sets.scala)
# ---------------------------------------------------------------------------

class OPList(FeatureType):
    @classmethod
    def _convert(cls, value):
        if value is None:
            return []
        return list(value)

    def _comparable(self):
        return tuple(self._value)


class TextList(OPList):
    column_kind = ColumnKind.TEXT_LIST

    @classmethod
    def _convert(cls, value):
        return [str(v) for v in (value or [])]


class DateList(OPList):
    column_kind = ColumnKind.INTEGRAL_LIST

    @classmethod
    def _convert(cls, value):
        return [int(v) for v in (value or [])]


class DateTimeList(DateList):
    pass


class OPSet(FeatureType, Categorical):
    @classmethod
    def _convert(cls, value):
        if value is None:
            return set()
        return set(value)

    def _comparable(self):
        return frozenset(self._value)


class MultiPickList(OPSet, MultiResponse):
    column_kind = ColumnKind.TEXT_SET

    @classmethod
    def _convert(cls, value):
        return {str(v) for v in (value or ())}


class Geolocation(OPList, Location):
    """(lat, lon, accuracy) triple; empty list when absent.

    Accuracy is an integer rank as in ``GeolocationAccuracy``
    (``features/.../types/Geolocation.scala:206``).
    """

    column_kind = ColumnKind.GEO

    @classmethod
    def _convert(cls, value):
        if value is None:
            return []
        vals = [float(v) for v in value]
        if vals and len(vals) != 3:
            raise FeatureTypeError(
                f"Geolocation must be empty or [lat, lon, accuracy], got {vals}")
        if vals:
            lat, lon = vals[0], vals[1]
            if math.isnan(lat) or math.isnan(lon):
                raise FeatureTypeError("Geolocation lat/lon cannot be NaN")
            if not (-90.0 <= lat <= 90.0):
                raise FeatureTypeError(f"Latitude {lat} out of range [-90, 90]")
            if not (-180.0 <= lon <= 180.0):
                raise FeatureTypeError(f"Longitude {lon} out of range [-180, 180]")
        return vals

    @property
    def lat(self) -> Optional[float]:
        return self._value[0] if self._value else None

    @property
    def lon(self) -> Optional[float]:
        return self._value[1] if self._value else None

    @property
    def accuracy(self) -> Optional[float]:
        return self._value[2] if self._value else None

    def to_unit_sphere(self) -> Optional[np.ndarray]:
        """3-D unit-sphere embedding, the TPU-friendly geo representation."""
        if self.is_empty:
            return None
        lat, lon = math.radians(self._value[0]), math.radians(self._value[1])
        return np.array([
            math.cos(lat) * math.cos(lon),
            math.cos(lat) * math.sin(lon),
            math.sin(lat),
        ])


# ---------------------------------------------------------------------------
# Maps (features/.../types/Maps.scala — 23 types + Prediction)
# ---------------------------------------------------------------------------

class OPMap(FeatureType):
    """String-keyed map. Subclasses fix the element type/kind."""

    column_kind = ColumnKind.MAP
    element_type: ClassVar[Type[FeatureType]] = FeatureType

    @classmethod
    def _convert(cls, value):
        if value is None:
            return {}
        return {str(k): cls._convert_element(v) for k, v in dict(value).items()}

    @classmethod
    def _convert_element(cls, v):
        return v

    def _comparable(self):
        return tuple(sorted(
            (k, tuple(v) if isinstance(v, (list, set)) else v)
            for k, v in self._value.items()))


def _make_map_type(name: str, element: Type[FeatureType], elem_kind: ColumnKind,
                   convert_element, bases: Tuple[type, ...] = ()) -> Type[OPMap]:
    cls = type(name, (OPMap,) + bases, {
        "element_type": element,
        "map_element_kind": elem_kind,
        "_convert_element": classmethod(lambda c, v: convert_element(v)),
        "__doc__": f"Map[str, {element.__name__}] (Maps.scala).",
    })
    return cls


def _real_elem(v):
    return None if v is None else float(v)


def _int_elem(v):
    return None if v is None else int(v)


def _bool_elem(v):
    return None if v is None else bool(v)


def _str_elem(v):
    return None if v is None else str(v)


def _set_elem(v):
    return {str(x) for x in (v or ())}


def _geo_elem(v):
    return Geolocation._convert(v)


TextMap = _make_map_type("TextMap", Text, ColumnKind.TEXT, _str_elem)
EmailMap = _make_map_type("EmailMap", Email, ColumnKind.TEXT, _str_elem)
Base64Map = _make_map_type("Base64Map", Base64, ColumnKind.TEXT, _str_elem)
PhoneMap = _make_map_type("PhoneMap", Phone, ColumnKind.TEXT, _str_elem)
IDMap = _make_map_type("IDMap", ID, ColumnKind.TEXT, _str_elem)
URLMap = _make_map_type("URLMap", URL, ColumnKind.TEXT, _str_elem)
TextAreaMap = _make_map_type("TextAreaMap", TextArea, ColumnKind.TEXT, _str_elem)
PickListMap = _make_map_type("PickListMap", PickList, ColumnKind.TEXT, _str_elem,
                             bases=(Categorical,))
ComboBoxMap = _make_map_type("ComboBoxMap", ComboBox, ColumnKind.TEXT, _str_elem,
                             bases=(Categorical,))
CountryMap = _make_map_type("CountryMap", Country, ColumnKind.TEXT, _str_elem,
                            bases=(Location,))
StateMap = _make_map_type("StateMap", State, ColumnKind.TEXT, _str_elem,
                          bases=(Location,))
CityMap = _make_map_type("CityMap", City, ColumnKind.TEXT, _str_elem,
                         bases=(Location,))
PostalCodeMap = _make_map_type("PostalCodeMap", PostalCode, ColumnKind.TEXT,
                               _str_elem, bases=(Location,))
StreetMap = _make_map_type("StreetMap", Street, ColumnKind.TEXT, _str_elem,
                           bases=(Location,))
RealMap = _make_map_type("RealMap", Real, ColumnKind.REAL, _real_elem)
PercentMap = _make_map_type("PercentMap", Percent, ColumnKind.REAL, _real_elem)
CurrencyMap = _make_map_type("CurrencyMap", Currency, ColumnKind.REAL, _real_elem)
IntegralMap = _make_map_type("IntegralMap", Integral, ColumnKind.INTEGRAL, _int_elem)
DateMap = _make_map_type("DateMap", Date, ColumnKind.INTEGRAL, _int_elem)
DateTimeMap = _make_map_type("DateTimeMap", DateTime, ColumnKind.INTEGRAL, _int_elem)
BinaryMap = _make_map_type("BinaryMap", Binary, ColumnKind.BINARY, _bool_elem,
                           bases=(Categorical,))
MultiPickListMap = _make_map_type("MultiPickListMap", MultiPickList,
                                  ColumnKind.TEXT_SET, _set_elem,
                                  bases=(Categorical, MultiResponse))
GeolocationMap = _make_map_type("GeolocationMap", Geolocation, ColumnKind.GEO,
                                _geo_elem, bases=(Location,))


class Prediction(RealMap):  # type: ignore[misc, valid-type]
    """Model output: RealMap with reserved keys (Maps.scala ``Prediction``).

    Keys: ``prediction`` (required), ``rawPrediction_<i>``, ``probability_<i>``.
    Columnar layout is a fixed struct-of-arrays (``ColumnKind.PREDICTION``):
    ``prediction: f32[n]``, ``rawPrediction: f32[n, k]``, ``probability: f32[n, k]``.
    """

    column_kind = ColumnKind.PREDICTION

    PREDICTION_KEY = "prediction"
    RAW_PREFIX = "rawPrediction_"
    PROB_PREFIX = "probability_"

    def __init__(self, value=None, *, prediction: Optional[float] = None,
                 raw_prediction: Optional[Sequence[float]] = None,
                 probability: Optional[Sequence[float]] = None):
        if value is None:
            value = {}
            if prediction is not None:
                value[self.PREDICTION_KEY] = float(prediction)
            for i, v in enumerate(raw_prediction or ()):
                value[f"{self.RAW_PREFIX}{i}"] = float(v)
            for i, v in enumerate(probability or ()):
                value[f"{self.PROB_PREFIX}{i}"] = float(v)
        super().__init__(value)
        if self.PREDICTION_KEY not in self._value:
            raise FeatureTypeError(
                "Prediction must contain a 'prediction' key "
                f"(got keys {sorted(self._value)})")
        for k in self._value:
            if k != self.PREDICTION_KEY and not (
                    k.startswith(self.RAW_PREFIX) or k.startswith(self.PROB_PREFIX)):
                raise FeatureTypeError(f"Invalid Prediction key {k!r}")

    @property
    def prediction(self) -> float:
        return self._value[self.PREDICTION_KEY]

    @property
    def raw_prediction(self) -> List[float]:
        return self._sorted_prefixed(self.RAW_PREFIX)

    @property
    def probability(self) -> List[float]:
        return self._sorted_prefixed(self.PROB_PREFIX)

    def _sorted_prefixed(self, prefix: str) -> List[float]:
        items = [(int(k[len(prefix):]), v) for k, v in self._value.items()
                 if k.startswith(prefix)]
        return [v for _, v in sorted(items)]


# ---------------------------------------------------------------------------
# Registry (FeatureType.scala:265-324)
# ---------------------------------------------------------------------------

FEATURE_TYPE_REGISTRY: Dict[str, Type[FeatureType]] = {
    cls.__name__: cls for cls in [
        # Vector
        OPVector,
        # Lists
        TextList, DateList, DateTimeList, Geolocation,
        # Maps
        Base64Map, BinaryMap, ComboBoxMap, CurrencyMap, DateMap, DateTimeMap,
        EmailMap, IDMap, IntegralMap, MultiPickListMap, PercentMap, PhoneMap,
        PickListMap, RealMap, TextAreaMap, TextMap, URLMap, CountryMap,
        StateMap, CityMap, PostalCodeMap, StreetMap, GeolocationMap, Prediction,
        # Numerics
        Binary, Currency, Date, DateTime, Integral, Percent, Real, RealNN,
        # Sets
        MultiPickList,
        # Text
        Base64, ComboBox, Email, ID, Phone, PickList, Text, TextArea, URL,
        Country, State, City, PostalCode, Street,
    ]
}

assert len(FEATURE_TYPE_REGISTRY) == 52, len(FEATURE_TYPE_REGISTRY)


def feature_type_by_name(name: str) -> Type[FeatureType]:
    try:
        return FEATURE_TYPE_REGISTRY[name]
    except KeyError:
        raise FeatureTypeError(f"Unknown feature type {name!r}") from None


def is_subtype(a: Type[FeatureType], b: Type[FeatureType]) -> bool:
    """True when feature type ``a`` can be used where ``b`` is expected."""
    return issubclass(a, b)
