from .base import (Estimator, FittedModel, FixedArity, InputSpec,  # noqa: F401
                   LambdaTransformer, OpPipelineStage, Transformer, VarArity,
                   AllowLabelAsInput, STAGE_REGISTRY, register_stage)
from .generator import FeatureGeneratorStage  # noqa: F401
