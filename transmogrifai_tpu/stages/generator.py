"""FeatureGeneratorStage — origin of raw features.

Mirrors ``features/.../stages/FeatureGeneratorStage.scala:45-108``: holds the
record → value ``extract_fn``, an optional monoid aggregator and event-time
window used by aggregating readers, and produces the raw Feature node.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Type

import numpy as np

from ..columns import Column, ColumnStore, column_from_values
from ..features import Feature
from ..types.feature_types import FeatureType
from .base import InputSpec, OpPipelineStage, Transformer, register_stage


class _NoInputs(InputSpec):
    def check(self, features):
        if features:
            raise TypeError("FeatureGeneratorStage takes no input features")


@register_stage
class FeatureGeneratorStage(Transformer):
    """Origin stage: extracts one raw feature from source records."""

    operation_name = "gen"
    is_raw_generator = True

    def __init__(self, name: str, ftype: Type[FeatureType],
                 extract_fn: Optional[Callable[[Any], Any]] = None,
                 is_response: bool = False,
                 aggregator=None, window_ms: Optional[int] = None,
                 extract_source: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.name = name
        self.ftype = ftype
        self.extract_fn = extract_fn or (lambda rec: rec.get(name))
        self.is_response = is_response
        self.aggregator = aggregator
        self.window_ms = window_ms
        self.extract_source = extract_source
        self.output_type = ftype

    @property
    def input_spec(self) -> InputSpec:
        return _NoInputs()

    def get_output(self) -> Feature:
        if self._output_feature is None:
            self._output_feature = Feature(
                name=self.name, ftype=self.ftype,
                is_response=self.is_response, origin_stage=self, parents=())
        return self._output_feature

    def make_output_name(self) -> str:
        return self.name

    # raw features are materialized by readers; transform just passes through
    # an existing column (used when scoring a store that already has the data)
    def transform_columns(self, store: ColumnStore) -> Column:
        if self.name in store:
            return store[self.name]
        raise KeyError(f"Raw feature {self.name!r} missing from input data")

    def extract_column(self, records) -> Column:
        """Run extract_fn over host records → typed column (reader path,
        DataReader.generateDataFrame analog)."""
        key = getattr(self.extract_fn, "_column_key", None)
        cols = getattr(records, "columns", None)
        if key is not None and cols is not None and key in cols:
            # columnar batch (avro.ColumnarRecords, the pipeline's
            # vectorized decode): the field is already a numpy column —
            # build the typed column in one bulk pass, no dicts at all
            from ..columns import column_from_array
            col = column_from_array(self.ftype, cols[key])
            if col is not None:
                return col
        if key is not None and not isinstance(records, np.ndarray):
            # from_column extractors are plain rec.get(name): run the map
            # in C (methodcaller) — at 300k rows × ~8 features the Python
            # lambda frames alone were seconds of ingest time
            from operator import methodcaller
            values = list(map(methodcaller("get", key), records))
        else:
            values = [self.extract_fn(r) for r in records]
        return column_from_values(self.ftype, values)

    def get_params(self):
        p = super().get_params()
        # extract fns/aggregators are code, not data — like the reference,
        # only their source hint survives serialization
        p.pop("extract_fn", None)
        p.pop("aggregator", None)
        p["ftype"] = self.ftype  # class; model_io encodes as {"__ftype__"}
        return p
