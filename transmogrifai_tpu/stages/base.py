"""Stage abstraction — transformers, estimators, fitted models.

Re-designs ``OpPipelineStages.scala:56-553`` and the per-arity base classes
(``features/.../stages/base/{unary,binary,ternary,quaternary,sequence}``)
for columnar TPU execution:

* A stage's bulk operation is **columnar**: ``transform_columns`` consumes a
  :class:`~transmogrifai_tpu.columns.ColumnStore` and produces one output
  Column. There is no per-row UDF path on the hot loop — row fusion is
  achieved by the workflow runtime jitting each DAG layer's device work as
  one XLA computation.
* ``transform_row`` (the reference's ``OpTransformer.transformRow``,
  ``features/.../stages/package.scala``) survives as the slow row-level API
  for Spark-free local serving and contract tests; its default implementation
  routes through a 1-row ColumnStore so columnar and row semantics can never
  diverge.
* Estimators ``fit`` on a ColumnStore and return a fitted model transformer
  carrying device-ready state (numpy/jax arrays).
* Arity typing (``OpPipelineStage1..4, N``) becomes an ``input_spec`` the
  base class checks in ``set_input`` (the reference's ``transformSchema``
  type check, OpPipelineStages.scala:113-142).
"""
from __future__ import annotations

import functools
import inspect
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple, Type,
                    Union)

import numpy as np

from ..columns import Column, ColumnStore, column_from_values
from ..features import Feature
from ..types.feature_types import FeatureType, OPVector, Prediction, RealNN
from ..utils import uid as uid_mod

__all__ = [
    "InputSpec", "FixedArity", "VarArity", "OpPipelineStage", "Transformer",
    "Estimator", "FittedModel", "LambdaTransformer", "AllowLabelAsInput",
    "STAGE_REGISTRY", "register_stage",
]


STAGE_REGISTRY: Dict[str, type] = {}


def register_stage(cls):
    """Register a stage class for serialization lookup."""
    STAGE_REGISTRY[cls.__name__] = cls
    return cls


class InputSpec:
    """Input arity/type contract for a stage."""

    def check(self, features: Sequence[Feature]) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        """Human-readable form of the declared contract — the static
        checker (lint.py TMG101) quotes it next to the actual wired
        feature types so a mis-typed edge names both sides."""
        return "?"


class FixedArity(InputSpec):
    """Exactly len(types) inputs, positionally typed (OpPipelineStage1..4)."""

    def __init__(self, *types: Type[FeatureType]):
        self.types = types

    def describe(self) -> str:
        return "(" + ", ".join(t.__name__ for t in self.types) + ")"

    def check(self, features: Sequence[Feature]) -> None:
        if len(features) != len(self.types):
            raise TypeError(
                f"Expected {len(self.types)} input features, got {len(features)}")
        for i, (f, t) in enumerate(zip(features, self.types)):
            if not issubclass(f.ftype, t):
                raise TypeError(
                    f"Input {i} ({f.name!r}) has type {f.ftype.__name__}, "
                    f"expected {t.__name__}")


class VarArity(InputSpec):
    """N same-typed inputs, optionally with fixed positional heads
    (SequenceEstimator / BinarySequenceEstimator)."""

    def __init__(self, seq_type: Type[FeatureType],
                 head_types: Sequence[Type[FeatureType]] = (), min_seq: int = 1):
        self.seq_type = seq_type
        self.head_types = tuple(head_types)
        self.min_seq = min_seq

    def describe(self) -> str:
        seq = (self.seq_type.__name__ if isinstance(self.seq_type, type)
               else "|".join(t.__name__ for t in self.seq_type))
        head = ", ".join(t.__name__ for t in self.head_types)
        return f"({head}{', ' if head else ''}{seq}*)"

    def check(self, features: Sequence[Feature]) -> None:
        n_head = len(self.head_types)
        if len(features) < n_head + self.min_seq:
            raise TypeError(
                f"Expected at least {n_head + self.min_seq} inputs, "
                f"got {len(features)}")
        for i, t in enumerate(self.head_types):
            if not issubclass(features[i].ftype, t):
                raise TypeError(
                    f"Input {i} ({features[i].name!r}) has type "
                    f"{features[i].ftype.__name__}, expected {t.__name__}")
        for f in features[n_head:]:
            if not issubclass(f.ftype, self.seq_type):
                expected = (self.seq_type.__name__
                            if isinstance(self.seq_type, type) else
                            "|".join(t.__name__ for t in self.seq_type))
                raise TypeError(
                    f"Sequence input {f.name!r} has type {f.ftype.__name__}, "
                    f"expected {expected}")


class AllowLabelAsInput:
    """Marker mixin: stage may consume response features without its output
    becoming a response (OpPipelineStages.scala:204-211)."""


class OpPipelineStage:
    """Base pipeline stage: named operation over input features.

    Subclass ``__init__`` kwargs are captured automatically for JSON
    round-trip (the reference's ctor-args serialization,
    ``OpPipelineStageWriter.scala``).
    """

    #: override in subclasses
    operation_name: str = "stage"
    output_type: Type[FeatureType] = OPVector

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        orig = cls.__init__
        if getattr(orig, "_captures_params", False):
            return
        try:
            sig = inspect.signature(orig)
        except (TypeError, ValueError):  # pragma: no cover
            return

        @functools.wraps(orig)
        def wrapper(self, *args, **kwargs):
            if not hasattr(self, "_ctor_params"):
                try:
                    bound = sig.bind(self, *args, **kwargs)
                    bound.apply_defaults()
                    self._ctor_params = {
                        k: v for k, v in bound.arguments.items()
                        if k not in ("self",) and not k.startswith("_")
                        and k != "kwargs"}
                    self._ctor_params.update(bound.arguments.get("kwargs") or {})
                except TypeError:
                    self._ctor_params = {}
            orig(self, *args, **kwargs)

        wrapper._captures_params = True
        cls.__init__ = wrapper

    def __init__(self, uid: Optional[str] = None):
        self.uid = uid or uid_mod.make_uid(type(self))
        self.input_features: Tuple[Feature, ...] = ()
        self._output_feature: Optional[Feature] = None

    # -- contract ----------------------------------------------------------
    @property
    def input_spec(self) -> InputSpec:
        raise NotImplementedError

    def stage_name(self) -> str:
        return f"{type(self).__name__}_{self.operation_name}"

    # -- wiring ------------------------------------------------------------
    def set_input(self, *features: Feature) -> "OpPipelineStage":
        self.input_spec.check(features)
        for f in features:
            if f.is_response and not isinstance(self, AllowLabelAsInput) \
                    and not all(x.is_response for x in features):
                raise TypeError(
                    f"Stage {self.stage_name()} mixes response feature "
                    f"{f.name!r} with predictors; only AllowLabelAsInput "
                    "stages may do that (label-leakage gate)")
        self.input_features = tuple(features)
        self._output_feature = None
        return self

    def get_output(self) -> Feature:
        if self._output_feature is None:
            if not self.input_features:
                raise ValueError(f"{self.stage_name()}: inputs not set")
            self._output_feature = Feature(
                name=self.make_output_name(),
                ftype=self.output_type,
                is_response=all(f.is_response for f in self.input_features),
                origin_stage=self,
                parents=self.input_features)
        return self._output_feature

    def make_output_name(self) -> str:
        ins = "-".join(f.name for f in self.input_features[:4])
        _, uid_hex = uid_mod.parse_uid(self.uid)
        return f"{ins}_{self.operation_name}_{uid_hex[-6:]}"

    @property
    def output_name(self) -> str:
        return self.get_output().name

    # -- params / serialization -------------------------------------------
    def get_params(self) -> Dict[str, Any]:
        return dict(getattr(self, "_ctor_params", {}))

    def set_params(self, **params) -> "OpPipelineStage":
        """Reflectively update ctor params + matching attributes
        (OpWorkflow.setStageParameters analog)."""
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            self._ctor_params[k] = v
        return self

    def copy(self) -> "OpPipelineStage":
        """Fresh instance with same ctor params + uid (ReflectionUtils.copy)."""
        params = self.get_params()
        params["uid"] = self.uid
        new = type(self)(**params)
        if self.input_features:
            new.input_features = self.input_features
        return new

    def __repr__(self) -> str:
        return f"{type(self).__name__}(uid={self.uid})"


class Transformer(OpPipelineStage):
    """Stage whose output is a pure function of its inputs."""

    def transform_columns(self, store: ColumnStore) -> Column:
        """Bulk columnar transform: compute the output column."""
        raise NotImplementedError

    def transform(self, store: ColumnStore) -> ColumnStore:
        return store.with_column(self.output_name, self.transform_columns(store))

    # -- row-level path (local serving / contract tests) -------------------
    def transform_row(self, row: Dict[str, Any]) -> Any:
        """Compute the output value for one row dict {feature name: raw value}.

        Default routes through a 1-row ColumnStore so the row path can never
        diverge from the columnar path. Stages may override for speed.
        """
        cols = {}
        for f in self.input_features:
            cols[f.name] = column_from_values(f.ftype, [row.get(f.name)])
        out = self.transform_columns(ColumnStore(cols, 1))
        return out.get_raw(0)

    def transform_key_value(self, get: Callable[[str], Any]) -> Any:
        row = {f.name: get(f.name) for f in self.input_features}
        return self.transform_row(row)


class FittedModel(Transformer):
    """A fitted estimator: transformer + serializable numeric state.

    Shares the estimator's uid and output feature so the workflow swaps it
    into the DAG in place of the estimator after fitting.
    """

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.parent_estimator_uid: Optional[str] = None

    def get_model_state(self) -> Dict[str, Any]:
        """JSON-able dict; numpy arrays allowed (stored via npz)."""
        raise NotImplementedError

    @classmethod
    def from_model_state(cls, state: Dict[str, Any], **ctor) -> "FittedModel":
        raise NotImplementedError

    def has_test_eval(self) -> bool:
        """True for models that evaluate on holdout during workflow fit
        (HasTestEval, used by ModelSelector)."""
        return False

    def evaluate_model(self, test: ColumnStore) -> None:  # pragma: no cover
        pass


class Estimator(OpPipelineStage):
    """Stage that must be fit on data, producing a :class:`FittedModel`.

    Estimators may additionally opt into the layer-wide fused
    fit-statistics engine (``fitstats.py``, the SequenceAggregators
    analog) by overriding :meth:`stat_requests` and
    :meth:`fit_columns_from_stats`: the workflow then computes every
    opted-in estimator's sufficient statistics for a DAG layer in ONE
    pass over the train store and hands each stage its finalized stats,
    instead of every ``fit_columns`` re-scanning the full store. The
    plain ``fit_columns`` stays as the sequential fallback and the two
    paths must produce identical models.
    """

    def fit(self, store: ColumnStore,
            stats: Optional[Any] = None) -> FittedModel:
        if stats is None:
            model = self.fit_columns(store)
        else:
            model = self.fit_columns_from_stats(store, stats)
        model.uid = self.uid
        model.parent_estimator_uid = self.uid
        model.input_features = self.input_features
        model._output_feature = self.get_output()
        if not hasattr(model, "_ctor_params"):
            model._ctor_params = {}
        return model

    def fit_columns(self, store: ColumnStore) -> FittedModel:
        raise NotImplementedError

    # -- fused fit-statistics protocol (fitstats.py) -----------------------
    def stat_requests(self, store: ColumnStore):
        """Sufficient statistics this estimator needs to fit, as a list
        of ``fitstats.StatRequest`` — or None to stay on the sequential
        ``fit_columns`` path (the default). An EMPTY list is a valid
        opt-in meaning "no data needed" (constant-fill vectorizers)."""
        return None

    def fit_columns_from_stats(self, store: ColumnStore,
                               stats: Any) -> FittedModel:
        """Finalize a fitted model from the layer pass's stats — must
        produce the identical model ``fit_columns`` would."""
        raise NotImplementedError(
            f"{type(self).__name__} declares stat_requests but not "
            "fit_columns_from_stats")


class LambdaTransformer(Transformer):
    """Transformer from a columnar function — the workhorse for math ops,
    aliasing, and the DSL's cheap derived features.

    ``fn(*input_columns, store) -> Column`` or ``fn(*input_columns) -> Column``.
    Not JSON-serializable unless ``fn_name`` refers to a registered function.
    """

    def __init__(self, operation_name: str,
                 fn: Callable[..., Column],
                 input_types: Sequence[Type[FeatureType]],
                 output_type: Type[FeatureType],
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.operation_name = operation_name
        self.fn = fn
        self._input_types = tuple(input_types)
        self.output_type = output_type

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(*self._input_types)

    def transform_columns(self, store: ColumnStore) -> Column:
        cols = [store[f.name] for f in self.input_features]
        return self.fn(*cols)
