"""ASCII table pretty-printer (utils/.../Table.scala analog) — used by
summaryPretty-style reports."""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

__all__ = ["Table"]


class Table:
    """Render rows of cells as a boxed ASCII table with a title row."""

    def __init__(self, columns: Sequence[str],
                 rows: Sequence[Sequence[Any]],
                 name: Optional[str] = None):
        if not columns:
            raise ValueError("Table needs at least one column")
        for r in rows:
            if len(r) != len(columns):
                raise ValueError(
                    f"Row width {len(r)} != column count {len(columns)}")
        self.columns = [str(c) for c in columns]
        self.rows = [[_fmt(c) for c in r] for r in rows]
        self.name = name

    def render(self) -> str:
        widths = [len(c) for c in self.columns]
        for r in self.rows:
            for i, cell in enumerate(r):
                widths[i] = max(widths[i], len(cell))

        def line(ch: str = "-") -> str:
            return "+" + "+".join(ch * (w + 2) for w in widths) + "+"

        def row(cells: List[str]) -> str:
            return "| " + " | ".join(
                c.ljust(w) for c, w in zip(cells, widths)) + " |"

        out: List[str] = []
        if self.name:
            total = sum(widths) + 3 * len(widths) + 1
            out.append(line("="))
            out.append("|" + self.name.center(total - 2) + "|")
        out.append(line("="))
        out.append(row(self.columns))
        out.append(line("="))
        for r in self.rows:
            out.append(row(r))
        out.append(line("-"))
        return "\n".join(out)

    def __str__(self) -> str:
        return self.render()


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return "" if v is None else str(v)
