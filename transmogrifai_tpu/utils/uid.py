"""Sequential UID generation, mirroring ``utils/.../UID.scala:41-95``.

UIDs look like ``ClassName_000000000001`` — sequential per process with a
global counter, resettable for deterministic tests and model round-trips
(the reference's ``UID.reset`` is load-bearing for warm-start by uid).
"""
from __future__ import annotations

import itertools
import re
import threading
from typing import Optional, Tuple

_COUNTER = itertools.count(1)
_LOCK = threading.Lock()
_UID_RE = re.compile(r"^(\w+)_(\w{12})$")


def make_uid(cls_or_name) -> str:
    name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
    with _LOCK:
        count = next(_COUNTER)
    return f"{name}_{count:012x}"


def reset(start: int = 1) -> None:
    """Reset the counter — deterministic uids for tests/golden models."""
    global _COUNTER
    with _LOCK:
        _COUNTER = itertools.count(start)


def parse_uid(uid: str) -> Tuple[str, str]:
    """Split ``Name_%012x`` into (name, hex) or raise ValueError."""
    m = _UID_RE.match(uid)
    if not m:
        raise ValueError(f"Invalid uid {uid!r}")
    return m.group(1), m.group(2)


def uid_prefix(uid: str) -> Optional[str]:
    try:
        return parse_uid(uid)[0]
    except ValueError:
        return None
