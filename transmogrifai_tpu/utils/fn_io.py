"""Function (de)serialization for lambda-carrying stages.

The reference persists ``FeatureBuilder.extract``/DSL lambdas as compiled
JVM classes reinstantiated reflectively; Python has no such luxury, so:

* module-level functions round-trip by qualified name (robust path);
* lambdas/local functions round-trip by marshaled code object + closure cell
  values (works for the closure-free or simple-valued closures the DSL
  produces; anything else raises at save time, not load time).

Loading marshaled code executes it — the same trust model as unpickling a
model file. Only load models you trust.
"""
from __future__ import annotations

import base64
import importlib
import marshal
import types
from typing import Any, Callable, Dict

__all__ = ["encode_fn", "decode_fn", "FunctionSerializationError"]


class FunctionSerializationError(ValueError):
    pass


#: module-level names available to deserialized lambdas (decode_fn globals)
_LAMBDA_MODULES = ("math", "re", "json", "datetime")


def _check_names(code, allowed: set, qualname: str) -> None:
    """Save-time check: every global the code loads must exist in the
    decode-side globals, so failures surface at save, not at scoring.

    Uses dis to look only at LOAD_GLOBAL targets — co_names also holds
    attribute names, which are not globals."""
    import builtins
    import dis
    for ins in dis.get_instructions(code):
        if ins.opname == "LOAD_GLOBAL":
            name = ins.argval
            if name in allowed or hasattr(builtins, name):
                continue
            raise FunctionSerializationError(
                f"Lambda {qualname or '<lambda>'} references global "
                f"{name!r}, which won't exist after loading (available: np, "
                f"{', '.join(_LAMBDA_MODULES)}, builtins). Use a "
                "module-level function instead.")
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            _check_names(const, allowed, qualname)


def encode_fn(fn: Callable) -> Dict[str, Any]:
    import numpy as np
    if isinstance(fn, np.ufunc):
        return {"kind": "named", "module": "numpy", "qualname": fn.__name__}
    if not hasattr(fn, "__code__"):
        raise FunctionSerializationError(
            f"Cannot serialize callable {fn!r} (no __code__); use a "
            "module-level function")
    mod = getattr(fn, "__module__", None)
    qualname = getattr(fn, "__qualname__", "")
    if mod and qualname and "<lambda>" not in qualname and "<locals>" not in qualname:
        return {"kind": "named", "module": mod, "qualname": qualname}
    closure = ()
    if fn.__closure__:
        try:
            closure = tuple(c.cell_contents for c in fn.__closure__)
            marshal.dumps(closure)
        except (ValueError, TypeError) as e:
            raise FunctionSerializationError(
                f"Cannot serialize closure of {qualname or fn}: {e}. "
                "Use a module-level function instead.") from e
    allowed = {"np", *_LAMBDA_MODULES,
               *(fn.__code__.co_varnames), *(fn.__code__.co_freevars)}
    _check_names(fn.__code__, allowed, qualname)
    return {
        "kind": "code",
        "code": base64.b64encode(marshal.dumps(fn.__code__)).decode("ascii"),
        "defaults": list(fn.__defaults__ or ()),
        "closure": list(closure),
        "name": fn.__name__,
    }


def decode_fn(spec: Dict[str, Any]) -> Callable:
    if spec["kind"] == "named":
        obj: Any = importlib.import_module(spec["module"])
        for part in spec["qualname"].split("."):
            obj = getattr(obj, part)
        return obj
    code = marshal.loads(base64.b64decode(spec["code"]))
    import builtins
    import numpy as np
    globs = {"__builtins__": builtins, "np": np}
    for m in _LAMBDA_MODULES:
        globs[m] = importlib.import_module(m)
    closure = tuple(types.CellType(v) for v in spec["closure"])
    fn = types.FunctionType(code, globs, spec["name"],
                            tuple(spec["defaults"]), closure or None)
    return fn
