"""Build/version stamping (utils/.../version/VersionInfo.scala analog).

The reference stamps gradle build properties; here the framework version
plus the git commit of the working tree (when available) identify what
produced a saved model — recorded into model.json by model_io.
"""
from __future__ import annotations

import os
import subprocess
from typing import Dict, Optional

__all__ = ["version_info"]

_cache: Optional[Dict[str, str]] = None


def _git_commit() -> Optional[str]:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", repo, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.SubprocessError):
        return None


def version_info() -> Dict[str, str]:
    global _cache
    if _cache is None:
        import jax

        from .. import __version__
        _cache = {"version": __version__,
                  "jax": jax.__version__}
        commit = _git_commit()
        if commit:
            _cache["gitCommit"] = commit
    return dict(_cache)
