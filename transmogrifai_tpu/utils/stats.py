"""Statistics utilities — the OpStatistics analog.

Parity: ``utils/src/main/scala/com/salesforce/op/utils/stats/OpStatistics.scala``
(:71-346): contingency statistics (Cramér's V, pointwise mutual
information, mutual information, association-rule support/confidence) and
streaming label correlations, re-designed as fused XLA reductions over
columnar arrays. The SanityChecker composes these (as the reference's does
``OpStatistics.contingencyStats``); they are exported here as standalone
utilities so user code can run the same statistics outside a workflow.

Device kernels (``moments``, ``contingency``) are jitted; the small
contingency-table post-processing is plain numpy on host (tables are
[n_classes, n_categories] — tiny).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["moments", "contingency", "cramers_v_stats", "pmi_mutual_info",
           "average_ranks", "spearman_with_label"]


@functools.partial(jax.jit, static_argnames=("label_corr_only",))
def moments(X, y, label_corr_only: bool = False):
    """One fused pass over [n, d] features + [n] label: means, variances,
    per-column label correlation, optional full correlation matrix, and
    column min/max — ``Statistics.colStats`` + ``corr`` in one program
    (SanityChecker.scala:575,634-638)."""
    n = X.shape[0]
    Z = jnp.concatenate([X, y[:, None]], axis=1)
    mean = Z.mean(axis=0)
    Zc = Z - mean
    cov = Zc.T @ Zc / jnp.maximum(n - 1, 1)
    var = jnp.diagonal(cov)
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    denom = jnp.maximum(jnp.outer(std, std), 1e-30)
    if label_corr_only:
        corr_label = cov[:-1, -1] / denom[:-1, -1]
        corr = None
    else:
        corr = cov / denom
        corr_label = corr[:-1, -1]
    zmin = Z.min(axis=0)
    zmax = Z.max(axis=0)
    return mean, var, corr_label, corr, zmin, zmax


@jax.jit
def contingency(Y_onehot, Xg):
    """Contingency counts [n_classes, n_categories] as one matmul — the
    reference's per-key ``reduceByKey`` sweep (SanityChecker.scala:420-516)
    collapsed onto the MXU."""
    return Y_onehot.T @ Xg


def moments_host(X: np.ndarray, y: np.ndarray,
                 label_corr_only: bool = False):
    """Host-BLAS twin of :func:`moments` for slow-link deployments: on a
    network-tunnelled TPU the [n, d] upload costs more than the gram
    itself (a 270k×550 f32 matrix is ~0.6 GB — ~30 s at tunnel rates for
    a ~160 GFLOP sgemm the host does in seconds). Same math, f32 gram
    with f64 mean subtraction; callers gate on the measured link
    bandwidth (the fusion gate's device_roundtrip_mbps)."""
    n = X.shape[0]
    Z = np.concatenate(
        [np.asarray(X, dtype=np.float32),
         np.asarray(y, dtype=np.float32)[:, None]], axis=1)
    mean = Z.mean(axis=0, dtype=np.float64)
    Zc = Z - mean.astype(np.float32)
    cov = (Zc.T @ Zc).astype(np.float64) / max(n - 1, 1)
    var = np.diagonal(cov)
    std = np.sqrt(np.maximum(var, 0.0))
    denom = np.maximum(np.outer(std, std), 1e-30)
    if label_corr_only:
        corr_label = cov[:-1, -1] / denom[:-1, -1]
        corr = None
    else:
        corr = cov / denom
        corr_label = corr[:-1, -1]
    return (mean, var, corr_label, corr, Z.min(axis=0), Z.max(axis=0))


def cramers_v_stats(cont: np.ndarray
                    ) -> Tuple[float, np.ndarray, np.ndarray]:
    """Cramér's V (bias-uncorrected, MLlib chi2 semantics) + per-category
    support and max association-rule confidence
    (OpStatistics.scala:71-346)."""
    total = cont.sum()
    if total <= 0:
        return 0.0, np.zeros(cont.shape[1]), np.zeros(cont.shape[1])
    row = cont.sum(axis=1, keepdims=True)
    col = cont.sum(axis=0, keepdims=True)
    expected = row @ col / total
    with np.errstate(divide="ignore", invalid="ignore"):
        chi2 = np.where(expected > 0,
                        (cont - expected) ** 2 / expected, 0.0).sum()
    r, c = cont.shape
    dof_dim = min(r - 1, c - 1)
    v = float(np.sqrt(chi2 / (total * dof_dim))) if dof_dim > 0 else 0.0
    support = (col / total).ravel()
    with np.errstate(divide="ignore", invalid="ignore"):
        confidence = np.where(col > 0,
                              cont.max(axis=0) / col.ravel(), 0.0).ravel()
    return v, support, confidence


def pmi_mutual_info(cont: np.ndarray) -> Tuple[np.ndarray, float]:
    """Pointwise mutual information per (class, category) cell and total
    mutual information, log base 2 (OpStatistics.contingencyStats :300)."""
    total = cont.sum()
    if total <= 0:
        return np.zeros_like(cont), 0.0
    p = cont / total
    pr = p.sum(axis=1, keepdims=True)
    pc = p.sum(axis=0, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        pmi = np.where(p > 0, np.log2(p / np.maximum(pr @ pc, 1e-300)), 0.0)
    mi = float((p * pmi).sum())
    return pmi, mi


def average_ranks(v: np.ndarray) -> np.ndarray:
    """Average ranks with ties (scipy.stats.rankdata 'average' semantics,
    what MLlib's Spearman uses) — one unique pass per column."""
    _uniq, inv, counts = np.unique(v, return_inverse=True,
                                   return_counts=True)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    avg = starts + (counts - 1) / 2.0 + 1.0     # 1-based average rank
    return avg[inv]


def spearman_with_label(X: np.ndarray, y: np.ndarray,
                        label_corr_only: bool = True,
                        host: bool = False):
    """Spearman rank correlation of each column with the label: ranks are
    built per column on host (ties averaged), then the Pearson moments of
    the ranks run on device (``Statistics.corr(..., "spearman")``
    semantics, SanityChecker.scala:634-638). Returns device arrays
    (corr_label, corr) — fetch lazily/batched with ``jax.device_get``.
    ``host=True`` runs the rank gram through :func:`moments_host`
    instead (the SanityChecker's slow-link gate applies here too — the
    rank matrix is as big as X)."""
    Xn = np.asarray(X)
    dtype = (Xn.dtype if np.issubdtype(Xn.dtype, np.floating)
             else np.float64)
    Xr = np.empty_like(Xn, dtype=dtype)
    for j in range(Xn.shape[1]):
        Xr[:, j] = average_ranks(Xn[:, j])
    yr = average_ranks(np.asarray(y)).astype(dtype)
    if host:
        _mean, _var, corr_label, corr, _zmin, _zmax = moments_host(
            Xr, yr, label_corr_only=label_corr_only)
        return corr_label, corr
    _mean, _var, corr_label, corr, _zmin, _zmax = moments(
        jnp.asarray(Xr), jnp.asarray(yr), label_corr_only=label_corr_only)
    return corr_label, corr
