"""Model-based POS / NER / sentence-boundary taggers.

The reference wires OpenNLP's pretrained maxent models through
``OpenNLPNameEntityTagger`` / ``OpenNLPSentenceSplitter`` /
``OpenNLPAnalyzer`` (``core/.../utils/text/OpenNLPNameEntityTagger.scala:1``,
``OpenNLPSentenceSplitter.scala:1``) with the binaries vendored as
resources (``models/README.md:1-5``). The TPU build vendors its own
learned weights the same way: small **averaged-perceptron** taggers
(the classic Collins 2002 structure — also what NLTK's default English
POS tagger uses) trained OFFLINE by ``tools/train_taggers.py`` on a
synthesized annotated corpus (template grammar over curated name /
organization / location / vocabulary lexicons — this image has no
network egress, so no external treebank; the trainer and its corpus
generator are in-repo and reproducible). Weights live under
``transmogrifai_tpu/resources/taggers/*.json.gz``.

Inference is host-side (strings never reach the device raw — SURVEY
§2.9 keeps OpenNLP-class work on CPU feeding device arrays).
"""
from __future__ import annotations

import gzip
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["AveragedPerceptron", "POSTagger", "NERTagger",
           "SentenceSplitter", "load_tagger", "resource_dir"]


def resource_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "resources", "taggers")


class AveragedPerceptron:
    """Sparse multiclass averaged perceptron.

    ``weights``: feature → {class: weight}. Training keeps per-weight
    accumulators so the final weights are the average over all updates
    (Collins 2002) — the variance reduction that makes a plain
    perceptron competitive on tagging tasks.
    """

    def __init__(self, weights: Optional[Dict[str, Dict[str, float]]] = None,
                 classes: Optional[Sequence[str]] = None):
        self.weights: Dict[str, Dict[str, float]] = weights or {}
        self.classes: List[str] = list(classes or [])
        # training state
        self._totals: Dict[Tuple[str, str], float] = {}
        self._tstamps: Dict[Tuple[str, str], int] = {}
        self._i = 0

    def score(self, features: Iterable[str]) -> Dict[str, float]:
        scores: Dict[str, float] = {}
        for f in features:
            w = self.weights.get(f)
            if w is None:
                continue
            for c, v in w.items():
                scores[c] = scores.get(c, 0.0) + v
        return scores

    def predict(self, features: Sequence[str]) -> str:
        scores = self.score(features)
        if not scores:
            return self.classes[0]
        # deterministic tie-break by class name
        return max(self.classes, key=lambda c: (scores.get(c, 0.0), c))

    # -- training ---------------------------------------------------------
    def update(self, truth: str, guess: str,
               features: Sequence[str]) -> None:
        self._i += 1
        if truth == guess:
            return

        def upd(f: str, c: str, v: float) -> None:
            key = (f, c)
            w = self.weights.setdefault(f, {})
            self._totals[key] = self._totals.get(key, 0.0) \
                + (self._i - self._tstamps.get(key, 0)) * w.get(c, 0.0)
            self._tstamps[key] = self._i
            w[c] = w.get(c, 0.0) + v
        for f in features:
            upd(f, truth, 1.0)
            upd(f, guess, -1.0)

    def average(self) -> None:
        for f, w in self.weights.items():
            for c in list(w):
                key = (f, c)
                total = self._totals.get(key, 0.0) \
                    + (self._i - self._tstamps.get(key, 0)) * w[c]
                avg = total / max(self._i, 1)
                if abs(avg) > 1e-6:
                    w[c] = round(avg, 5)
                else:
                    del w[c]
        self.weights = {f: w for f, w in self.weights.items() if w}

    # -- persistence ------------------------------------------------------
    def save(self, path: str, extra: Optional[dict] = None) -> None:
        doc = {"classes": self.classes, "weights": self.weights,
               **(extra or {})}
        with gzip.open(path, "wt", encoding="utf-8") as fh:
            json.dump(doc, fh)

    @classmethod
    def load(cls, path: str) -> Tuple["AveragedPerceptron", dict]:
        with gzip.open(path, "rt", encoding="utf-8") as fh:
            doc = json.load(fh)
        return cls(doc["weights"], doc["classes"]), doc


def _shape(w: str) -> str:
    if w.isdigit():
        return "d"
    if w.isupper():
        return "A"
    if w[:1].isupper():
        return "Aa"
    if any(ch.isdigit() for ch in w):
        return "ad"
    return "a"


class POSTagger:
    """Greedy left-to-right POS tagging (PTB-style coarse tags)."""

    START = ["-S2-", "-S1-"]

    def __init__(self, model: AveragedPerceptron):
        self.model = model

    @staticmethod
    def features(tokens: Sequence[str], i: int,
                 prev: str, prev2: str) -> List[str]:
        w = tokens[i]
        lw = w.lower()
        p1 = tokens[i - 1].lower() if i > 0 else "-S1-"
        n1 = tokens[i + 1].lower() if i + 1 < len(tokens) else "-E1-"
        return [
            "b", f"w={lw}", f"sfx3={lw[-3:]}", f"sfx2={lw[-2:]}",
            f"sh={_shape(w)}", f"p1={p1}", f"n1={n1}",
            f"t1={prev}", f"t2={prev2}", f"t1w={prev}+{lw}",
            f"i0={i == 0}",
        ]

    def tag(self, tokens: Sequence[str]) -> List[str]:
        prev, prev2 = self.START[1], self.START[0]
        out: List[str] = []
        for i in range(len(tokens)):
            t = self.model.predict(self.features(tokens, i, prev, prev2))
            out.append(t)
            prev2, prev = prev, t
        return out


class NERTagger:
    """Greedy BIO tagging over PER/ORG/LOC with lexicon features."""

    def __init__(self, model: AveragedPerceptron, lexicons: dict):
        self.model = model
        self.lex = {k: set(v) for k, v in lexicons.items()}

    def features(self, tokens: Sequence[str], i: int,
                 prev_tag: str, pos: Optional[Sequence[str]] = None
                 ) -> List[str]:
        w = tokens[i]
        lw = w.lower()
        p1 = tokens[i - 1] if i > 0 else "-S1-"
        n1 = tokens[i + 1] if i + 1 < len(tokens) else "-E1-"
        feats = [
            "b", f"w={lw}", f"sh={_shape(w)}",
            f"p1={p1.lower()}", f"n1={n1.lower()}",
            f"p1sh={_shape(p1) if p1 != '-S1-' else 'S'}",
            f"n1sh={_shape(n1) if n1 != '-E1-' else 'E'}",
            f"t1={prev_tag}", f"i0={i == 0}",
            f"sfx2={lw[-2:]}",
        ]
        for name, vocab in self.lex.items():
            if lw in vocab:
                feats.append(f"lex={name}")
            if n1.lower() in vocab:
                feats.append(f"n1lex={name}")
        if pos is not None:
            feats.append(f"pos={pos[i]}")
        return feats

    def tag(self, tokens: Sequence[str],
            pos: Optional[Sequence[str]] = None) -> List[str]:
        prev = "O"
        out: List[str] = []
        for i in range(len(tokens)):
            t = self.model.predict(self.features(tokens, i, prev, pos))
            # BIO validity: an I- must continue a same-type span
            if t.startswith("I-") and not (
                    prev.endswith(t[2:]) and prev != "O"):
                t = "B-" + t[2:]
            out.append(t)
            prev = t
        return out

    @staticmethod
    def spans(tokens: Sequence[str], tags: Sequence[str]
              ) -> List[Tuple[str, str]]:
        """BIO tags → [(entity text, type)]."""
        out: List[Tuple[str, str]] = []
        cur: List[str] = []
        cur_t = ""
        for tok, tag in zip(tokens, tags):
            if tag.startswith("B-"):
                if cur:
                    out.append((" ".join(cur), cur_t))
                cur, cur_t = [tok], tag[2:]
            elif tag.startswith("I-") and cur:
                cur.append(tok)
            else:
                if cur:
                    out.append((" ".join(cur), cur_t))
                cur, cur_t = [], ""
        if cur:
            out.append((" ".join(cur), cur_t))
        return out


class SentenceSplitter:
    """Classify every [.?!] occurrence as boundary / not (abbreviations,
    initials, decimals stay inside their sentence)."""

    CANDIDATES = ".?!"

    def __init__(self, model: AveragedPerceptron):
        self.model = model

    @staticmethod
    def features(text: str, i: int) -> List[str]:
        ch = text[i]
        # fixed windows keep split() linear in document length — slicing
        # the whole prefix/suffix per candidate made long cells quadratic
        before = text[max(0, i - 40):i].rstrip()
        bparts = before.split()
        prev_tok = bparts[-1] if bparts else "-S-"
        after = text[i + 1:i + 41].lstrip()
        aparts = after.split()
        next_tok = aparts[0] if aparts else "-E-"
        prev_core = prev_tok.rstrip(".,;:!?\"')")
        return [
            "b", f"c={ch}",
            f"pt={prev_tok.lower()[-12:]}",
            f"ptlen1={len(prev_core) == 1}",
            f"ptcap={prev_core[:1].isupper()}",
            f"ptdig={prev_core.isdigit()}",
            f"ptdot={'.' in prev_tok[:-1]}",
            f"ntcap={next_tok[:1].isupper()}",
            f"ntdig={next_tok[:1].isdigit()}",
            f"ntlow={next_tok[:1].islower()}",
            f"nt={next_tok.lower()[:12]}",
            f"spc={i + 1 < len(text) and text[i + 1].isspace()}",
            f"eot={not after}",
        ]

    def split(self, text: str) -> List[str]:
        if not text:
            return []
        bounds: List[int] = []
        for i, ch in enumerate(text):
            if ch in self.CANDIDATES:
                # only positions followed by whitespace/EOT are candidates
                if i + 1 < len(text) and not text[i + 1].isspace():
                    continue
                if self.model.predict(self.features(text, i)) == "1":
                    bounds.append(i)
        out: List[str] = []
        start = 0
        for b in bounds:
            seg = text[start:b + 1].strip()
            if seg:
                out.append(seg)
            start = b + 1
        tail = text[start:].strip()
        if tail:
            out.append(tail)
        return out


_CACHE: Dict[str, object] = {}


def load_tagger(kind: str):
    """Load a vendored tagger ('pos' | 'ner' | 'sent'); None when the
    resource is absent (callers keep their documented fallback)."""
    if kind in _CACHE:
        return _CACHE[kind]
    path = os.path.join(resource_dir(), f"{kind}.json.gz")
    tagger = None
    if os.path.exists(path):
        model, doc = AveragedPerceptron.load(path)
        if kind == "pos":
            tagger = POSTagger(model)
        elif kind == "ner":
            tagger = NERTagger(model, doc.get("lexicons", {}))
        elif kind == "sent":
            tagger = SentenceSplitter(model)
    _CACHE[kind] = tagger
    return tagger
