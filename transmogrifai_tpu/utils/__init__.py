from . import uid  # noqa: F401
