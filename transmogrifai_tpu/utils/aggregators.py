"""Monoid aggregators + per-feature-type defaults.

Parity: ``features/.../aggregators/MonoidAggregatorDefaults.scala:41-120``
and the concrete monoids in ``aggregators/{Numerics,Text,Maps,Geolocation,
Lists,Sets}.scala``. An aggregator folds a key's event values into one
value for event-grouped readers (``AggregateReader``); ``aggregator_of``
returns the reference's default per feature type:

    numerics → sum (Binary → logical or, Date → max, Percent → mean)
    text     → concat (PickList → mode)
    lists    → concat, sets → union, vectors → elementwise sum
    geo      → weighted midpoint, maps → per-key union with the value
               type's monoid
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Type

import numpy as np

from ..types import feature_types as ft

__all__ = [
    "MonoidAggregator", "SumAggregator", "MeanAggregator", "MaxAggregator",
    "MinAggregator", "LogicalOrAggregator", "ModeAggregator",
    "ConcatTextAggregator", "ConcatListAggregator", "UnionSetAggregator",
    "CombineVectorAggregator", "GeolocationMidpointAggregator",
    "UnionMapAggregator", "FirstAggregator", "LastAggregator",
    "aggregator_of",
]


class MonoidAggregator:
    """fold(values) → one value; None/empty folds to None (the type's
    empty)."""

    def fold(self, values: Sequence[Any]):
        raise NotImplementedError


class _FnAggregator(MonoidAggregator):
    def __init__(self, fn: Callable[[List[Any]], Any], name: str):
        self._fn = fn
        self.name = name

    def fold(self, values: Sequence[Any]):
        vals = [v for v in values if v is not None]
        if not vals:
            return None
        return self._fn(vals)

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class SumAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(lambda v: float(np.sum(v)), "sum")


class MeanAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(lambda v: float(np.mean(v)), "mean")


class MaxAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(max, "max")


class MinAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(min, "min")


class LogicalOrAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(lambda v: bool(any(v)), "or")


class ModeAggregator(_FnAggregator):
    """Most frequent value, ties by value order (ModePickList)."""

    def __init__(self):
        def mode(vals):
            c = Counter(vals)
            return sorted(c.items(), key=lambda kv: (-kv[1], str(kv[0])))[0][0]
        super().__init__(mode, "mode")


class ConcatTextAggregator(_FnAggregator):
    def __init__(self, sep: str = " "):
        super().__init__(lambda v: sep.join(str(x) for x in v), "concat")


class ConcatListAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(lambda v: [x for lst in v for x in lst], "concat")


class UnionSetAggregator(_FnAggregator):
    def __init__(self):
        super().__init__(lambda v: set().union(*[set(s) for s in v]),
                         "union")


class CombineVectorAggregator(_FnAggregator):
    """Elementwise sum of dense vectors (CombineVector)."""

    def __init__(self):
        super().__init__(
            lambda v: np.sum([np.asarray(x, np.float64) for x in v], axis=0),
            "combine")


class GeolocationMidpointAggregator(MonoidAggregator):
    """Spherical midpoint of (lat, lon, accuracy) triples
    (Geolocation.scala:134 midpoint via 3-D unit vectors)."""

    def fold(self, values: Sequence[Any]):
        pts = [v for v in values if v is not None]
        if not pts:
            return None
        lat = np.radians([p[0] for p in pts])
        lon = np.radians([p[1] for p in pts])
        x = np.cos(lat) * np.cos(lon)
        y = np.cos(lat) * np.sin(lon)
        z = np.sin(lat)
        mx, my, mz = x.mean(), y.mean(), z.mean()
        out_lat = np.degrees(np.arctan2(mz, np.hypot(mx, my)))
        out_lon = np.degrees(np.arctan2(my, mx))
        acc = max(p[2] for p in pts if len(p) > 2) if any(
            len(p) > 2 for p in pts) else 0.0
        return (float(out_lat), float(out_lon), float(acc))


class UnionMapAggregator(MonoidAggregator):
    """Per-key union: values under the same key fold with ``value_agg``
    (UnionRealMap / UnionConcatTextMap family)."""

    def __init__(self, value_agg: Optional[MonoidAggregator] = None):
        self.value_agg = value_agg or SumAggregator()

    def fold(self, values: Sequence[Any]):
        maps = [m for m in values if m]
        if not maps:
            return None
        keys: Dict[str, List[Any]] = {}
        for m in maps:
            for k, v in m.items():
                keys.setdefault(k, []).append(v)
        return {k: self.value_agg.fold(vs) for k, vs in keys.items()}


class FirstAggregator(_FnAggregator):
    """First non-empty event value (TimeBasedAggregator first)."""

    def __init__(self):
        super().__init__(lambda v: v[0], "first")


class LastAggregator(_FnAggregator):
    """Last non-empty event value (TimeBasedAggregator last)."""

    def __init__(self):
        super().__init__(lambda v: v[-1], "last")


def aggregator_of(ftype: Type[ft.FeatureType]) -> MonoidAggregator:
    """Default monoid per feature type
    (MonoidAggregatorDefaults.aggregatorOf)."""
    text_concat = (ft.Text, ft.TextArea, ft.Email, ft.Base64, ft.Phone,
                   ft.ID, ft.URL, ft.ComboBox, ft.Country, ft.State,
                   ft.City, ft.PostalCode, ft.Street)
    if ftype is ft.Binary:
        return LogicalOrAggregator()
    if ftype in (ft.Date, ft.DateTime):
        return MaxAggregator()
    if ftype is ft.Percent:
        return MeanAggregator()
    if issubclass(ftype, ft.OPNumeric):
        return SumAggregator()
    if ftype is ft.PickList:
        return ModeAggregator()
    if ftype in text_concat or (issubclass(ftype, ft.Text)
                                and not issubclass(ftype, ft.PickList)):
        return ConcatTextAggregator()
    if ftype is ft.Geolocation:
        return GeolocationMidpointAggregator()
    if ftype is ft.OPVector:
        return CombineVectorAggregator()
    if ftype is ft.MultiPickList or ftype.__name__.endswith("Set"):
        return UnionSetAggregator()
    if ftype.__name__.endswith("List"):
        return ConcatListAggregator()
    if ftype.__name__.endswith("Map") or ftype is ft.Prediction:
        if ftype in (ft.TextMap, ft.EmailMap, ft.PhoneMap, ft.IDMap,
                     ft.URLMap, ft.ComboBoxMap, ft.PickListMap,
                     ft.TextAreaMap, ft.Base64Map, ft.CountryMap,
                     ft.StateMap, ft.CityMap, ft.PostalCodeMap,
                     ft.StreetMap):
            return UnionMapAggregator(ConcatTextAggregator())
        if ftype in (ft.DateMap, ft.DateTimeMap):
            return UnionMapAggregator(MaxAggregator())
        if ftype is ft.PercentMap or ftype is ft.Prediction:
            return UnionMapAggregator(MeanAggregator())
        if ftype is ft.BinaryMap:
            return UnionMapAggregator(LogicalOrAggregator())
        if ftype is ft.GeolocationMap:
            return UnionMapAggregator(GeolocationMidpointAggregator())
        if ftype is ft.MultiPickListMap:
            return UnionMapAggregator(UnionSetAggregator())
        return UnionMapAggregator(SumAggregator())
    raise ValueError(
        f"No default aggregator mapping for feature type {ftype.__name__}")
