"""Runtime lock-order witness (the dynamic half of the TMG8xx pass).

The static analyzer (``tools/concurrency_lint.py``) derives a lock-order
graph from the source and flags cycles (TMG801) before any thread runs.
This module is the belt to that suspender: in debug/test mode every
:func:`witness_lock` records the per-thread acquisition order actually
observed at runtime and raises (or records) the moment two threads
disagree about which of two locks comes first — i.e. the instant a
latent deadlock becomes demonstrable, not the rare run where it hangs.

Disarmed (the default) a witnessed lock costs one attribute read per
acquisition on top of the underlying ``threading.Lock``; production code
pays nothing measurable.  The chaos suites arm the witness in
record-only mode so the fleet/continual/server tests double as a race
harness, and an intentional-inversion unit test proves the raise path.

Arm it three ways:

* ``locks.arm(raise_on_violation=True)`` / ``locks.disarm()``
* ``with locks.armed(): ...`` (tests; restores prior state)
* environment knob ``TMOG_LOCK_WITNESS=1`` (record) or ``=raise``
  read once at import — deliberately *not* a ``config`` knob, because
  the witness must be armable before any package module executes.

``fcntl.flock`` regions have no lock object to wrap; bracket them with
:func:`witness_acquire` / :func:`witness_release` so kernel file locks
join the same ordering graph as in-process mutexes.
"""

from __future__ import annotations

import os
import threading
import traceback
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    "LockOrderViolation",
    "WitnessedLock",
    "arm",
    "armed",
    "disarm",
    "is_armed",
    "reset",
    "violations",
    "witness_acquire",
    "witness_lock",
    "witness_release",
]


class LockOrderViolation(RuntimeError):
    """Two threads acquired the same pair of locks in opposite orders."""


_armed = False
_raise_on_violation = False
#: plain (never witnessed) mutex guarding the tables below
_mu = threading.Lock()
#: (first, second) -> human description of the first observation
_edges: Dict[Tuple[str, str], str] = {}
_violations: List[str] = []
_tls = threading.local()


def _held() -> List[Tuple[str, str]]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _site() -> str:
    """Short 'file:line in func' stack for the current acquisition."""
    frames = [f for f in traceback.extract_stack(limit=12)
              if not f.filename.endswith("locks.py")]
    return " <- ".join(
        f"{os.path.basename(f.filename)}:{f.lineno} in {f.name}"
        for f in reversed(frames[-3:]))


def _record(name: str) -> Optional[str]:
    """Record an acquisition; return a violation message or None.

    The caller pushes onto the per-thread stack itself (so manual
    flock brackets and real locks share one code path) and decides
    whether a returned violation raises or is merely recorded.
    """
    held = _held()
    if any(h == name for h, _ in held):
        return None                       # reentrant re-entry: no edge
    site = _site()
    tname = threading.current_thread().name
    msg = None
    with _mu:
        for h, h_site in held:
            rev = _edges.get((name, h))
            if rev is not None:
                msg = (
                    f"lock-order inversion: thread '{tname}' holds "
                    f"'{h}' (acquired at {h_site}) and is acquiring "
                    f"'{name}' at {site}, but the opposite order was "
                    f"established earlier — {rev}")
                _violations.append(msg)
                break
            _edges.setdefault(
                (h, name),
                f"thread '{tname}' held '{h}' (at {h_site}) then "
                f"acquired '{name}' at {site}")
    return msg


def _push(name: str) -> None:
    _held().append((name, _site()))


def _pop(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == name:
            del held[i]
            return


class WitnessedLock:
    """``threading.Lock``/``RLock`` proxy feeding the order witness."""

    __slots__ = ("name", "reentrant", "_inner")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _armed:
            msg = _record(self.name)
            if msg is not None and _raise_on_violation:
                self._inner.release()
                raise LockOrderViolation(msg)
            _push(self.name)
        return got

    def release(self) -> None:
        if _armed:
            _pop(self.name)
        self._inner.release()

    def __enter__(self) -> "WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kind = "RLock" if self.reentrant else "Lock"
        return f"<WitnessedLock {self.name!r} ({kind})>"


def witness_lock(name: str, reentrant: bool = False) -> WitnessedLock:
    """Factory for a named, order-witnessed lock.

    The static analyzer resolves ``witness_lock(...)`` assignments the
    same way it resolves ``threading.Lock()`` ones, so converting a
    lock to the witness never hides it from TMG801/TMG803.
    """
    return WitnessedLock(name, reentrant=reentrant)


def witness_acquire(name: str) -> None:
    """Manually enter a named region (e.g. after ``fcntl.flock``)."""
    if not _armed:
        return
    msg = _record(name)
    if msg is not None and _raise_on_violation:
        raise LockOrderViolation(msg)
    _push(name)


def witness_release(name: str) -> None:
    """Manually leave a region opened with :func:`witness_acquire`."""
    if getattr(_tls, "held", None):
        _pop(name)


def arm(raise_on_violation: bool = False) -> None:
    """Start witnessing; clears previously recorded edges/violations."""
    global _armed, _raise_on_violation
    reset()
    _raise_on_violation = raise_on_violation
    _armed = True


def disarm() -> None:
    global _armed, _raise_on_violation
    _armed = False
    _raise_on_violation = False


def is_armed() -> bool:
    return _armed


def reset() -> None:
    """Forget all recorded edges and violations (keeps armed state)."""
    with _mu:
        _edges.clear()
        del _violations[:]


def violations() -> List[str]:
    with _mu:
        return list(_violations)


@contextmanager
def armed(raise_on_violation: bool = False) -> Iterator[None]:
    """Arm for the duration of a block, restoring the prior state."""
    prev = (_armed, _raise_on_violation)
    arm(raise_on_violation=raise_on_violation)
    try:
        yield
    finally:
        if prev[0]:
            arm(raise_on_violation=prev[1])
        else:
            disarm()


_env = os.environ.get("TMOG_LOCK_WITNESS", "").strip().lower()
if _env and _env not in ("0", "false", "no", "off"):
    arm(raise_on_violation=_env == "raise")
del _env
