"""Feature DAG nodes + FeatureBuilder.

Re-designs ``FeatureLike``/``Feature``/``FeatureBuilder``
(``features/.../FeatureLike.scala:48-466``, ``Feature.scala``,
``FeatureBuilder.scala:47-341``) as plain Python objects:

* A :class:`Feature` is a symbolic node — ``name``, ``uid``, feature type,
  ``is_response``, ``origin_stage``, ``parents``. Raw features have a
  :class:`FeatureGeneratorStage`-like origin with an ``extract_fn``;
  derived features point at the transformer/estimator that computes them.
* ``transform_with`` (FeatureLike.scala:210-279) wires a stage to inputs and
  returns its output feature.
* ``parent_stages`` (FeatureLike.scala:363-425) is a DFS + Kahn toposort to
  stage → max-distance, with cycle detection (FeatureCycleException :405).
"""
from __future__ import annotations

from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple, Type,
                    TYPE_CHECKING)

from .types.feature_types import FeatureType, FeatureTypeError
from .utils import uid as uid_mod

if TYPE_CHECKING:  # pragma: no cover
    from .stages.base import OpPipelineStage

__all__ = ["Feature", "FeatureCycleError", "FeatureBuilder", "FeatureHistory",
           "copy_dag"]


class FeatureCycleError(Exception):
    """Cycle detected in the feature DAG (FeatureLike.scala:405)."""


class FeatureHistory:
    """Origin raw features + stage lineage (utils/.../FeatureHistory.scala)."""

    def __init__(self, origin_features: Sequence[str], stages: Sequence[str]):
        self.origin_features = list(origin_features)
        self.stages = list(stages)

    def to_json(self) -> Dict[str, Any]:
        return {"originFeatures": self.origin_features, "stages": self.stages}

    def __repr__(self) -> str:
        return f"FeatureHistory(origins={self.origin_features}, stages={self.stages})"


class Feature:
    """Symbolic DAG node typed by a FeatureType subclass."""

    __slots__ = ("name", "ftype", "is_response", "origin_stage", "parents",
                 "uid", "is_raw")

    def __init__(self, name: str, ftype: Type[FeatureType], is_response: bool,
                 origin_stage: Optional["OpPipelineStage"],
                 parents: Sequence["Feature"] = (),
                 uid: Optional[str] = None):
        self.name = name
        self.ftype = ftype
        self.is_response = is_response
        self.origin_stage = origin_stage
        self.parents: Tuple[Feature, ...] = tuple(parents)
        self.uid = uid or uid_mod.make_uid("Feature")
        self.is_raw = origin_stage is None or len(self.parents) == 0

    # -- graph wiring ------------------------------------------------------
    def transform_with(self, stage: "OpPipelineStage",
                       *others: "Feature") -> "Feature":
        """Set (self, *others) as the stage's inputs; return its output feature."""
        stage.set_input(self, *others)
        return stage.get_output()

    # -- traversal ---------------------------------------------------------
    def traverse(self, visit: Callable[["Feature"], None]) -> None:
        """Depth-first traversal over ancestors, self first."""
        seen = set()
        stack: List[Feature] = [self]
        while stack:
            f = stack.pop()
            if f.uid in seen:
                continue
            seen.add(f.uid)
            visit(f)
            stack.extend(f.parents)

    def raw_features(self) -> List["Feature"]:
        out: List[Feature] = []

        def visit(f: Feature) -> None:
            if f.is_raw:
                out.append(f)

        self.traverse(visit)
        return sorted(out, key=lambda f: f.name)

    def parent_stages(self) -> Dict["OpPipelineStage", int]:
        """All ancestor stages mapped to max distance from this feature.

        Distance = longest path in stage hops; used for DAG layering
        (FitStagesUtil.computeDAG). Raises FeatureCycleError on cycles.
        """
        dist: Dict[str, int] = {}
        stages: Dict[str, "OpPipelineStage"] = {}
        on_path: set = set()
        done: set = set()

        def visit(f: Feature, d: int) -> None:
            if f.uid in on_path:
                raise FeatureCycleError(
                    f"Cycle detected in feature graph at {f.name!r}")
            st = f.origin_stage
            if st is None:
                return
            key = st.uid
            prev = stages.get(key)
            if prev is not None and prev is not st:
                # distinct stages sharing a uid would collapse into one
                # node (and one would silently vanish from the DAG) —
                # see graph.compute_dag / lint rule TMG102
                raise ValueError(
                    f"duplicate stage uid {key!r}: {prev.stage_name()} "
                    f"and {st.stage_name()} are distinct stages sharing "
                    "one uid")
            stages[key] = st
            if dist.get(key, -1) < d:
                dist[key] = d
            elif f.uid in done:
                return
            on_path.add(f.uid)
            for p in f.parents:
                visit(p, d + 1)
            on_path.discard(f.uid)
            done.add(f.uid)

        visit(self, 0)
        return {stages[k]: v for k, v in dist.items()}

    def history(self) -> FeatureHistory:
        origins = [f.name for f in self.raw_features() if f is not self]
        stage_names = sorted(
            {s.stage_name() for s in self.parent_stages()
             if not getattr(s, "is_raw_generator", False)})
        return FeatureHistory(origins, stage_names)

    # -- identity ----------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Feature) and other.uid == self.uid

    def __hash__(self) -> int:
        return hash(self.uid)

    def __repr__(self) -> str:
        kind = "response" if self.is_response else "predictor"
        return (f"Feature({self.name!r}, {self.ftype.__name__}, {kind}, "
                f"uid={self.uid})")


class _FeatureBuilderWithExtract:
    """Second step of FeatureBuilder: has an extract function, can set
    aggregation, then finalize as predictor/response
    (FeatureBuilder.scala:268-341)."""

    def __init__(self, name: str, ftype: Type[FeatureType],
                 extract_fn: Callable[[Any], Any],
                 extract_source: Optional[str] = None):
        self.name = name
        self.ftype = ftype
        self.extract_fn = extract_fn
        self.extract_source = extract_source
        self.aggregator = None
        self.window_ms: Optional[int] = None

    def aggregate(self, aggregator) -> "_FeatureBuilderWithExtract":
        """Attach a monoid aggregator for event-grouped readers."""
        self.aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "_FeatureBuilderWithExtract":
        self.window_ms = int(window_ms)
        return self

    def _build(self, is_response: bool) -> Feature:
        from .stages.generator import FeatureGeneratorStage
        stage = FeatureGeneratorStage(
            name=self.name, ftype=self.ftype, extract_fn=self.extract_fn,
            is_response=is_response, aggregator=self.aggregator,
            window_ms=self.window_ms, extract_source=self.extract_source)
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)


class _FeatureBuilderOfType:
    def __init__(self, name: str, ftype: Type[FeatureType]):
        self.name = name
        self.ftype = ftype

    def extract(self, fn: Callable[[Any], Any],
                source: Optional[str] = None) -> _FeatureBuilderWithExtract:
        """Attach a record → value extractor.

        The reference captures the source text with a macro
        (FeatureBuilderMacros.scala); here pass ``source`` explicitly or we
        best-effort introspect the function.
        """
        if source is None:
            source = getattr(fn, "__name__", None)
            if source == "<lambda>":
                source = None
        return _FeatureBuilderWithExtract(self.name, self.ftype, fn, source)

    def from_column(self) -> _FeatureBuilderWithExtract:
        """Extract by record key == feature name (dict-record readers)."""
        name = self.name
        fn = lambda rec: rec.get(name)  # noqa: E731
        # marker for the bulk-ingest fast path (generator.extract_column
        # runs a C-speed methodcaller map instead of n Python frames)
        fn._column_key = name
        return _FeatureBuilderWithExtract(
            name, self.ftype, fn, f"record[{name!r}]")


class _FeatureBuilderMeta(type):
    """``FeatureBuilder.Real["age"]`` / ``FeatureBuilder.of(Real, "age")``."""

    def __getattr__(cls, type_name: str):
        from .types.feature_types import FEATURE_TYPE_REGISTRY
        if type_name in FEATURE_TYPE_REGISTRY:
            ftype = FEATURE_TYPE_REGISTRY[type_name]
            return lambda name: _FeatureBuilderOfType(name, ftype)
        raise AttributeError(type_name)


class FeatureBuilder(metaclass=_FeatureBuilderMeta):
    """Entry point for declaring raw features (FeatureBuilder.scala:47).

    Usage::

        age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()
        survived = FeatureBuilder.RealNN("survived").from_column().as_response()
    """

    @staticmethod
    def of(ftype: Type[FeatureType], name: str) -> _FeatureBuilderOfType:
        return _FeatureBuilderOfType(name, ftype)

    @staticmethod
    def from_store(store, response: str,
                   response_type: Type[FeatureType] = None,
                   ignore: Sequence[str] = ()) -> Tuple[Feature, List[Feature]]:
        """Infer raw features from an existing ColumnStore's column types
        (FeatureBuilder.fromDataFrame, FeatureBuilder.scala:190-217).

        Returns (response_feature, predictor_features).
        """
        from .types.feature_types import RealNN
        response_type = response_type or RealNN
        if response not in store:
            raise ValueError(f"Response column {response!r} not in store")
        resp = (FeatureBuilder.of(response_type, response)
                .from_column().as_response())
        predictors = []
        skip = set(ignore) | {response}
        for name, col in store.items():
            if name in skip:
                continue
            predictors.append(
                FeatureBuilder.of(col.ftype, name).from_column().as_predictor())
        return resp, predictors


def copy_dag(result_features: Sequence[Feature],
             drop_raw_uids: frozenset = frozenset()) -> List[Feature]:
    """Deep-copy the derived part of a feature DAG
    (``FeatureLike.copyWithNewStages``, FeatureLike.scala:456).

    Raw features (and their generator stages) are shared, every derived
    feature and its origin stage are copied, so rewiring the copy — e.g.
    dropping blacklisted raw features from variable-arity stage inputs via
    ``drop_raw_uids`` — never mutates the user-owned graph. Copies keep the
    original uids, so fitted-stage lookup by uid still works.

    Raises TypeError if a dropped feature is required by a fixed-arity stage.
    """
    memo: Dict[str, Feature] = {}

    def go(f: Feature) -> Feature:
        if f.uid in memo:
            return memo[f.uid]
        if f.is_raw:
            memo[f.uid] = f
            return f
        new_parents = tuple(go(p) for p in f.parents
                            if p.uid not in drop_raw_uids)
        stage = f.origin_stage.copy()
        stage.input_spec.check(new_parents)
        stage.input_features = new_parents
        nf = Feature(name=f.name, ftype=f.ftype, is_response=f.is_response,
                     origin_stage=stage, parents=new_parents, uid=f.uid)
        stage._output_feature = nf
        memo[f.uid] = nf
        return nf

    return [go(f) for f in result_features]
