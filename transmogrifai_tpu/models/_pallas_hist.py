"""Pallas TPU kernel for the level-wise tree histogram build.

The tree engine's hot op (``_treefit._level_cumhist``) computes, per level,

    cum[s, c, t, f] = Σ_i 1[node_i = s] · 1[Xb_if ≤ t] · stats_ic

as one MXU matmul ``NSᵀ @ Bc`` with ``NS = one_hot(node) ⊗ stats`` and
``Bc = (bin ≤ t)``. The pure-XLA path must *materialize* NS ([n, A·C]) and
Bc ([n, B·F]) in HBM before the dot — at the 10M-row BASELINE config that
write+read traffic (n · (A·C + B·F) elements per level per tree) dominates
the histogram build, which is exactly the bandwidth problem SURVEY §2.9
assigns to a Pallas kernel (the xgboost4j/Rabit replacement: "Pallas
histogram-build & split kernels").

This kernel fuses operand construction into the matmul: row blocks of
``Xb``/``node``/``stats`` stream HBM→VMEM once (n · (F + C + 1) elements),
the one-hot expansion and the bin-threshold indicator are built in VMEM,
and the [A·C, B, Fc] output block stays resident in VMEM across the row
grid (TPU grids execute sequentially; the row axis is the fastest-varying
grid dim, so the accumulator is revisited, zero-initialised at row step 0).
Features are tiled over the slower grid axis to bound VMEM.

Numerics match the XLA path: bf16 operands (counts are sums of exact bf16
1.0s) with f32 accumulation when stats are f32; f64 (CPU tests) stays f64.

Measured on a v5e-1 at the synthetic-trees bench shape (n=200k, F=20,
B=32, A=128, C=3): 6.2 ms per histogram vs 13.4 ms for the XLA path
(2.2× — amortized over a scanned jit; single-call timings only measure
dispatch latency), and end-to-end the 200k-row RF+GBT+XGB CV sweep
trains in 21.5 s warm vs 29.4 s (27% faster), with slightly lower cold
time too (81 s vs 91 s — the fused kernel is less HLO than the
materialized matmul graphs). Identical selections and AuPR. The win
grows with rows: histogram HBM traffic is linear in n while the
fixed-shape level overheads are not.

Default: **on for TPU backends** (one-time compile probe; any Mosaic
failure falls back to the XLA path), off elsewhere. ``TMOG_PALLAS=1``
forces it on (interpret mode off-TPU), ``TMOG_PALLAS=0`` forces the XLA
path. The gate value is part of the CV executable cache key
(``ModelFamily.trace_signature``), so flipping it mid-process retraces.
"""
from __future__ import annotations

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

__all__ = ["cumhist", "route_level", "split_scan", "split_scan_ok",
           "pallas_histograms_enabled", "sparse01_enabled",
           "split_scan_enabled", "tree_kernel_stats", "ROW_ALIGN"]

import threading as _threading

_PROBE: Optional[bool] = None
#: created at import — a lazy check-then-assign could hand two racing
#: threads two different locks, defeating the double-compile guard
_PROBE_LOCK = _threading.Lock()

#: always-on tree-kernel tallies (the ``fitstats_stats`` discipline):
#: TRACE-time routing decisions — how many times each kernel family was
#: staged into a compiled program, how many histogram builds went through
#: the mesh-sharded shard_map wrapper, and whether the gate ever flipped
#: off mid-process. Stamped on every runner metrics doc and every bench
#: doc under ``trees`` (docs/observability.md).
_TK_LOCK = _threading.Lock()
_TK = {"cumhist_traces": 0, "sparse01_traces": 0, "split_scan_traces": 0,
       "route_traces": 0, "predict_traces": 0, "sharded_hist_traces": 0,
       "sharded_route_traces": 0, "feature_shard_traces": 0,
       "kernel_disables": 0}


def _tk_tally(key: str, n: int = 1) -> None:
    with _TK_LOCK:
        _TK[key] += n


def tree_kernel_stats() -> dict:
    """Snapshot of the tree-engine kernel tallies plus the effective
    gate states — always on, cheap, stamped on every bench/runner doc."""
    env = os.environ.get("TMOG_PALLAS", "").strip()
    gate = {"1": "forced_on", "0": "forced_off"}.get(
        env, "on" if _PROBE else ("off" if _PROBE is False else "unprobed"))
    with _TK_LOCK:
        out = dict(_TK)
    out["gate"] = gate
    out["sparse01"] = sparse01_enabled()
    out["split_scan"] = split_scan_enabled()
    return out


def sparse01_enabled() -> bool:
    """Gate for the sparsity-aware 2-bin histogram kernel (the
    wide-sparse path): indicator blocks stream the [F, n] bin matrix
    itself instead of a 2×-wider dense ``bin ≤ t`` operand, computing the
    zero-bin column as (per-slot total − nonzero side). Counts stay
    exact; float-weighted channels pick up subtraction-order rounding
    (the TMOG_SIBLING trade). ``TMOG_SPARSE01=0`` disables."""
    return os.environ.get("TMOG_SPARSE01", "1") != "0"


def split_scan_enabled() -> bool:
    """Gate for the fused split-scan kernel (cumulative-histogram →
    per-slot best (feature, threshold) in one VMEM pass). Rides the same
    probe/fallback as ``cumhist`` — ``TMOG_SPLIT_SCAN=0`` keeps the
    histogram kernel while the scan stays on the XLA path."""
    return os.environ.get("TMOG_SPLIT_SCAN", "1") != "0"


def warm_probe_async() -> None:
    """Kick the one-time kernel compile probe on a background thread —
    XLA compilation releases the GIL, so callers with a cold process
    (bench.py before its first config) overlap the ~10-15 s tunnel
    compile with data loading instead of paying it inside the first
    tree-family sweep."""
    def _go():
        try:
            pallas_histograms_enabled()
        except Exception:  # lint: broad-except — probe failures fall back at consult
            pass
    _threading.Thread(target=_go, name="pallas-probe-warm",
                      daemon=True).start()

#: Kernel row alignment. **Rows live in the LANE dimension**: per-row
#: vectors (slot/g/stats channels) travel as rows of a small [k ≤ 8, n]
#: f32 pack and the bin matrix travels TRANSPOSED ([F, n]) — both are
#: lane-compact layouts. The round-4 first cut passed them as [n, 1] /
#: [n, C] / [n, F]: T(8,128) tiling pads the minor dim to 128 lanes
#: (128× / 43× / 6.4× physical blowup), and the fold × tree-chunk vmap
#: turned that into four 10.3 GB HLO temps — an HBM OOM at compile. 1D
#: refs dodge the padding but reject vmap batching; the transposed
#: domain supports both, and every kernel op stays elementwise on
#: [A, lanes] tiles plus an NT-form MXU dot contracting lanes. Callers
#: pre-pad rows once (device_prep / grow_tree) to this multiple so the
#: kernels never materialize per-level padded copies.
ROW_ALIGN = 1024


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _pad_lanes(v, n_pad, fill):
    """Pad the trailing (row/lane) axis of [..., n] to n_pad."""
    n = v.shape[-1]
    if n == n_pad:
        return v
    return jnp.concatenate(
        [v, jnp.full(v.shape[:-1] + (n_pad - n,), fill, v.dtype)], axis=-1)


def _kernel(xbt_ref, pack_ref, o_ref, *, n_nodes, n_bins, n_chan,
            mm_dtype):
    """Transposed domain (rows = lanes). ``pack_ref`` [1+C, bnl]: row 0
    the node slot, rows 1.. the stats channels. ``xbt_ref`` [Fc, bnl].
    The bin indicator is built flat along the SUBLANE axis ([B·Fc, bnl]
    with threshold i // Fc and a B-fold sublane tile of XbT), the node
    one-hot is an elementwise compare against a sublane iota, and each
    channel's histogram is one NT-form dot contracting lanes."""
    rb = pl.program_id(1)

    @pl.when(rb == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    Fc, bnl = xbt_ref.shape
    A, B = n_nodes, n_bins
    node = pack_ref[0, :].astype(jnp.int32)                # [bnl]
    # one_hot(node): padded rows carry node = A → all-false → zero cols.
    ohT = (node[None, :] ==
           lax.broadcasted_iota(jnp.int32, (A, bnl), 0)
           ).astype(jnp.float32).astype(o_ref.dtype)       # [A, bnl]
    # BcT = lower-triangular bin indicator (bin ≤ t) → left-cumulative
    # sums fall straight out of the dot; sublane i = t·Fc + f.
    xb_tile = jnp.concatenate([xbt_ref[:]] * B, axis=0)    # [B·Fc, bnl]
    thr = lax.broadcasted_iota(jnp.int32, (B * Fc, bnl), 0) // Fc
    bcT = (xb_tile <= thr).astype(jnp.float32).astype(mm_dtype)
    for c in range(n_chan):
        ohcT = (ohT * pack_ref[1 + c, :][None, :]).astype(mm_dtype)
        o_ref[c * A:(c + 1) * A, :] += lax.dot_general(
            ohcT, bcT, (((1,), (1,)), ((), ())),
            preferred_element_type=o_ref.dtype)


def _kernel_prebc(bc_ref, pack_ref, o_ref, *, n_nodes, n_chan, mm_dtype):
    """cumhist with the bin indicator STREAMED instead of built: the
    [B·Fc, bnl] lower-triangular compare depends only on Xb, yet the
    in-kernel build (tile + iota compare, ~B·F·bnl VPU ops per block)
    re-runs per level × tree × fold and dominates shallow levels where
    the dot itself is tiny. Callers precompute it once per fit (XLA
    hoists it out of the tree/round scans) when it fits HBM."""
    rb = pl.program_id(1)

    @pl.when(rb == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    A = n_nodes
    bnl = pack_ref.shape[1]
    node = pack_ref[0, :].astype(jnp.int32)
    ohT = (node[None, :] ==
           lax.broadcasted_iota(jnp.int32, (A, bnl), 0)
           ).astype(jnp.float32).astype(o_ref.dtype)
    bcT = bc_ref[:].astype(mm_dtype)
    for c in range(n_chan):
        ohcT = (ohT * pack_ref[1 + c, :][None, :]).astype(mm_dtype)
        o_ref[c * A:(c + 1) * A, :] += lax.dot_general(
            ohcT, bcT, (((1,), (1,)), ((), ())),
            preferred_element_type=o_ref.dtype)


def _kernel_sparse01(xbt_ref, pack_ref, o_ref, *, n_nodes, n_chan,
                     mm_dtype):
    """Sparsity-aware 2-bin histogram (the wide-sparse path): for an
    indicator block the ``bin ≤ t`` operand is redundant — the t=0 (zero
    side) column is (per-slot total − nonzero side) and the t=1 column IS
    the per-slot total, so the kernel streams the [Fc, bnl] 0/1 bin
    matrix itself (half the generic kernel's [2·Fc, bnl] indicator
    traffic) and runs ONE dot per channel instead of a 2×-wider one.
    High-cardinality OneHot/text-hash matrices are mostly zero and
    mostly one-bin (PAPER.md §L2), which is exactly this block shape.

    Output layout matches ``_kernel`` at B=2: [C·A, 2·Fc] with (t, f)
    t-major columns — cols [:Fc] the cumulative zero-bin, [Fc:] totals.
    """
    rb = pl.program_id(1)

    @pl.when(rb == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    Fc, bnl = xbt_ref.shape
    A = n_nodes
    node = pack_ref[0, :].astype(jnp.int32)
    ohT = (node[None, :] ==
           lax.broadcasted_iota(jnp.int32, (A, bnl), 0)
           ).astype(jnp.float32).astype(o_ref.dtype)        # [A, bnl]
    xT = xbt_ref[:].astype(jnp.float32).astype(mm_dtype)    # [Fc, bnl] 0/1
    for c in range(n_chan):
        ohcT = (ohT * pack_ref[1 + c, :][None, :]).astype(mm_dtype)
        nz = lax.dot_general(
            ohcT, xT, (((1,), (1,)), ((), ())),
            preferred_element_type=o_ref.dtype)             # [A, Fc]
        tot = jnp.sum(ohcT.astype(o_ref.dtype), axis=1,
                      keepdims=True)                        # [A, 1]
        o_ref[c * A:(c + 1) * A, 0:Fc] += tot - nz
        o_ref[c * A:(c + 1) * A, Fc:2 * Fc] += jnp.broadcast_to(
            tot, (A, Fc))


def make_bc(XbT: jnp.ndarray, n_bins: int, dtype) -> jnp.ndarray:
    """[F, n] bins → [B·F, n] lower-triangular bin indicator (sublane
    i = t·F + f ⇒ bin[f] ≤ t), the precomputed operand for
    ``cumhist(..., bc=...)``. bf16 for f32 stats (counts stay exact —
    sums of exact 1.0s in an f32 accumulator)."""
    F, n = XbT.shape
    tiles = jnp.concatenate([XbT] * n_bins, axis=0)        # [B·F, n]
    thr = (jnp.arange(n_bins * F, dtype=jnp.int32) // F)[:, None]
    return (tiles <= thr).astype(dtype)


def bc_cache_ok(n: int, F: int, n_bins: int,
                max_bytes: float = 3e9, itemsize: int = 2) -> bool:
    """Precompute the bin indicator only when it fits comfortably in HBM
    and a single feature chunk covers it (the chunked layout interleaves
    (t, f) rows per chunk). ``itemsize`` must be the byte width of the
    dtype ``make_bc`` will actually build (bf16 for f32 stats, else the
    stats dtype — e.g. 8 on the f64 CPU/x64 path), or the budget check
    undercounts the cached indicator (ADVICE r4)."""
    return (isinstance(n, int) and n_bins * F <= 1024
            and float(itemsize) * n * n_bins * F <= max_bytes)


def cumhist(stats: jnp.ndarray, node: jnp.ndarray, XbT: jnp.ndarray,
            n_nodes: int, n_bins: int, *, block_lanes: int = ROW_ALIGN,
            max_sub: int = 1024, interpret: Optional[bool] = None,
            bc: Optional[jnp.ndarray] = None,
            sparse01: bool = False) -> jnp.ndarray:
    """[n, C] stats + [n] node slots + [F, n] TRANSPOSED bins →
    [A, C, B, F] cumulative histograms (idle rows: node == n_nodes →
    zero). Drop-in replacement for the XLA matmul path in
    ``_treefit._level_cumhist``.

    Per-row operands enter as a [1+C, n] f32 pack and the bin matrix
    feature-major — both lane-compact (see ROW_ALIGN). Callers at scale
    pre-pad rows (device_prep); unaligned small-n calls pad here.

    ``sparse01`` — the block is a 2-bin indicator block whose bin values
    are all in {0, 1}: route through :func:`_kernel_sparse01` (half the
    operand traffic, one dot per channel; ``bc`` is ignored)."""
    F, n = XbT.shape
    C = stats.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bnl = block_lanes
    Fc = max(1, min(F, max_sub // n_bins))
    n_pad = _round_up(n, bnl)
    F_pad = _round_up(F, Fc)
    pack = jnp.concatenate(
        [_pad_lanes(node[None, :].astype(stats.dtype), n_pad, n_nodes),
         _pad_lanes(stats.T, n_pad, 0)])                   # [1+C, n_pad]
    mm_dtype = jnp.bfloat16 if stats.dtype == jnp.float32 else stats.dtype
    if sparse01:
        if n_bins != 2:
            raise ValueError(
                f"cumhist(sparse01=True) needs a 2-bin block, got "
                f"n_bins={n_bins}")
        _tk_tally("sparse01_traces")
        XbT = _pad_lanes(XbT, n_pad, 0)     # pad bins 0 → zero side; the
        if F_pad != F:                      # pack's zero stats keep pads
            XbT = jnp.concatenate(          # out of every histogram
                [XbT, jnp.zeros((F_pad - F, n_pad), XbT.dtype)])
        kern = functools.partial(_kernel_sparse01, n_nodes=n_nodes,
                                 n_chan=C, mm_dtype=mm_dtype)
        nfb = F_pad // Fc
        out = pl.pallas_call(
            kern,
            grid=(nfb, n_pad // bnl),                      # rows fastest
            in_specs=[
                pl.BlockSpec((Fc, bnl), lambda fb, rb: (fb, rb)),
                pl.BlockSpec((1 + C, bnl), lambda fb, rb: (0, rb)),
            ],
            out_specs=pl.BlockSpec((C * n_nodes, 2 * Fc),
                                   lambda fb, rb: (0, fb)),
            out_shape=jax.ShapeDtypeStruct((C * n_nodes, nfb * 2 * Fc),
                                           stats.dtype),
            interpret=interpret,
        )(XbT, pack)
        out = out.reshape(C, n_nodes, nfb, 2, Fc)
        out = out.transpose(1, 0, 3, 2, 4).reshape(
            n_nodes, C, 2, F_pad)
        return out[..., :F]
    _tk_tally("cumhist_traces")
    if bc is not None and F_pad == F:
        # precomputed-indicator path (see _kernel_prebc / make_bc)
        bc = _pad_lanes(bc, n_pad, 0)
        kern = functools.partial(_kernel_prebc, n_nodes=n_nodes,
                                 n_chan=C, mm_dtype=mm_dtype)
        out = pl.pallas_call(
            kern,
            grid=(1, n_pad // bnl),
            in_specs=[
                pl.BlockSpec((n_bins * F, bnl), lambda fb, rb: (0, rb)),
                pl.BlockSpec((1 + C, bnl), lambda fb, rb: (0, rb)),
            ],
            out_specs=pl.BlockSpec((C * n_nodes, n_bins * F),
                                   lambda fb, rb: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((C * n_nodes, n_bins * F),
                                           stats.dtype),
            interpret=interpret,
        )(bc, pack)
        return out.reshape(C, n_nodes, n_bins, F).transpose(1, 0, 2, 3)
    XbT = _pad_lanes(XbT, n_pad, 0)
    if F_pad != F:
        XbT = jnp.concatenate(
            [XbT, jnp.zeros((F_pad - F, n_pad), XbT.dtype)])
    kern = functools.partial(_kernel, n_nodes=n_nodes, n_bins=n_bins,
                             n_chan=C, mm_dtype=mm_dtype)
    nfb = F_pad // Fc
    out = pl.pallas_call(
        kern,
        grid=(nfb, n_pad // bnl),                          # rows fastest
        in_specs=[
            pl.BlockSpec((Fc, bnl), lambda fb, rb: (fb, rb)),
            pl.BlockSpec((1 + C, bnl), lambda fb, rb: (0, rb)),
        ],
        out_specs=pl.BlockSpec((C * n_nodes, n_bins * Fc),
                               lambda fb, rb: (0, fb)),
        out_shape=jax.ShapeDtypeStruct((C * n_nodes, nfb * n_bins * Fc),
                                       stats.dtype),
        interpret=interpret,
    )(XbT, pack)
    # rows are channel-major (c·A + a), columns (fb, t, f_local): restore
    # the channel-minor [A, C, B, F] layout the tree engine expects.
    out = out.reshape(C, n_nodes, nfb, n_bins, Fc)
    out = out.transpose(1, 0, 3, 2, 4).reshape(
        n_nodes, C, n_bins, F_pad)
    return out[..., :F]


def _route_kernel(xbt_ref, pack_ref, tab_ref, o_ref, xv_ref, *,
                  A_parent, A_child, Fc, nfb):
    """Per-row level routing, one streamed pass over [Fc, bnl] bin blocks
    (transposed domain, rows = lanes).

    The XLA routing path materializes ~3 [n, A] f32 tensors per level
    (one-hot slot masks, per-row split-feature values, child selects) —
    at 1.8M rows × A=128 that is several GB of HBM traffic per level per
    tree, and it showed up as ~42% of device time in the round-3 profile
    (``BENCH_r03.json`` top ops are %while routing/binning state). Here
    the whole lookup chain (slot → split feature/threshold/children →
    compare → child slot) runs in VMEM with only [F, n] streamed in and
    one [2, n] pack out.

    Grid = (row blocks, feature blocks), features fastest: each row
    block accumulates its selected split-feature value in a VMEM scratch
    across feature blocks (bounds VMEM for wide matrices), then routes on
    the last feature step.

    ``pack_ref`` [2, bnl]: row 0 slot, row 1 g. ``tab_ref`` [Ap, 8] f32
    columns: 0=f_idx, 1=t_idx(bin), 2=lchild, 3=rchild, 4=do_split —
    slot-major so table values broadcast along lanes without transposes.
    """
    fb = pl.program_id(1)
    bnl = xbt_ref.shape[1]
    slot = pack_ref[0, :]                                   # [bnl] f32
    ohT = (slot.astype(jnp.int32)[None, :] ==
           lax.broadcasted_iota(jnp.int32, (A_parent, bnl), 0)
           ).astype(jnp.float32)                            # [Ap, bnl]

    def sel(col):                                           # [bnl] f32
        return jnp.sum(ohT * tab_ref[:, col:col + 1].astype(jnp.float32),
                       axis=0)

    @pl.when(fb == 0)
    def _init():
        xv_ref[:] = jnp.zeros_like(xv_ref)

    f_sel = sel(0).astype(jnp.int32)
    fiota = fb * Fc + lax.broadcasted_iota(jnp.int32, (Fc, bnl), 0)
    xv_ref[0, :] += jnp.sum(
        jnp.where(fiota == f_sel[None, :],
                  xbt_ref[:].astype(jnp.float32), 0.0), axis=0)

    @pl.when(fb == nfb - 1)
    def _route():
        g = pack_ref[1, :]
        t_sel = sel(1)
        l_sel = sel(2)
        r_sel = sel(3)
        ds_sel = sel(4)
        right = ((xv_ref[0, :] > t_sel) & (ds_sel > 0.5)
                 & (slot < A_parent)).astype(jnp.float32)
        child = jnp.where(right > 0.5, r_sel, l_sel)
        o_ref[0, :] = jnp.where(slot >= A_parent,
                                jnp.float32(A_child), child)
        o_ref[1, :] = 2.0 * g + right


def route_level(XbT: jnp.ndarray, slot: jnp.ndarray, g: jnp.ndarray,
                f_idx, t_idx, lchild, rchild, do_split,
                A_parent: int, A_child: int, *,
                interpret: Optional[bool] = None):
    """(slot, g) → (slot', g') for one tree level over [F, n] transposed
    bins (see ``_route_kernel``). slot/g values stay exact in f32 (< 2^24:
    slots ≤ 128, g < 2^maxdepth)."""
    _tk_tally("route_traces")
    F, n = XbT.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bnl = ROW_ALIGN
    Fc = max(1, min(F, 512))
    n_pad = _round_up(n, bnl)
    F_pad = _round_up(F, Fc)
    XbT = _pad_lanes(XbT, n_pad, 0)
    pack = jnp.concatenate(
        [_pad_lanes(slot[None, :].astype(jnp.float32), n_pad, A_parent),
         _pad_lanes(g[None, :].astype(jnp.float32), n_pad, 0)])
    if F_pad != F:
        XbT = jnp.concatenate(
            [XbT, jnp.zeros((F_pad - F, n_pad), XbT.dtype)])
    tab = jnp.stack(
        [f_idx.astype(jnp.float32), t_idx.astype(jnp.float32),
         lchild.astype(jnp.float32), rchild.astype(jnp.float32),
         do_split.astype(jnp.float32),
         jnp.zeros((A_parent,), jnp.float32),
         jnp.zeros((A_parent,), jnp.float32),
         jnp.zeros((A_parent,), jnp.float32)], axis=1)      # [Ap, 8]
    nfb = F_pad // Fc
    kern = functools.partial(_route_kernel, A_parent=A_parent,
                             A_child=A_child, Fc=Fc, nfb=nfb)
    out = pl.pallas_call(
        kern,
        grid=(n_pad // bnl, nfb),                       # features fastest
        in_specs=[
            pl.BlockSpec((Fc, bnl), lambda rb, fb: (fb, rb)),
            pl.BlockSpec((2, bnl), lambda rb, fb: (0, rb)),
            pl.BlockSpec((A_parent, 8), lambda rb, fb: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, bnl), lambda rb, fb: (0, rb)),
        out_shape=jax.ShapeDtypeStruct((2, n_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((8, bnl), jnp.float32)],
        interpret=interpret,
    )(XbT, pack, tab)
    return (out[0, :n].astype(jnp.int32), out[1, :n].astype(jnp.int32))


#: masked-out candidate score — must sort below every real score (the
#: criteria are sums of squares / squared-over-positive terms, all ≥ 0)
SPLIT_NEG = -1e30
#: "no candidate yet" flat index for the cross-block merge; real flat
#: indices are gated < 2^24 (exact in f32) by split_scan_ok
_SPLIT_IDX_BIG = float(1 << 25)


def _scan_kernel(cumT_ref, mask_ref, pars_ref, o_ref, *, kind, n_bins,
                 n_chan, Fc, F_total):
    """Fused split scan over one feature block of the cumulative
    histogram (see :func:`split_scan` for the contract).

    The XLA alternative materializes ~6 [A, B−1, F] HBM tensors per
    level (criterion score, instance/hessian masks, the _NEG-masked flat
    matrix, argmax companions) and runs as a serialized chain of small
    elementwise ops — the residual `%while` body cost once the histogram
    itself is a kernel. Here the whole chain (score → masks → argmax
    with first-occurrence tie-break → winner validity) runs on VPU tiles
    with only the [C·B, A, Fc] histogram block streamed in and an [A, 8]
    pack out.

    Layout: slots in sublanes, features in lanes — [A, Fc] tiles per
    (channel, bin), the bin loop statically unrolled (B ≤ 32). Grid =
    feature blocks; the output pack is revisited and merged with the
    (score desc, flat idx asc) tie rule, which reproduces
    ``jnp.argmax``'s first-occurrence semantics over the t-major flat
    candidate axis exactly.
    """
    fb = pl.program_id(0)
    B, C = n_bins, n_chan
    A = mask_ref.shape[0]
    dt = o_ref.dtype
    neg = jnp.asarray(SPLIT_NEG, dt)
    big = jnp.asarray(_SPLIT_IDX_BIG, dt)
    eps = jnp.asarray(1e-12, dt)        # _treefit._EPS
    pmin = pars_ref[0, 0:1]             # min_instances      [1]
    pmcw = pars_ref[0, 1:2]             # min_child_weight   [1]
    plam = pars_ref[0, 2:3]             # xgb lambda         [1]

    def ch(c, t):                       # [A, Fc] channel/bin tile
        return cumT_ref[c * B + t]

    @pl.when(fb == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)
        o_ref[:, 0:1] = jnp.full((A, 1), neg, dt)
        o_ref[:, 1:2] = jnp.full((A, 1), big, dt)

    fio = lax.broadcasted_iota(jnp.int32, (A, Fc), 1) + fb * Fc
    mask_ok = mask_ref[:] > 0.5
    best_s = jnp.full((A, Fc), neg, dt)
    best_i = jnp.zeros((A, Fc), dt)
    best_ok = jnp.zeros((A, Fc), dt)
    for t in range(B - 1):
        if kind == "variance":
            # VarianceCriterion.score: channels (w, w·y, …, count)
            wL, wT = ch(0, t), ch(0, B - 1)
            sL, sT = ch(1, t), ch(1, B - 1)
            sR, wR = sT - sL, wT - wL
            sb = sL * sL / jnp.maximum(wL, eps) \
                + sR * sR / jnp.maximum(wR, eps)
        elif kind == "gini":
            # GiniCriterion.score: channels (class weights …, count)
            wL = jnp.zeros((A, Fc), dt)
            wR = jnp.zeros((A, Fc), dt)
            l2 = jnp.zeros((A, Fc), dt)
            r2 = jnp.zeros((A, Fc), dt)
            for k in range(C - 1):
                lk, tk = ch(k, t), ch(k, B - 1)
                rk = tk - lk
                wL, wR = wL + lk, wR + rk
                l2, r2 = l2 + lk * lk, r2 + rk * rk
            sb = l2 / jnp.maximum(wL, eps) + r2 / jnp.maximum(wR, eps)
        else:                           # "xgb": channels (g, h, count)
            gL, gT = ch(0, t), ch(0, B - 1)
            hL, hT = ch(1, t), ch(1, B - 1)
            gR, hR = gT - gL, hT - hL
            sb = gL * gL / (hL + plam + eps) \
                + gR * gR / (hR + plam + eps)
        lc, tc = ch(C - 1, t), ch(C - 1, B - 1)
        okb = (lc >= pmin) & (tc - lc >= pmin) & mask_ok
        if kind == "xgb":
            hL, hT = ch(1, t), ch(1, B - 1)
            okb = okb & (hL >= pmcw) & (hT - hL >= pmcw)
        okf = okb.astype(dt)
        sb_m = jnp.where(okb, sb, neg)
        flat = (jnp.asarray(t * F_total, dt)
                + fio.astype(dt))       # global t-major candidate index
        better = sb_m > best_s          # strict: earlier t wins ties
        best_i = jnp.where(better, flat, best_i)
        best_ok = jnp.where(better, okf, best_ok)
        best_s = jnp.where(better, sb_m, best_s)
    m = jnp.max(best_s, axis=1, keepdims=True)             # [A, 1]
    idx = jnp.min(jnp.where(best_s == m, best_i, big), axis=1,
                  keepdims=True)
    sel = (best_i == idx) & (best_s == m)
    vld = jnp.max(jnp.where(sel, best_ok, jnp.zeros_like(best_ok)),
                  axis=1, keepdims=True)
    prev_s = o_ref[:, 0:1]
    prev_i = o_ref[:, 1:2]
    prev_v = o_ref[:, 2:3]
    take = (m > prev_s) | ((m == prev_s) & (idx < prev_i))
    o_ref[:, 0:1] = jnp.where(take, m, prev_s)
    o_ref[:, 1:2] = jnp.where(take, idx, prev_i)
    o_ref[:, 2:3] = jnp.where(take, vld, prev_v)


#: candidate indices travel in f32 lanes — exact only below 2^24
SPLIT_SCAN_MAX_CANDIDATES = 1 << 24


def split_scan_ok(n_nodes: int, n_bins: int, n_feat: int) -> bool:
    """Gate for the fused split-scan kernel on one histogram block."""
    return (split_scan_enabled()
            and (n_bins - 1) * n_feat < SPLIT_SCAN_MAX_CANDIDATES
            and n_nodes <= 1024)


def split_scan(cum: jnp.ndarray, kind: str, min_instances, *,
               lam: float = 0.0, min_child_weight=None,
               mask: Optional[jnp.ndarray] = None,
               interpret: Optional[bool] = None):
    """Fused cumulative-sum→gain→argmax over one histogram block:
    [A, C, B, F] cumulative histograms → per-slot
    ``(best_score [A], best_flat_idx [A] int32, valid [A] bool)`` where
    the flat candidate axis is the t-major ``t·F + f`` order the XLA
    path's ``reshape(A, -1)`` + ``argmax`` walks, ``best_score`` is the
    criterion's monotone surrogate (``crit.score``) masked to ``_NEG``
    exactly as the XLA path masks it, and ``valid`` is the winner's
    min-instances/hessian/feature-mask admissibility.

    ``kind`` ∈ {"variance", "gini", "xgb"} selects the inlined criterion
    (channel layouts per ``_treefit``'s criteria classes).
    ``min_instances`` / ``min_child_weight`` may be traced scalars (grid
    hyperparameters); ``lam`` is static. ``mask`` [A, F] (0/1) carries
    the feature/per-node candidate masks; None means all-allowed."""
    A, C, B, F = cum.shape
    _tk_tally("split_scan_traces")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dt = cum.dtype
    # slots→sublanes, features→lanes: [C·B, A, F]
    cumT = cum.transpose(1, 2, 0, 3).reshape(C * B, A, F)
    itemsize = jnp.dtype(dt).itemsize
    Fc = int(max(1, min(F, (4 << 20) // max(C * B * A * itemsize, 1))))
    F_pad = _round_up(F, Fc)
    if mask is None:
        mask = jnp.ones((A, F), dt)
    else:
        mask = mask.astype(dt)
    if F_pad != F:                      # padded features masked out
        cumT = jnp.concatenate(
            [cumT, jnp.zeros((C * B, A, F_pad - F), dt)], axis=2)
        mask = jnp.concatenate(
            [mask, jnp.zeros((A, F_pad - F), dt)], axis=1)
    mcw = (jnp.asarray(0.0, dt) if min_child_weight is None
           else jnp.asarray(min_child_weight, dt))
    pars = jnp.stack([jnp.asarray(min_instances, dt), mcw,
                      jnp.asarray(lam, dt),
                      jnp.zeros((), dt)]).reshape(1, 4)
    kern = functools.partial(_scan_kernel, kind=kind, n_bins=B,
                             n_chan=C, Fc=Fc, F_total=F)
    out = pl.pallas_call(
        kern,
        grid=(F_pad // Fc,),
        in_specs=[
            pl.BlockSpec((C * B, A, Fc), lambda fb: (0, 0, fb)),
            pl.BlockSpec((A, Fc), lambda fb: (0, fb)),
            pl.BlockSpec((1, 4), lambda fb: (0, 0)),
        ],
        out_specs=pl.BlockSpec((A, 8), lambda fb: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((A, 8), dt),
        interpret=interpret,
    )(cumT, mask, pars)
    return out[:, 0], out[:, 1].astype(jnp.int32), out[:, 2] > 0.5


def _predict_kernel(xt_ref, feat_ref, thr_ref, leaf_ref, o_ref, *,
                    depth, n_classes):
    """Route one tree over a lane-block of rows and accumulate its
    (pre-weighted) leaf values into the output pack.

    Routed ensemble prediction through XLA is per-row gathers on the TPU
    scalar core (feat[node] table lookups + per-row column selects) —
    ~44 s of the round-4 2M profile across the workflow's train-store
    transform and the full-store eval. Here the whole descent is
    elementwise VPU work in the transposed domain: per level a one-hot
    over that level's ≤ 2^d nodes selects the split feature/threshold
    ([2^d, bnl] masks), a feature-iota compare selects the row's value,
    and the leaf lookup is one [2^D, bnl] one-hot reduce per class.

    Grid = (row blocks, trees), trees fastest: the output block is
    revisited and accumulates across trees. The node-major tables
    (``feat_ref``/``thr_ref`` [NN, T], ``leaf_ref`` [2^D, T·K]) are tiny
    and ride whole in VMEM; the running tree's column is a dynamic lane
    slice.
    """
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    F, bnl = xt_ref.shape
    T = feat_ref.shape[1]
    # dynamic lane slices need 128-aligned indices; select the running
    # tree's column with a lane mask + reduce instead (tables are tiny)
    tmask = (lax.broadcasted_iota(jnp.int32, (1, T), 1)
             == t).astype(jnp.float32)                     # [1, T]
    node = jnp.zeros((bnl,), jnp.int32)
    off = 0
    for d in range(depth):
        sz = 1 << d
        ohn = (node[None, :] ==
               lax.broadcasted_iota(jnp.int32, (sz, bnl), 0)
               ).astype(jnp.float32)                       # [2^d, bnl]
        fcol = jnp.sum(feat_ref[off:off + sz, :] * tmask,
                       axis=1, keepdims=True)              # [2^d, 1]
        tcol = jnp.sum(thr_ref[off:off + sz, :] * tmask,
                       axis=1, keepdims=True)
        f_sel = jnp.sum(ohn * fcol, axis=0)
        t_sel = jnp.sum(ohn * tcol, axis=0)
        fio = lax.broadcasted_iota(jnp.int32, (F, bnl), 0)
        xv = jnp.sum(jnp.where(fio == f_sel.astype(jnp.int32)[None, :],
                               xt_ref[:], 0.0), axis=0)
        node = 2 * node + (xv > t_sel).astype(jnp.int32)
        off += sz
    ohl = (node[None, :] ==
           lax.broadcasted_iota(jnp.int32, (1 << depth, bnl), 0)
           ).astype(jnp.float32)                           # [2^D, bnl]
    kmask = lax.broadcasted_iota(jnp.int32, (1, leaf_ref.shape[1]), 1)
    for k in range(n_classes):
        lcol = jnp.sum(
            leaf_ref[:] * (kmask == t * n_classes + k).astype(jnp.float32),
            axis=1, keepdims=True)                         # [2^D, 1]
        o_ref[k, :] += jnp.sum(ohl * lcol, axis=0)


#: routed-predict kernel limits: feature block must fit VMEM in one shot
#: (per-level accumulation across feature blocks would need per-level
#: scratch), and the leaf one-hot is [2^D, bnl]
PREDICT_KERNEL_MAX_F = 1024
PREDICT_KERNEL_MAX_DEPTH = 10
PREDICT_KERNEL_MAX_CLASSES = 8


def predict_trees(X, feat, thr, leaf_w, max_depth: int, *,
                  interpret: Optional[bool] = None):
    """[n, F] raw rows through [T, 2^D−1] stacked trees → [n, K] summed
    (tree-weight-scaled) leaf values. See ``_predict_kernel``; callers
    gate on ``predict_kernel_ok``."""
    _tk_tally("predict_traces")
    n, F = X.shape
    T, NN = feat.shape
    K = leaf_w.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bnl = ROW_ALIGN
    n_pad = _round_up(n, bnl)
    XT = _pad_lanes(X.T.astype(jnp.float32), n_pad, 0)     # [F, n_pad]
    featT = feat.T.astype(jnp.float32)                     # [NN, T]
    # dead splits carry +inf thresholds; the kernel's lane-mask select
    # multiplies them by 0 (inf·0 = NaN), so clip to a huge finite value
    # — any real feature value still compares below it
    thrT = jnp.clip(thr.T.astype(jnp.float32), -1e30, 1e30)
    # [2^D, T·K]: per-tree (0, t) block is that tree's [2^D, K] leaves
    leafR = leaf_w.transpose(1, 0, 2).reshape(1 << max_depth, T * K)
    kern = functools.partial(_predict_kernel, depth=max_depth,
                             n_classes=K)
    out = pl.pallas_call(
        kern,
        grid=(n_pad // bnl, T),                            # trees fastest
        in_specs=[
            pl.BlockSpec((F, bnl), lambda rb, t: (0, rb)),
            pl.BlockSpec((NN, T), lambda rb, t: (0, 0)),
            pl.BlockSpec((NN, T), lambda rb, t: (0, 0)),
            pl.BlockSpec((1 << max_depth, T * K), lambda rb, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((8, bnl), lambda rb, t: (0, rb)),
        out_shape=jax.ShapeDtypeStruct((8, n_pad), jnp.float32),
        interpret=interpret,
    )(XT, featT, thrT, jnp.asarray(leafR, jnp.float32))
    return out[:K, :n].T                                   # [n, K]


def predict_kernel_ok(n: int, F: int, max_depth: int, K: int,
                      T: int = 0, min_rows: int = 65_536) -> bool:
    """Gate for the routed-predict kernel: large row counts on the
    kernel path, everything else (tiny batches, very deep/wide models,
    huge ensembles, serving exports with symbolic batch dims) on the XLA
    gather path. The whole-table VMEM residency bounds T: feat/thr
    [NN, T] ×2 + leaf [2^D, T·K] must stay a few MB. A gate miss at
    scoring time is no longer fatal — TreeEnsembleModel.predict_arrays
    wraps the dispatch in with_pallas_fallback (ADVICE r4), so a Mosaic/
    VMEM rejection at gate-passing shapes retraces onto the XLA path."""
    nn = (1 << max_depth) - 1
    table_bytes = 4 * (2 * nn * max(T, 1)
                       + (1 << max_depth) * max(T, 1) * max(K, 1))
    return (pallas_histograms_enabled()
            and isinstance(n, int) and n >= min_rows
            and F <= PREDICT_KERNEL_MAX_F
            and max_depth <= PREDICT_KERNEL_MAX_DEPTH
            and K <= PREDICT_KERNEL_MAX_CLASSES
            and table_bytes <= 4e6)


def disable_pallas_histograms(exc: BaseException) -> bool:
    """Fit-level fallback (ADVICE r2): the probe compiles only a tiny
    shape, so Mosaic can still reject PRODUCTION shapes (n_bins·Fc off the
    128-lane grid, C·A blocks pressuring VMEM). When a tree-fit compile or
    dispatch dies with a kernel-looking error while the gate is on,
    disable the kernel process-wide and return True — callers retrace,
    which re-keys every family's ``trace_signature`` onto the XLA matmul
    path. Returns False (caller re-raises) for unrelated errors, when
    already off, or when ``TMOG_PALLAS=1`` explicitly forces the kernel
    (the user asked for it; failing loudly beats silently ignoring them).
    """
    global _PROBE
    if os.environ.get("TMOG_PALLAS", "").strip() == "1":
        return False
    if _PROBE is not True:
        return False
    text = repr(exc).lower()
    # kernel-specific markers only (ADVICE r3): a generic "internal:"
    # match let any unrelated XLA INTERNAL error permanently disable the
    # kernel process-wide and silently re-run the sweep on the slow path
    if not any(s in text for s in ("mosaic", "pallas", "vmem")):
        return False
    import warnings
    msg = (f"pallas histogram kernel failed at production shapes ({exc!r}); "
           "retracing the tree engine onto the XLA matmul path")
    logger.warning(msg)
    warnings.warn(msg)
    _PROBE = False
    _tk_tally("kernel_disables")
    return True


def with_pallas_fallback(build):
    """Run ``build()`` (a compile/fit thunk); on a kernel-shaped failure
    with the gate on, flip the gate off and run it once more."""
    try:
        return build()
    except Exception as e:  # lint: broad-except — Mosaic/backend rejection falls back to XLA
        if disable_pallas_histograms(e):
            return build()
        raise


def pallas_histograms_enabled() -> bool:
    """Trace-time gate for the tree engine. Default: on for TPU backends
    after a one-time compile probe, off elsewhere. ``TMOG_PALLAS=1``
    forces the kernel on (interpret mode off-TPU), ``0`` forces the XLA
    matmul path (see module docstring for the measurements)."""
    global _PROBE
    env = os.environ.get("TMOG_PALLAS", "").strip()
    if env == "1":
        return True
    if env == "0":
        return False
    if jax.default_backend() != "tpu":
        return False
    if _PROBE is None:
        # The probe must run EAGERLY — pallas_call cannot execute under an
        # enclosing trace (and ensure_compile_time_eval cannot evaluate
        # program_id). The gate is consulted from host code first
        # (ModelFamily._trace_extras during trace_signature), which caches
        # the result; if a direct fit consults it mid-trace before any
        # host-side call, fall back to XLA for that trace WITHOUT caching
        # so a later eager call can still probe. The lock keeps a
        # concurrent warm_probe_async from compiling the probe twice.
        from jax._src import core as _core
        detector = getattr(_core, "trace_state_clean", None)
        if detector is not None and not detector():
            return False
        with _PROBE_LOCK:
            return _probe_locked(detector)
    return _PROBE


def _probe_locked(detector) -> bool:
    global _PROBE
    if _PROBE is None:
        try:
            import numpy as np
            out = cumhist(
                jnp.ones((16, 3), jnp.float32),
                jnp.zeros((16,), jnp.int32),
                jnp.zeros((4, 16), jnp.int32),     # XbT: [F, n]
                2, 2, interpret=False)
            _PROBE = bool(np.asarray(out).shape == (2, 3, 2, 4))
            logger.info("pallas histogram kernel %s (compile probe)",
                        "enabled" if _PROBE else "disabled")
        except Exception as e:  # lint: broad-except — Mosaic/backend failure → XLA path
            if detector is None:
                # can't tell an eager failure from a mid-trace one (the
                # private trace-state API moved): fall back for THIS
                # consult but leave the probe open for a later eager call
                return False
            import warnings
            msg = (f"pallas histogram kernel unavailable ({e!r}); "
                   "falling back to the XLA matmul path")
            logger.warning(msg)
            warnings.warn(msg)
            _PROBE = False
    return _PROBE
