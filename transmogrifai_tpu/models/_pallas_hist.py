"""Pallas TPU kernel for the level-wise tree histogram build.

The tree engine's hot op (``_treefit._level_cumhist``) computes, per level,

    cum[s, c, t, f] = Σ_i 1[node_i = s] · 1[Xb_if ≤ t] · stats_ic

as one MXU matmul ``NSᵀ @ Bc`` with ``NS = one_hot(node) ⊗ stats`` and
``Bc = (bin ≤ t)``. The pure-XLA path must *materialize* NS ([n, A·C]) and
Bc ([n, B·F]) in HBM before the dot — at the 10M-row BASELINE config that
write+read traffic (n · (A·C + B·F) elements per level per tree) dominates
the histogram build, which is exactly the bandwidth problem SURVEY §2.9
assigns to a Pallas kernel (the xgboost4j/Rabit replacement: "Pallas
histogram-build & split kernels").

This kernel fuses operand construction into the matmul: row blocks of
``Xb``/``node``/``stats`` stream HBM→VMEM once (n · (F + C + 1) elements),
the one-hot expansion and the bin-threshold indicator are built in VMEM,
and the [A·C, B, Fc] output block stays resident in VMEM across the row
grid (TPU grids execute sequentially; the row axis is the fastest-varying
grid dim, so the accumulator is revisited, zero-initialised at row step 0).
Features are tiled over the slower grid axis to bound VMEM.

Numerics match the XLA path: bf16 operands (counts are sums of exact bf16
1.0s) with f32 accumulation when stats are f32; f64 (CPU tests) stays f64.

Measured on a v5e-1 at the synthetic-trees bench shape (n=200k, F=20,
B=32, A=128, C=3): 6.2 ms per histogram vs 13.4 ms for the XLA path
(2.2× — amortized over a scanned jit; single-call timings only measure
dispatch latency), and end-to-end the 200k-row RF+GBT+XGB CV sweep
trains in 21.5 s warm vs 29.4 s (27% faster), with slightly lower cold
time too (81 s vs 91 s — the fused kernel is less HLO than the
materialized matmul graphs). Identical selections and AuPR. The win
grows with rows: histogram HBM traffic is linear in n while the
fixed-shape level overheads are not.

Default: **on for TPU backends** (one-time compile probe; any Mosaic
failure falls back to the XLA path), off elsewhere. ``TMOG_PALLAS=1``
forces it on (interpret mode off-TPU), ``TMOG_PALLAS=0`` forces the XLA
path. The gate value is part of the CV executable cache key
(``ModelFamily.trace_signature``), so flipping it mid-process retraces.
"""
from __future__ import annotations

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

logger = logging.getLogger(__name__)

__all__ = ["cumhist", "route_level", "pallas_histograms_enabled"]

_PROBE: Optional[bool] = None


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _kernel(xb_ref, node_ref, stats_ref, o_ref, *, n_nodes, n_bins,
            mm_dtype):
    """Everything stays rank-2: Mosaic's vector layouts reject
    shape-changing reshapes whose minor dim is not 128-aligned, so the
    [bn, B, Fc] bin indicator is built flat ([bn, B·Fc] with threshold
    j // Fc and a B-fold column tile of Xb) and the channel axis is a
    static Python loop over C per-channel dots writing row slices."""
    rb = pl.program_id(1)

    @pl.when(rb == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    bn, Fc = xb_ref.shape
    C = stats_ref.shape[1]
    A, B = n_nodes, n_bins
    node = node_ref[:, 0]                                  # [bn]
    # one_hot(node): padded rows carry node = A → all-false → zero rows.
    oh = (node[:, None] == lax.broadcasted_iota(jnp.int32, (bn, A), 1)
          ).astype(jnp.float32).astype(stats_ref.dtype)
    # Bc = lower-triangular bin indicator (bin ≤ t) → left-cumulative sums
    # fall straight out of the dot; column j = t·Fc + f.
    xb_tile = jnp.concatenate([xb_ref[:]] * B, axis=1)     # [bn, B·Fc]
    thr = lax.broadcasted_iota(jnp.int32, (bn, B * Fc), 1) // Fc
    bc = (xb_tile <= thr).astype(jnp.float32).astype(mm_dtype)
    for c in range(C):
        ohc = (oh * stats_ref[:, c:c + 1]).astype(mm_dtype)
        o_ref[c * A:(c + 1) * A, :] += lax.dot_general(
            ohc, bc, (((0,), (0,)), ((), ())),
            preferred_element_type=o_ref.dtype)


def cumhist(stats: jnp.ndarray, node: jnp.ndarray, Xb: jnp.ndarray,
            n_nodes: int, n_bins: int, *, block_rows: int = 256,
            max_cols: int = 2048, interpret: Optional[bool] = None
            ) -> jnp.ndarray:
    """[n, C] stats + [n] node slots + [n, F] bins → [A, C, B, F] cumulative
    histograms. Drop-in replacement for the XLA matmul path in
    ``_treefit._level_cumhist`` (idle rows: node == n_nodes → zero)."""
    n, F = Xb.shape
    C = stats.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn = min(block_rows, _round_up(n, 8))
    Fc = max(1, min(F, max_cols // n_bins))
    n_pad = _round_up(n, bn)
    F_pad = _round_up(F, Fc)
    if n_pad != n:
        pad = n_pad - n
        Xb = jnp.concatenate([Xb, jnp.zeros((pad, F), Xb.dtype)])
        node = jnp.concatenate(
            [node, jnp.full((pad,), n_nodes, node.dtype)])
        stats = jnp.concatenate([stats, jnp.zeros((pad, C), stats.dtype)])
    if F_pad != F:
        Xb = jnp.concatenate(
            [Xb, jnp.zeros((n_pad, F_pad - F), Xb.dtype)], axis=1)
    mm_dtype = jnp.bfloat16 if stats.dtype == jnp.float32 else stats.dtype
    kern = functools.partial(_kernel, n_nodes=n_nodes, n_bins=n_bins,
                             mm_dtype=mm_dtype)
    nfb = F_pad // Fc
    out = pl.pallas_call(
        kern,
        grid=(nfb, n_pad // bn),                           # rows fastest
        in_specs=[
            pl.BlockSpec((bn, Fc), lambda fb, rb: (rb, fb)),
            pl.BlockSpec((bn, 1), lambda fb, rb: (rb, 0)),
            pl.BlockSpec((bn, C), lambda fb, rb: (rb, 0)),
        ],
        out_specs=pl.BlockSpec((C * n_nodes, n_bins * Fc),
                               lambda fb, rb: (0, fb)),
        out_shape=jax.ShapeDtypeStruct((C * n_nodes, nfb * n_bins * Fc),
                                       stats.dtype),
        interpret=interpret,
    )(Xb, node.reshape(-1, 1).astype(jnp.int32), stats)
    # rows are channel-major (c·A + a), columns (fb, t, f_local): restore
    # the channel-minor [A, C, B, F] layout the tree engine expects.
    out = out.reshape(C, n_nodes, nfb, n_bins, Fc)
    out = out.transpose(1, 0, 3, 2, 4).reshape(
        n_nodes, C, n_bins, F_pad)
    return out[..., :F]


def _route_kernel(xb_ref, slot_ref, g_ref, tab_ref, slot_out, g_out, *,
                  A_parent, A_child):
    """Per-row level routing, one streamed pass over [bn, F] bin rows.

    The XLA routing path materializes ~3 [n, A] f32 tensors per level
    (one-hot slot masks, per-row split-feature values, child selects) —
    at 1.8M rows × A=128 that is several GB of HBM traffic per level per
    tree, and it showed up as ~42% of device time in the round-3 profile
    (``BENCH_r03.json`` top ops are %while routing/binning state). Here
    the whole lookup chain (slot → split feature/threshold/children →
    compare → child slot) runs in VMEM with only [n, F] streamed in and
    two [n] vectors out.

    ``tab_ref`` rows: 0=f_idx, 1=t_idx(bin), 2=lchild, 3=rchild,
    4=do_split — all int32, one column per parent slot.
    """
    bn, F = xb_ref.shape
    slot = slot_ref[:, 0]                                   # [bn] i32
    g = g_ref[:, 0]
    oh = (slot[:, None] ==
          lax.broadcasted_iota(jnp.int32, (bn, A_parent), 1)
          ).astype(jnp.float32)                             # [bn, Ap]

    def sel(row):                                           # [bn] f32
        return jnp.sum(oh * tab_ref[row, :][None, :].astype(jnp.float32),
                       axis=1)
    f_sel = sel(0)
    t_sel = sel(1)
    l_sel = sel(2)
    r_sel = sel(3)
    ds_sel = sel(4)
    fiota = lax.broadcasted_iota(jnp.int32, (bn, F), 1)
    xv = jnp.sum(jnp.where(fiota == f_sel.astype(jnp.int32)[:, None],
                           xb_ref[:].astype(jnp.float32), 0.0), axis=1)
    right = ((xv > t_sel) & (ds_sel > 0.5)
             & (slot < A_parent)).astype(jnp.int32)
    child = jnp.where(right > 0, r_sel, l_sel).astype(jnp.int32)
    slot_out[:, 0] = jnp.where(slot >= A_parent, A_child, child)
    g_out[:, 0] = 2 * g + right


def route_level(Xb: jnp.ndarray, slot: jnp.ndarray, g: jnp.ndarray,
                f_idx, t_idx, lchild, rchild, do_split,
                A_parent: int, A_child: int, *,
                interpret: Optional[bool] = None):
    """(slot, g) → (slot', g') for one tree level (see ``_route_kernel``)."""
    n, F = Xb.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn = max(8, min(512, (1 << 21) // max(4 * F, 1) // 8 * 8))
    n_pad = _round_up(n, bn)
    if n_pad != n:
        pad = n_pad - n
        Xb = jnp.concatenate([Xb, jnp.zeros((pad, F), Xb.dtype)])
        slot = jnp.concatenate(
            [slot, jnp.full((pad,), A_parent, slot.dtype)])
        g = jnp.concatenate([g, jnp.zeros((pad,), g.dtype)])
    tab = jnp.stack([f_idx.astype(jnp.int32), t_idx.astype(jnp.int32),
                     lchild.astype(jnp.int32), rchild.astype(jnp.int32),
                     do_split.astype(jnp.int32)])           # [5, Ap]
    kern = functools.partial(_route_kernel, A_parent=A_parent,
                             A_child=A_child)
    slot2, g2 = pl.pallas_call(
        kern,
        grid=(n_pad // bn,),
        in_specs=[
            pl.BlockSpec((bn, F), lambda rb: (rb, 0)),
            pl.BlockSpec((bn, 1), lambda rb: (rb, 0)),
            pl.BlockSpec((bn, 1), lambda rb: (rb, 0)),
            pl.BlockSpec((5, A_parent), lambda rb: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((bn, 1), lambda rb: (rb, 0)),
                   pl.BlockSpec((bn, 1), lambda rb: (rb, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
                   jax.ShapeDtypeStruct((n_pad, 1), jnp.int32)],
        interpret=interpret,
    )(Xb, slot.reshape(-1, 1).astype(jnp.int32),
      g.reshape(-1, 1).astype(jnp.int32), tab)
    return slot2[:n, 0], g2[:n, 0]


def disable_pallas_histograms(exc: BaseException) -> bool:
    """Fit-level fallback (ADVICE r2): the probe compiles only a tiny
    shape, so Mosaic can still reject PRODUCTION shapes (n_bins·Fc off the
    128-lane grid, C·A blocks pressuring VMEM). When a tree-fit compile or
    dispatch dies with a kernel-looking error while the gate is on,
    disable the kernel process-wide and return True — callers retrace,
    which re-keys every family's ``trace_signature`` onto the XLA matmul
    path. Returns False (caller re-raises) for unrelated errors, when
    already off, or when ``TMOG_PALLAS=1`` explicitly forces the kernel
    (the user asked for it; failing loudly beats silently ignoring them).
    """
    global _PROBE
    if os.environ.get("TMOG_PALLAS", "").strip() == "1":
        return False
    if _PROBE is not True:
        return False
    text = repr(exc).lower()
    # kernel-specific markers only (ADVICE r3): a generic "internal:"
    # match let any unrelated XLA INTERNAL error permanently disable the
    # kernel process-wide and silently re-run the sweep on the slow path
    if not any(s in text for s in ("mosaic", "pallas", "vmem")):
        return False
    import warnings
    msg = (f"pallas histogram kernel failed at production shapes ({exc!r}); "
           "retracing the tree engine onto the XLA matmul path")
    logger.warning(msg)
    warnings.warn(msg)
    _PROBE = False
    return True


def with_pallas_fallback(build):
    """Run ``build()`` (a compile/fit thunk); on a kernel-shaped failure
    with the gate on, flip the gate off and run it once more."""
    try:
        return build()
    except Exception as e:
        if disable_pallas_histograms(e):
            return build()
        raise


def pallas_histograms_enabled() -> bool:
    """Trace-time gate for the tree engine. Default: on for TPU backends
    after a one-time compile probe, off elsewhere. ``TMOG_PALLAS=1``
    forces the kernel on (interpret mode off-TPU), ``0`` forces the XLA
    matmul path (see module docstring for the measurements)."""
    global _PROBE
    env = os.environ.get("TMOG_PALLAS", "").strip()
    if env == "1":
        return True
    if env == "0":
        return False
    if jax.default_backend() != "tpu":
        return False
    if _PROBE is None:
        # The probe must run EAGERLY — pallas_call cannot execute under an
        # enclosing trace (and ensure_compile_time_eval cannot evaluate
        # program_id). The gate is consulted from host code first
        # (ModelFamily._trace_extras during trace_signature), which caches
        # the result; if a direct fit consults it mid-trace before any
        # host-side call, fall back to XLA for that trace WITHOUT caching
        # so a later eager call can still probe.
        from jax._src import core as _core
        detector = getattr(_core, "trace_state_clean", None)
        if detector is not None and not detector():
            return False
        try:
            import numpy as np
            out = cumhist(
                jnp.ones((16, 3), jnp.float32),
                jnp.zeros((16,), jnp.int32),
                jnp.zeros((16, 4), jnp.int32),
                2, 2, interpret=False)
            _PROBE = bool(np.asarray(out).shape == (2, 3, 2, 4))
            logger.info("pallas histogram kernel %s (compile probe)",
                        "enabled" if _PROBE else "disabled")
        except Exception as e:  # Mosaic/backend failure → XLA path
            if detector is None:
                # can't tell an eager failure from a mid-trace one (the
                # private trace-state API moved): fall back for THIS
                # consult but leave the probe open for a later eager call
                return False
            import warnings
            msg = (f"pallas histogram kernel unavailable ({e!r}); "
                   "falling back to the XLA matmul path")
            logger.warning(msg)
            warnings.warn(msg)
            _PROBE = False
    return _PROBE
