"""Tree-ensemble stages: decision tree, random forest, GBT, XGBoost-style.

Parity targets (reference): ``OpDecisionTreeClassifier/Regressor``,
``OpRandomForestClassifier/Regressor`` (``core/.../impl/classification/
OpRandomForestClassifier.scala``), ``OpGBTClassifier/Regressor``,
``OpXGBoostClassifier/Regressor`` (``OpXGBoostClassifier.scala:46``) —
all fit natively with the JAX histogram engine (models/_treefit.py)
instead of wrapping MLlib / xgboost4j-JNI.

Grid batching: ALL grid hyperparameters (minInstancesPerNode, minInfoGain,
eta, minChildWeight, numTrees/numRound, subsample, maxDepth) are traced and
vmapped, so the whole (fold × grid) sweep is ONE compiled program per
family. ``maxDepth`` gates splitting per level inside the constant-shape
level scan (models/_treefit.py); the static scan length is the grid's max
depth, and shallower grid points route left through +inf thresholds below
their depth limit — exactly the tree the grouped-by-depth build produced,
at a small extra compute cost and a ~10× compile-time saving.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import register_stage
from . import _treefit as TF
from .base import (ModelFamily, PredictorEstimator, PredictorModel,
                   extract_xy)


def _strip_caches(p: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in p.items()
            if k not in ("train_node", "train_margin")}


#: row count beyond which the level loop unrolls with per-level slot
#: growth (grow_tree unroll=True) and the CV engine compiles one program
#: per static maxDepth: below it compile time dominates and the traced-
#: depth scan program is the right trade; above it the A=cap histogram
#: matmuls at shallow levels dominate device time (round-3 profile)
UNROLL_MIN_ROWS = 131_072

#: binned-input cache for device_prep: (id(X), shape, dtype, bins, mask)
#: → (weakref to X, prep). The weakref dies with the caller's array, so
#: the cached Xb/edges (and the id-keyed entry) release their HBM as soon
#: as the sweep drops the feature matrix — a strong ref here would pin
#: ~1.6 GB per 2M×100 entry for the life of the process
_PREP_CACHE: Dict[Any, Any] = {}

#: jitted compute_bins per (n_bins, mask-bytes): jit's own shape cache
#: handles retraces; a fresh jax.jit(lambda) per device_prep call would
#: recompile the same binning program on every cache miss (per-fold CV)
_BIN_FNS: Dict[Any, Any] = {}


def _tree_dtype(X) -> Any:
    """Prediction dtype for a fit/predict input that may be the prebinned
    dict (no raw X on the CV path)."""
    return X["edges"].dtype if isinstance(X, dict) else X.dtype


def _tree_rows(X) -> int:
    if isinstance(X, dict):
        return (X["XbT"].shape[1] if "XbT" in X else X["Xb"].shape[0])
    return X.shape[0]


def _sibling_on() -> bool:
    """Normalized sibling-subtraction gate (grow_tree's semantics:
    anything but '0' enables) — keying the raw string would fragment the
    executable cache across equivalent spellings."""
    import os
    return os.environ.get("TMOG_SIBLING", "1") != "0"


def pad_rows_to(n_pad: int, *arrs):
    """Zero-pad leading (row) axis to ``n_pad`` — device_prep may have
    ROW_ALIGN-padded the binned matrix; y/weights/masks must follow.
    Zero weights keep pad rows out of every histogram and metric."""
    out = []
    for a in arrs:
        n = a.shape[0]
        out.append(a if n == n_pad else jnp.concatenate(
            [a, jnp.zeros((n_pad - n,) + a.shape[1:], a.dtype)]))
    return out


def detect_binary_columns(X: np.ndarray) -> Optional[np.ndarray]:
    """Host-side [F] bool: columns whose values are all in {0, 1}.

    AutoML feature matrices are dominated by one-hot/indicator columns
    (Titanic: 470 of 498); the tree engine gives those a 2-bin histogram
    block instead of 32 quantile bins (~8× less histogram work)."""
    mask = np.all((X == 0.0) | (X == 1.0), axis=0)
    return mask if mask.any() else None

__all__ = [
    "TreeEnsembleModel",
    "RandomForestFamily", "DecisionTreeFamily", "GBTFamily", "XGBoostFamily",
    "OpDecisionTreeClassifier", "OpDecisionTreeRegressor",
    "OpRandomForestClassifier", "OpRandomForestRegressor",
    "OpGBTClassifier", "OpGBTRegressor",
    "OpXGBoostClassifier", "OpXGBoostRegressor",
]

_MAX_DEPTH_DEFAULT = (3, 6, 12)        # DefaultSelectorParams.MaxDepth
_MIN_INST_DEFAULT = (10, 100)          # .MinInstancesPerNode
_MIN_GAIN_DEFAULT = (0.001, 0.01, 0.1)  # .MinInfoGain


# ---------------------------------------------------------------------------
# Fitted model
# ---------------------------------------------------------------------------

@register_stage
class TreeEnsembleModel(PredictorModel):
    """Stacked level-order trees + per-tree weights; kind selects the head."""

    operation_name = "trees"

    def __init__(self, kind: str = "rf_classification", n_classes: int = 2,
                 max_depth: int = 6, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.kind = kind
        self.n_classes = int(n_classes)
        self.max_depth = int(max_depth)
        self.trees: Dict[str, np.ndarray] = {}

    def predict_arrays(self, X):
        """Scoring-path Pallas fallback (ADVICE r4): the routed-predict
        kernel is gated by ``predict_kernel_ok`` but has no fit-style
        retry wrapper — a Mosaic/VMEM rejection at gate-passing
        production shapes would otherwise fail scoring outright after a
        successful (possibly hours-long) fit. On a kernel-shaped compile
        failure the gate flips off process-wide and the predict retraces
        onto the XLA gather path."""
        from ._pallas_hist import with_pallas_fallback
        base = super().predict_arrays
        return with_pallas_fallback(lambda: base(X))

    def predict_device(self, Xd):
        """Device-side Prediction triple (pure jax; export/serving path)."""
        p = {k: jnp.asarray(v) for k, v in self.trees.items()}
        if self.kind == "rf_classification":
            out = TF.predict_rf_classification(p, Xd, self.max_depth,
                                               self.n_classes)
        elif self.kind == "rf_regression":
            out = TF.predict_rf_regression(p, Xd, self.max_depth)
        elif self.kind == "gbt_classification":
            out = TF.predict_margin_classification(p, Xd, self.max_depth,
                                                   margin_scale=2.0)
        elif self.kind == "xgb_classification":
            out = TF.predict_margin_classification(p, Xd, self.max_depth,
                                                   margin_scale=1.0)
        else:   # gbt_regression / xgb_regression
            out = TF.predict_margin_regression(p, Xd, self.max_depth)
        return out

    def get_model_state(self):
        state = {f"tree_{k}": np.asarray(v) for k, v in self.trees.items()}
        state["kind"] = self.kind
        return state

    def apply_model_state(self, state) -> None:
        self.trees = {k[5:]: np.asarray(v) for k, v in state.items()
                      if k.startswith("tree_")}
        if "kind" in state:
            self.kind = str(state["kind"])

    def summary(self):
        t = self.trees.get("tree_w")
        return {"model": "TreeEnsemble", "kind": self.kind,
                "numTrees": int(t.shape[0]) if t is not None else 0,
                "maxDepth": self.max_depth}


# ---------------------------------------------------------------------------
# Families
# ---------------------------------------------------------------------------

class _TreeFamilyBase(ModelFamily):
    """Shared single-program grid batching (maxDepth traced)."""

    task = "classification"
    n_bins = 32                      # DefaultSelectorParams.MaxBin

    def __init__(self, grid=None, task: Optional[str] = None,
                 n_classes: int = 2, seed: int = 7,
                 max_active_nodes: int = 128,
                 tree_chunk: Optional[int] = None, **fixed):
        super().__init__(grid, **fixed)
        if task is not None:
            self.task = task
        self.n_classes = n_classes
        self.seed = seed
        self.max_active_nodes = max_active_nodes
        #: bootstrap trees grown per scan step (RF/DT only — boosting is
        #: inherently sequential). >1 batches the per-level histogram and
        #: routing work of several trees into one device step (200k-row
        #: RF sweep: 28.1s → 20.4s warm at chunk 4) at the cost of
        #: ~tree_chunk× the level transients. None = auto: the CV
        #: engine's HBM budget picks it (tuning._auto_chunks); an int
        #: pins it (1 disables batching).
        self.tree_chunk = tree_chunk
        #: grid points fitted concurrently (None = whole grid vmapped).
        #: The CV engine sets this from its HBM budget at large row counts:
        #: each in-flight grid instance carries ~rows × max_active_nodes
        #: of routing transients, so the (fold × grid) product must shrink
        #: as rows grow. lax.map(batch_size) serializes chunks while still
        #: vmapping within one.
        self.grid_chunk: Optional[int] = None
        #: STATIC host-side [F] bool marking one-hot indicator columns;
        #: set by the caller (ModelSelector / estimator) before fit so the
        #: histogram engine gives those columns a 2-bin block (see
        #: _treefit.prepare_bins). None → single full-width bin block.
        self.binary_mask = None

    #: keys whose stacked values are traced & vmapped
    traced_keys: List[str] = []

    #: the CV engine may group grid points by maxDepth and compile one
    #: static-depth unrolled program per group at large row counts
    supports_static_depth = True

    def _trace_extras(self):
        # trace-time toggles that change the tree engine's emitted
        # program must key this family's executable cache entries
        import os

        from ._pallas_hist import (pallas_histograms_enabled,
                                   sparse01_enabled, split_scan_enabled)
        from ._treefit import active_feature_shards, active_tree_mesh
        tm = active_tree_mesh()
        return (("__pallas__", pallas_histograms_enabled()),
                ("__sibling__", _sibling_on()),
                ("__sparse01__", sparse01_enabled()),
                ("__split_scan__", split_scan_enabled()),
                ("__tree_mesh__", None if tm is None else
                 (int(tm.shape.get("data", 1)),
                  int(tm.shape.get("grid", 1)))),
                ("__feature_shards__", active_feature_shards()))

    def _cache_bytes_per_row(self) -> int:
        """Per-row bytes of fit-time prediction caches an in-flight
        instance holds (budget input for _auto_chunks): RF keeps the
        [T, n] train-node routing, boosting one [n] margin."""
        if self._head() == "rf":
            return 4 * self._static_trees()
        return 4

    def analytic_flops(self, n_rows: int, n_features: int,
                       static_depth=None) -> float:
        """Estimated MXU FLOPs of ONE (fold, grid-point) fit — the
        histogram dots run inside Pallas custom calls, which XLA cost
        analysis cannot see, so the MFU accounting (tuning.DEVICE_FLOPS)
        adds this analytic term per dispatch. Dominant term only: per
        tree per level, the [A_d·C, n] × [n, Σ_b nb·F_b] dot — 2-bin
        indicator blocks counted at their true width via binary_mask;
        kernel lane padding excluded (unpadded n, errs low at small n);
        routing/predict kernels are comparatively negligible."""
        D = int(static_depth) if static_depth else self.global_depth()
        cap = max(2, min(self.max_active_nodes, 1 << max(D - 1, 1)))
        if static_depth:
            # unrolled driver: per-level slot growth; with sibling
            # subtraction (the default) levels ≥ 1 histogram only the
            # LEFT children — half the slots
            cap -= cap % 2
            scale = 0.5 if _sibling_on() else 1.0
            a_sum = 1 + scale * sum(min(1 << d, cap)
                                    for d in range(1, D))
        else:
            # scan driver: constant cap slots at every level
            a_sum = cap * D
        # mixed-bin col_blocks: indicator columns get 2-bin histograms
        # (Titanic: 470 of 498 columns — treating them at n_bins
        # overestimated the dispatched FLOPs ~9×)
        bm = self.binary_mask
        if bm is not None:
            nb_bin = int(np.asarray(bm, bool).sum())
            bin_feat = (self.n_bins * (n_features - nb_bin)
                        + 2 * nb_bin)
        else:
            bin_feat = self.n_bins * n_features
        T = self._static_trees()
        return (2.0 * n_rows * a_sum * self._stat_channels()
                * bin_feat * T)

    def _stat_channels(self) -> int:
        # RF/DT: per-class weights + count (gini) or variance stats
        return (self.n_classes + 1 if self.task == "classification" else 4)

    def _fit_single(self, X, y, w, depth: int, n_trees: int,
                    traced: Dict[str, Any], prebinned=None,
                    unroll: bool = False) -> Dict[str, Any]:
        raise NotImplementedError

    def _static_trees(self) -> int:
        raise NotImplementedError

    def _stacked_col(self, stacked, key):
        if key in stacked:
            return stacked[key]          # may be a tracer (jit argument)
        # default column sized to the PASSED grid batch — the CV engine
        # may hand fit_batch a chunk of the grid, not the whole of it
        gsize = (next(iter(stacked.values())).shape[0] if stacked
                 else self.grid_size())
        return np.full((gsize,), self.param_defaults()[key])

    def global_depth(self) -> int:
        return int(max(int(g.get("maxDepth",
                                 self.param_defaults()["maxDepth"]))
                       for g in self.grid))

    def device_prep(self, Xd):
        """Bin the feature matrix ONCE per (data, binning config) and
        return the ``{"Xb", "edges"}`` dict fit_batch/predict_batch accept
        in place of raw X. Round 3 recomputed quantile edges + binarize
        inside every dispatched (fold × grid-chunk) fit — ~13% of the
        2M-row device profile. Cached across families/folds sharing the
        same device array (strong ref keeps ``id`` stable)."""
        import functools
        import weakref

        from ._pallas_hist import ROW_ALIGN, pallas_histograms_enabled
        from ._treefit import active_tree_mesh
        bm = self.binary_mask
        pallas_on = pallas_histograms_enabled()
        # under a tree-mesh scope the padded row count must ALSO split
        # evenly over the mesh data axis (shard_map's even-sharding
        # requirement — the pad_rows discipline applied to the binned
        # matrix). ROW_ALIGN already divides by every power-of-two data
        # axis; the multiply covers odd device counts ONLY. Padding to
        # ROW_ALIGN×d always would leave each shard's block perfectly
        # lane-aligned (saving the kernels a small per-level re-pad of
        # the shard remainder), but it would also change the padded
        # length — and the bootstrap uniforms are drawn at the PADDED
        # shape, so the sharded sweep would stop being bit-identical to
        # the single-device sweep. Parity wins; the remainder re-pad is
        # one [F, <ROW_ALIGN] zero concat per level-block.
        tm = active_tree_mesh()
        dshards = int(tm.shape["data"]) if tm is not None else 1
        align = (ROW_ALIGN if ROW_ALIGN % dshards == 0
                 else ROW_ALIGN * dshards)
        mkey = None if bm is None else np.asarray(bm, bool).tobytes()
        key = (id(Xd), tuple(Xd.shape), str(Xd.dtype), self.n_bins, mkey,
               pallas_on, align)
        hit = _PREP_CACHE.get(key)
        if hit is not None and hit[0]() is not None:
            return hit[1]

        def bins_padded(X, n_bins=self.n_bins, binary_mask=bm):
            Xb, edges = TF.compute_bins(X, n_bins, binary_mask)
            if not pallas_on:
                return {"Xb": Xb, "edges": edges}
            # kernel path: TRANSPOSED feature-major bins (lane-compact —
            # a [n, 20] i32 matrix is 6.4× larger physically than its
            # [20, n] transpose under TPU (8,128) tiling), rows padded to
            # ROW_ALIGN (× the mesh data axis when it does not divide)
            # once so the kernels never re-pad per level. Pad rows carry
            # zero weights downstream, so they never reach a histogram;
            # edges come from the real rows above.
            XbT = Xb.T
            n = XbT.shape[1]
            n_pad = -(-n // align) * align
            if n_pad != n:
                XbT = jnp.concatenate(
                    [XbT, jnp.zeros((XbT.shape[0], n_pad - n),
                                    XbT.dtype)], axis=1)
            return {"XbT": XbT, "edges": edges}

        fkey = (self.n_bins, mkey, pallas_on, align)
        fn = _BIN_FNS.get(fkey)
        if fn is None:
            fn = jax.jit(bins_padded)
            while len(_BIN_FNS) >= 16:
                _BIN_FNS.pop(next(iter(_BIN_FNS)))
            _BIN_FNS[fkey] = fn
        prep = fn(Xd)
        while len(_PREP_CACHE) >= 4:
            _PREP_CACHE.pop(next(iter(_PREP_CACHE)))    # FIFO evict
        try:
            ref = weakref.ref(Xd, lambda _r, k=key: _PREP_CACHE.pop(k, None))
        except TypeError:       # non-weakref-able input: don't cache —
            return prep         # a strong ref would pin X + Xb for life
        _PREP_CACHE[key] = (ref, prep)
        return prep

    def fit_prepared(self, Xd, y, w, grid=None):
        """Bin once + (single-depth grids at large n) static-depth
        unrolled fit — the one place encoding that decision, shared by
        the standalone estimator stages and the selector's final refit.
        Returns (params, Xarg) with Xarg reusable for on_train predicts."""
        grid = grid if grid is not None else self.stack_grid()
        Xarg = self.device_prep(Xd)
        y, w = pad_rows_to(_tree_rows(Xarg), jnp.asarray(y),
                           jnp.asarray(w))
        dflt = self.param_defaults().get("maxDepth", 0)
        depths = {int(g.get("maxDepth", dflt)) for g in self.grid}
        sd = (depths.pop() if len(depths) == 1
              and Xd.shape[0] >= UNROLL_MIN_ROWS else None)
        params = jax.jit(lambda X, y, w: self.fit_batch(
            X, y, w, grid, static_depth=sd))(Xarg, y, w)
        return params, Xarg

    def _prebinned_of(self, X):
        """(prebinned tuple or None, raw-X or None) from a fit input that
        is either raw [n, F] or a device_prep dict (whose bin matrix may
        be the transposed kernel layout)."""
        if isinstance(X, dict):
            edges = X["edges"]
            cb = TF.make_col_blocks(edges, self.n_bins, self.binary_mask)
            if "XbT" in X:
                return (X["XbT"], edges, cb, True), None
            return (X["Xb"], edges, cb, False), None
        return None, X

    def fit_batch(self, X, y, w, stacked, static_depth: Optional[int] = None):
        prebinned, Xraw = self._prebinned_of(X)
        unroll = static_depth is not None
        D = int(static_depth) if unroll else self.global_depth()
        n_trees = self._static_trees()
        traced = {k: jnp.asarray(self._stacked_col(stacked, k), dtype=y.dtype)
                  for k in self.traced_keys}
        if not unroll:
            # traced depth gate shares one program across grid depths;
            # static-depth chunks (all points at depth D) need no gate
            traced["maxDepth"] = jnp.asarray(
                self._stacked_col(stacked, "maxDepth"), jnp.int32)

        def fit_one(tr):
            return self._fit_single(Xraw, y, w, D, n_trees, tr,
                                    prebinned=prebinned, unroll=unroll)
        if self.grid_chunk and self.grid_chunk < self.grid_size():
            from jax import lax
            return lax.map(fit_one, traced,
                           batch_size=int(self.grid_chunk))
        return jax.vmap(fit_one)(traced)

    def predict_batch(self, params, X, on_train: bool = False):
        """Batched Prediction triple for the grid.

        With ``on_train=True`` (the CV engine's path, asserting ``X`` IS
        the training matrix the fit saw) predictions come straight from
        the fit-time caches — leaf gathers over ``train_node`` (RF) or a
        sigmoid over ``train_margin`` (boosting) — skipping per-level tree
        routing, which runs on the TPU scalar core and dominated the CV
        sweep. Otherwise full routing. Both paths share the head math in
        ``_treefit`` (rf_head / margin_head).
        """
        D = self.global_depth()
        head = self._head()
        dt = _tree_dtype(X)
        if on_train and head == "rf" and "train_node" in params:
            from jax import lax

            n = _tree_rows(X)

            def fn(p):
                # trees accumulate in byte-capped chunks, one CLASS
                # CHANNEL at a time: gathering [c, L, K] leaves in one op
                # emits a K-minor result that TPU tiling pads 64× for
                # binary K=2 (a 10 GB HLO temp under the fold×chunk vmap
                # at 2M rows); per-channel [L]-table gathers keep every
                # intermediate [c, n] lane-compact
                leaf, node, tw = p["leaf"], p["train_node"], p["tree_w"]
                T_, L, K = leaf.shape
                c = max(1, min(T_, int(64e6 // max(n * 4, 1))))
                pad = (-T_) % c
                if pad:
                    leaf = jnp.concatenate(
                        [leaf, jnp.zeros((pad, L, K), leaf.dtype)])
                    node = jnp.concatenate(
                        [node, jnp.zeros((pad, n), node.dtype)])
                    tw = jnp.concatenate(
                        [tw, jnp.zeros((pad,), tw.dtype)])
                nc = (T_ + pad) // c

                # classification leaves are per-leaf probabilities that
                # sum to 1, and on the TRAIN matrix every row lands in a
                # non-empty leaf — so the last class needs no gather:
                # acc_{K-1} = Σ tree_w − Σ_{k<K-1} acc_k. The [n]-table
                # gathers run on the scalar core; this cuts them by 1/K.
                k_gather = K - 1 if self.task == "classification" else K

                def body(accs, tl):
                    lf, nd, w_t = tl           # [c, L, K], [c, n], [c]
                    outs = []
                    for k in range(k_gather):
                        vals = jax.vmap(
                            lambda l1, m, k=k: l1[:, k][m])(lf, nd)
                        outs.append(accs[k]
                                    + jnp.einsum("t,tn->n", w_t, vals))
                    return tuple(outs), None
                accs, _ = lax.scan(
                    body,
                    tuple(jnp.zeros((n,), leaf.dtype)
                          for _ in range(k_gather)),
                    (leaf.reshape(nc, c, L, K), node.reshape(nc, c, n),
                     tw.reshape(nc, c)))
                accs = list(accs)
                if k_gather < K:
                    accs.append(jnp.sum(tw) - sum(accs))
                # the stack below folds away in the fused metric program
                # (the device metric slices one class column back out)
                return TF.rf_head(jnp.stack(accs, axis=-1), dt, self.task)
            return jax.vmap(fn)(params)
        if on_train and head in ("gbt", "xgb") and "train_margin" in params:
            scale = 2.0 if head == "gbt" else 1.0

            def fn(p):
                return TF.margin_head(p["train_margin"], scale, dt,
                                      self.task)
            return jax.vmap(fn)(params)
        assert not isinstance(X, dict), \
            "routed prediction needs the raw feature matrix, not the " \
            "prebinned dict (on_train caches missing?)"
        if self.task == "classification":
            if head == "rf":
                fn = lambda p: TF.predict_rf_classification(
                    _strip_caches(p), X, D, self.n_classes)
            else:
                scale = 2.0 if head == "gbt" else 1.0
                fn = lambda p: TF.predict_margin_classification(
                    _strip_caches(p), X, D, margin_scale=scale)
        else:
            if head == "rf":
                fn = lambda p: TF.predict_rf_regression(_strip_caches(p), X, D)
            else:
                fn = lambda p: TF.predict_margin_regression(
                    _strip_caches(p), X, D)
        return jax.vmap(fn)(params)

    def _head(self) -> str:
        return "rf"

    def realize(self, params, hparams) -> TreeEnsembleModel:
        kind = f"{self._head()}_{self.task}"
        model = TreeEnsembleModel(kind=kind, n_classes=self.n_classes,
                                  max_depth=self.global_depth())
        model.trees = {k: np.asarray(v) for k, v in params.items()
                       if k not in ("train_node", "train_margin")}
        return model


class RandomForestFamily(_TreeFamilyBase):
    """RF grid = MaxDepth × MinInstancesPerNode × MinInfoGain
    (BinaryClassificationModelSelector.scala:52-128), numTrees = 50."""

    name = "OpRandomForestClassifier"
    default_grid = [
        {"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg}
        for d in _MAX_DEPTH_DEFAULT for mi in _MIN_INST_DEFAULT
        for mg in _MIN_GAIN_DEFAULT
    ]
    traced_keys = ["minInstancesPerNode", "minInfoGain", "numTrees",
                   "subsamplingRate"]

    def __init__(self, grid=None, task: Optional[str] = None,
                 n_classes: int = 2, num_trees: int = 50, seed: int = 7,
                 per_node_features: bool = True, **fixed):
        super().__init__(grid, task=task, n_classes=n_classes, seed=seed,
                         **fixed)
        self.num_trees = num_trees
        #: Spark-parity per-node candidate feature sampling (MLlib
        #: featureSubsetStrategy); False reverts to per-tree masks.
        #: Lands in trace_signature via __dict__, so flipping it re-keys
        #: the compiled-executable cache.
        self.per_node_features = per_node_features
        if task == "regression":
            self.name = "OpRandomForestRegressor"
            self.task = "regression"

    def param_defaults(self):
        return {"maxDepth": 6, "minInstancesPerNode": 10,
                "minInfoGain": 0.001, "numTrees": self.num_trees,
                "subsamplingRate": 1.0}

    def _static_trees(self) -> int:
        return int(max(int(g.get("numTrees", self.num_trees))
                       for g in self.grid))

    def _fit_single(self, X, y, w, depth, n_trees, tr, prebinned=None,
                    unroll=False):
        return TF.fit_forest(
            X, y, w, task=self.task, n_classes=self.n_classes,
            n_trees=n_trees, max_depth=depth, n_bins=self.n_bins,
            min_instances=tr["minInstancesPerNode"],
            min_info_gain=tr["minInfoGain"],
            num_trees_used=tr["numTrees"],
            subsample_rate=tr["subsamplingRate"],
            depth_limit=tr.get("maxDepth"),
            max_active_nodes=self.max_active_nodes,
            tree_chunk=self.tree_chunk
            or getattr(self, "_tree_chunk_auto", 1),
            binary_mask=self.binary_mask, seed=self.seed,
            per_node_features=getattr(self, "per_node_features", True),
            prebinned=prebinned, unroll=unroll)


class DecisionTreeFamily(RandomForestFamily):
    """Single unbagged tree, all features (OpDecisionTreeClassifier);
    inherits the RF MaxDepth × MinInstancesPerNode × MinInfoGain grid."""

    name = "OpDecisionTreeClassifier"

    def __init__(self, grid=None, task: Optional[str] = None,
                 n_classes: int = 2, seed: int = 7, **fixed):
        super().__init__(grid, task=task, n_classes=n_classes, num_trees=1,
                         seed=seed, **fixed)
        self.name = ("OpDecisionTreeRegressor" if self.task == "regression"
                     else "OpDecisionTreeClassifier")

    def param_defaults(self):
        d = super().param_defaults()
        d["numTrees"] = 1
        return d

    def _static_trees(self) -> int:
        return 1


class GBTFamily(_TreeFamilyBase):
    """GBT grid = MaxDepth × MinInstancesPerNode × MinInfoGain,
    maxIter=20 rounds, stepSize=0.1 (DefaultSelectorParams)."""

    name = "OpGBTClassifier"
    default_grid = [
        {"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg}
        for d in _MAX_DEPTH_DEFAULT for mi in _MIN_INST_DEFAULT
        for mg in _MIN_GAIN_DEFAULT
    ]
    traced_keys = ["minInstancesPerNode", "minInfoGain", "maxIter",
                   "stepSize"]

    def __init__(self, grid=None, task: Optional[str] = None,
                 n_classes: int = 2, max_iter: int = 20, seed: int = 7,
                 **fixed):
        super().__init__(grid, task=task, n_classes=n_classes, seed=seed,
                         **fixed)
        self.max_iter = max_iter
        if task == "regression":
            self.name = "OpGBTRegressor"
            self.task = "regression"

    def param_defaults(self):
        return {"maxDepth": 6, "minInstancesPerNode": 10,
                "minInfoGain": 0.001, "maxIter": self.max_iter,
                "stepSize": 0.1}

    def _stat_channels(self) -> int:
        return 4                 # variance stats on residuals, any task

    def _head(self) -> str:
        return "gbt"

    def _static_trees(self) -> int:
        return int(max(int(g.get("maxIter", self.max_iter))
                       for g in self.grid))

    def _fit_single(self, X, y, w, depth, n_trees, tr, prebinned=None,
                    unroll=False):
        return TF.fit_gbt(
            X, y, w, task=self.task, n_rounds=n_trees, max_depth=depth,
            n_bins=self.n_bins, min_instances=tr["minInstancesPerNode"],
            min_info_gain=tr["minInfoGain"], step_size=tr["stepSize"],
            num_rounds_used=tr["maxIter"], depth_limit=tr.get("maxDepth"),
            max_active_nodes=self.max_active_nodes,
            binary_mask=self.binary_mask,
            prebinned=prebinned, unroll=unroll)


class XGBoostFamily(_TreeFamilyBase):
    """XGB grid = NumRound × Eta × MaxDepth × MinChildWeight
    (BinaryClassificationModelSelector.scala:119-124)."""

    name = "OpXGBoostClassifier"
    default_grid = [
        {"maxDepth": d, "eta": e, "minChildWeight": mc, "numRound": 100}
        for d in _MAX_DEPTH_DEFAULT for e in (0.1, 0.3)
        for mc in (1.0, 5.0, 10.0)
    ]
    traced_keys = ["eta", "minChildWeight", "numRound"]

    def __init__(self, grid=None, task: Optional[str] = None,
                 n_classes: int = 2, reg_lambda: float = 1.0, seed: int = 7,
                 **fixed):
        super().__init__(grid, task=task, n_classes=n_classes, seed=seed,
                         **fixed)
        self.reg_lambda = reg_lambda
        if task == "regression":
            self.name = "OpXGBoostRegressor"
            self.task = "regression"

    def param_defaults(self):
        return {"maxDepth": 6, "eta": 0.3, "minChildWeight": 1.0,
                "numRound": 100}

    def _stat_channels(self) -> int:
        return 3                 # (g, h, count), any task

    def _head(self) -> str:
        return "xgb"

    def _static_trees(self) -> int:
        return int(max(int(g.get("numRound", 100)) for g in self.grid))

    def _fit_single(self, X, y, w, depth, n_trees, tr, prebinned=None,
                    unroll=False):
        return TF.fit_xgb(
            X, y, w, task=self.task, n_rounds=n_trees, max_depth=depth,
            n_bins=self.n_bins, eta=tr["eta"], lam=self.reg_lambda,
            min_child_weight=tr["minChildWeight"],
            num_rounds_used=tr["numRound"], depth_limit=tr.get("maxDepth"),
            max_active_nodes=self.max_active_nodes,
            binary_mask=self.binary_mask,
            prebinned=prebinned, unroll=unroll)


# ---------------------------------------------------------------------------
# Standalone estimator stages
# ---------------------------------------------------------------------------

class _TreeEstimatorBase(PredictorEstimator):
    family_cls = RandomForestFamily
    task = "classification"

    #: (data, grid) mesh this estimator's fit shards over — None
    #: resolves to the process-default mesh, ``False`` forces the
    #: unsharded path; ``Workflow._resolve_mesh`` assigns it like it
    #: assigns ModelSelector meshes, so standalone tree fits scale with
    #: the mesh too, not just the CV fold grid
    mesh = None

    def _family(self, n_classes: int) -> _TreeFamilyBase:
        raise NotImplementedError

    def fit_columns(self, store) -> TreeEnsembleModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        n_classes = max(int(y.max()) + 1 if len(y) else 2, 2) \
            if self.task == "classification" else 2
        fam = self._family(n_classes)
        fam.binary_mask = detect_binary_columns(X)
        Xd = jnp.asarray(X, jnp.float32)
        from ._pallas_hist import with_pallas_fallback
        from ._treefit import tree_mesh_scope
        from ..parallel.mesh import process_default_mesh
        # a workflow-managed assignment (_mesh_auto) wins even when it
        # resolved to None (mesh=False forces unsharded); only a stage
        # nobody ever assigned resolves the process default itself
        if self.mesh is None and not getattr(self, "_mesh_auto", False):
            mesh = process_default_mesh()
        else:
            mesh = self.mesh
        with tree_mesh_scope(mesh):
            params, _ = with_pallas_fallback(
                lambda: fam.fit_prepared(
                    Xd, jnp.asarray(y, jnp.float32),
                    jnp.ones((X.shape[0],), jnp.float32)))
        single = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], params)
        return fam.realize(single, fam.grid[0])


@register_stage
class OpRandomForestClassifier(_TreeEstimatorBase):
    operation_name = "randomForest"

    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsampling_rate: float = 1.0,
                 max_active_nodes: int = 128,
                 seed: int = 7, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.max_active_nodes = max_active_nodes
        self.seed = seed

    def _family(self, n_classes):
        return RandomForestFamily(
            grid=[{"maxDepth": self.max_depth,
                   "minInstancesPerNode": self.min_instances_per_node,
                   "minInfoGain": self.min_info_gain,
                   "numTrees": self.num_trees,
                   "subsamplingRate": self.subsampling_rate}],
            task=self.task, n_classes=n_classes, num_trees=self.num_trees,
            max_active_nodes=self.max_active_nodes, seed=self.seed)


@register_stage
class OpRandomForestRegressor(OpRandomForestClassifier):
    operation_name = "randomForestReg"
    task = "regression"


@register_stage
class OpDecisionTreeClassifier(_TreeEstimatorBase):
    operation_name = "decisionTree"

    def __init__(self, max_depth: int = 5, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, max_active_nodes: int = 128,
                 seed: int = 7, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_depth = max_depth
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.max_active_nodes = max_active_nodes
        self.seed = seed

    def _family(self, n_classes):
        return DecisionTreeFamily(
            grid=[{"maxDepth": self.max_depth,
                   "minInstancesPerNode": self.min_instances_per_node,
                   "minInfoGain": self.min_info_gain}],
            task=self.task, n_classes=n_classes,
            max_active_nodes=self.max_active_nodes, seed=self.seed)


@register_stage
class OpDecisionTreeRegressor(OpDecisionTreeClassifier):
    operation_name = "decisionTreeReg"
    task = "regression"


@register_stage
class OpGBTClassifier(_TreeEstimatorBase):
    operation_name = "gbtClassifier"

    def __init__(self, max_iter: int = 20, max_depth: int = 5,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 step_size: float = 0.1, max_active_nodes: int = 128,
                 seed: int = 7, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_iter = max_iter
        self.max_depth = max_depth
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.step_size = step_size
        self.max_active_nodes = max_active_nodes
        self.seed = seed

    def _family(self, n_classes):
        return GBTFamily(
            grid=[{"maxDepth": self.max_depth,
                   "minInstancesPerNode": self.min_instances_per_node,
                   "minInfoGain": self.min_info_gain,
                   "maxIter": self.max_iter, "stepSize": self.step_size}],
            task=self.task, n_classes=n_classes, max_iter=self.max_iter,
            max_active_nodes=self.max_active_nodes, seed=self.seed)


@register_stage
class OpGBTRegressor(OpGBTClassifier):
    operation_name = "gbtRegressor"
    task = "regression"


@register_stage
class OpXGBoostClassifier(_TreeEstimatorBase):
    operation_name = "xgbClassifier"

    def __init__(self, num_round: int = 100, max_depth: int = 6,
                 eta: float = 0.3, min_child_weight: float = 1.0,
                 reg_lambda: float = 1.0, max_active_nodes: int = 128,
                 seed: int = 7, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_round = num_round
        self.max_depth = max_depth
        self.eta = eta
        self.min_child_weight = min_child_weight
        self.reg_lambda = reg_lambda
        self.max_active_nodes = max_active_nodes
        self.seed = seed

    def _family(self, n_classes):
        return XGBoostFamily(
            grid=[{"maxDepth": self.max_depth, "eta": self.eta,
                   "minChildWeight": self.min_child_weight,
                   "numRound": self.num_round}],
            task=self.task, n_classes=n_classes,
            reg_lambda=self.reg_lambda,
            max_active_nodes=self.max_active_nodes, seed=self.seed)


@register_stage
class OpXGBoostRegressor(OpXGBoostClassifier):
    operation_name = "xgbRegressor"
    task = "regression"
