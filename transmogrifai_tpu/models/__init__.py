from .base import ModelFamily, PredictorEstimator, PredictorModel  # noqa: F401
from .linear import (OpLogisticRegression, LogisticRegressionModel,  # noqa: F401
                     LogisticRegressionFamily, OpLinearRegression,
                     LinearRegressionModel, LinearRegressionFamily,
                     OpNaiveBayes, NaiveBayesModel, NaiveBayesFamily)
from .tuning import (CrossValidation, TrainValidationSplit, DataSplitter,  # noqa: F401
                     DataBalancer, DataCutter, Splitter)
from .selector import (ModelSelector, SelectedModel, ModelSelectorSummary,  # noqa: F401
                       BinaryClassificationModelSelector,
                       MultiClassificationModelSelector,
                       RegressionModelSelector)
from .trees import (TreeEnsembleModel,  # noqa: F401
                    RandomForestFamily, DecisionTreeFamily, GBTFamily,
                    XGBoostFamily,
                    OpDecisionTreeClassifier, OpDecisionTreeRegressor,
                    OpRandomForestClassifier, OpRandomForestRegressor,
                    OpGBTClassifier, OpGBTRegressor,
                    OpXGBoostClassifier, OpXGBoostRegressor)
from .svm import (OpLinearSVC, LinearSVCModel, LinearSVCFamily,  # noqa: F401
                  OpMultilayerPerceptronClassifier, MLPModel, MLPFamily)
