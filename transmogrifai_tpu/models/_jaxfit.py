"""Pure-JAX model fitting kernels — static shapes, vmappable, mesh-shardable.

These are the TPU replacement for Spark MLlib's LBFGS/OWLQN solvers: every
fit is a fixed-iteration FISTA (accelerated proximal gradient) loop expressed
with ``lax.fori_loop`` so XLA compiles one program for the entire
(fold × hyperparameter) batch under ``vmap``. Elastic-net matches MLlib's
objective: ``1/n Σ w_i ℓ_i + reg * (α ||β||₁ + (1-α)/2 ||β||²)`` with
internal feature standardization and an unpenalized intercept
(Spark ``LogisticRegression`` semantics).

Sample weights ``w`` double as fold masks: the CV engine passes 0/1 vectors
so one compiled computation serves every fold.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fit_binary_logistic", "fit_multinomial_logistic", "fit_linear",
           "fit_naive_bayes", "predict_binary_logistic",
           "predict_multinomial_logistic", "predict_linear",
           "predict_naive_bayes", "standardize_stats"]


def standardize_stats(X, w):
    """Weighted per-feature mean/std (std>=eps to keep constants harmless)."""
    wsum = jnp.maximum(w.sum(), 1e-12)
    mean = (X * w[:, None]).sum(0) / wsum
    var = ((X - mean) ** 2 * w[:, None]).sum(0) / wsum
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    return mean, std


def _power_iter_sq_norm(Xs, w, iters: int = 16):
    """Largest eigenvalue of (1/n) Xᵀ W X via power iteration (static iters)."""
    d = Xs.shape[1]
    v = jnp.full((d,), 1.0 / jnp.sqrt(d), dtype=Xs.dtype)
    wsum = jnp.maximum(w.sum(), 1e-12)

    def body(_, v):
        u = (Xs.T @ (w * (Xs @ v))) / wsum
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-12)

    v = lax.fori_loop(0, iters, body, v)
    return jnp.maximum((v @ (Xs.T @ (w * (Xs @ v))) / wsum), 1e-12)


def _soft_threshold(x, t):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def _cg_solve(matvec, b, n_iter: int):
    """Conjugate gradient for SPD systems, fixed iteration count.

    Pure matmuls — the TPU-native replacement for LAPACK ``solve`` (which
    XLA lowers to host custom calls that neither map to the MXU nor vmap).
    """
    x = jnp.zeros_like(b)
    r = b
    p = r
    rs = r @ r

    def body(i, state):
        x, r, p, rs = state
        Ap = matvec(p)
        alpha = rs / jnp.maximum(p @ Ap, 1e-30)
        x = x + alpha * p
        r = r - alpha * Ap
        rs_new = r @ r
        beta = rs_new / jnp.maximum(rs, 1e-30)
        p = r + beta * p
        return x, r, p, rs_new

    x, _, _, _ = lax.fori_loop(0, n_iter, body, (x, r, p, rs))
    return x


def _fista(grad_fn, prox_fn, beta0, step, n_iter: int):
    """Accelerated proximal gradient with fixed iteration count."""

    def body(i, state):
        beta, z, t = state
        g = grad_fn(z)
        beta_next = prox_fn(z - step * g, step)
        t_next = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
        z_next = beta_next + ((t - 1.0) / t_next) * (beta_next - beta)
        return beta_next, z_next, t_next

    beta, _, _ = lax.fori_loop(0, n_iter, body,
                               (beta0, beta0, jnp.asarray(1.0, beta0.dtype)))
    return beta


# ---------------------------------------------------------------------------
# Binary logistic regression (binomial, sigmoid link)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_iter",))
def fit_binary_logistic(X, y, w, reg_param, elastic_net, max_iter: int = 128):
    """→ (coef [d], intercept). Objective matches Spark LogisticRegression."""
    mean, std = standardize_stats(X, w)
    Xs = (X - mean) / std
    wsum = jnp.maximum(w.sum(), 1e-12)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)

    def grad(params):
        beta, b = params[:-1], params[-1]
        z = Xs @ beta + b
        p = jax.nn.sigmoid(z)
        r = w * (p - y)
        g_beta = Xs.T @ r / wsum + l2 * beta
        g_b = r.sum() / wsum
        return jnp.concatenate([g_beta, g_b[None]])

    def prox(params, step):
        beta = _soft_threshold(params[:-1], step * l1)
        return jnp.concatenate([beta, params[-1:]])

    lip = 0.25 * _power_iter_sq_norm(Xs, w) + l2 + 0.25  # +intercept row
    step = 1.0 / lip
    params0 = jnp.zeros((X.shape[1] + 1,), dtype=X.dtype)
    params = _fista(grad, prox, params0, step, max_iter)
    coef_s, b = params[:-1], params[-1]
    coef = coef_s / std
    intercept = b - (coef * mean).sum()
    return coef, intercept


def predict_binary_logistic(coef, intercept, X):
    """→ (prediction, raw [n,2], prob [n,2])."""
    margin = X @ coef + intercept
    p1 = jax.nn.sigmoid(margin)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-margin, margin], axis=1)
    pred = (p1 > 0.5).astype(X.dtype)
    return pred, raw, prob


# ---------------------------------------------------------------------------
# Multinomial (softmax) logistic regression
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_multinomial_logistic(X, y, w, reg_param, elastic_net,
                             n_classes: int, max_iter: int = 128):
    """→ (coef [K, d], intercept [K])."""
    mean, std = standardize_stats(X, w)
    Xs = (X - mean) / std
    wsum = jnp.maximum(w.sum(), 1e-12)
    d = X.shape[1]
    k = n_classes
    y_onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=X.dtype)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)

    def grad(params):
        W = params[:, :d]
        b = params[:, d]
        logits = Xs @ W.T + b
        p = jax.nn.softmax(logits, axis=1)
        r = (p - y_onehot) * w[:, None]
        gW = r.T @ Xs / wsum + l2 * W
        gb = r.sum(0) / wsum
        return jnp.concatenate([gW, gb[:, None]], axis=1)

    def prox(params, step):
        W = _soft_threshold(params[:, :d], step * l1)
        return jnp.concatenate([W, params[:, d:]], axis=1)

    lip = 0.5 * _power_iter_sq_norm(Xs, w) + l2 + 0.5
    params0 = jnp.zeros((k, d + 1), dtype=X.dtype)
    params = _fista(grad, prox, params0, 1.0 / lip, max_iter)
    W_s, b = params[:, :d], params[:, d]
    W = W_s / std[None, :]
    intercept = b - W @ mean
    return W, intercept


def predict_multinomial_logistic(coef, intercept, X):
    logits = X @ coef.T + intercept
    prob = jax.nn.softmax(logits, axis=-1)
    pred = jnp.argmax(prob, axis=-1).astype(X.dtype)
    return pred, logits, prob


# ---------------------------------------------------------------------------
# Linear regression (elastic net; ridge closed form blended via select)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_iter",))
def fit_linear(X, y, w, reg_param, elastic_net, max_iter: int = 128):
    """→ (coef [d], intercept). Ridge/OLS solved in closed form; any L1
    component switches to FISTA (lax.cond keeps it one compiled program)."""
    mean, std = standardize_stats(X, w)
    Xs = (X - mean) / std
    wsum = jnp.maximum(w.sum(), 1e-12)
    ybar = (y * w).sum() / wsum
    yc = y - ybar
    d = X.shape[1]
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)

    def closed_form(_):
        def matvec(v):
            return Xs.T @ (w * (Xs @ v)) / wsum + (l2 + 1e-10) * v
        rhs = (Xs.T @ (w * yc)) / wsum
        return _cg_solve(matvec, rhs, n_iter=min(max(d, 16), 128))

    def fista_path(_):
        def grad(beta):
            r = w * (Xs @ beta - yc)
            return Xs.T @ r / wsum + l2 * beta

        def prox(beta, step):
            return _soft_threshold(beta, step * l1)

        lip = _power_iter_sq_norm(Xs, w) + l2
        return _fista(grad, prox, jnp.zeros((d,), X.dtype), 1.0 / lip, max_iter)

    coef_s = lax.cond(l1 > 0.0, fista_path, closed_form, operand=None)
    coef = coef_s / std
    intercept = ybar - (coef * mean).sum()
    return coef, intercept


def predict_linear(coef, intercept, X):
    pred = X @ coef + intercept
    empty = jnp.zeros((X.shape[0], 0), dtype=X.dtype)
    return pred, empty, empty


# ---------------------------------------------------------------------------
# Multinomial naive Bayes (Spark NaiveBayes default, smoothing λ)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_classes",))
def fit_naive_bayes(X, y, w, smoothing, n_classes: int):
    """→ (log_prior [K], log_likelihood [K, d]). Features must be >= 0."""
    k = n_classes
    y_onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=X.dtype)
    yw = y_onehot * w[:, None]
    class_count = yw.sum(0)
    feat_sum = yw.T @ jnp.maximum(X, 0.0)
    log_prior = jnp.log(class_count + smoothing) - jnp.log(
        class_count.sum() + smoothing * k)
    log_like = jnp.log(feat_sum + smoothing) - jnp.log(
        feat_sum.sum(1, keepdims=True) + smoothing * X.shape[1])
    return log_prior, log_like


def predict_naive_bayes(log_prior, log_like, X):
    logits = jnp.maximum(X, 0.0) @ log_like.T + log_prior
    prob = jax.nn.softmax(logits, axis=-1)
    pred = jnp.argmax(logits, axis=-1).astype(X.dtype)
    return pred, logits, prob
