"""Predictor base classes + the ModelFamily protocol for vmapped CV grids.

The reference wraps Spark estimators one JVM fit at a time and parallelizes
with a thread pool (``OpValidator.scala:270-312``). The TPU design instead
treats **the whole (fold × hyperparameter) grid as one batched computation**:

* a :class:`ModelFamily` exposes ``fit_batch(X, y, w, grid)`` /
  ``predict_batch(params, X)`` written in pure JAX with static shapes, so
  the CV engine can ``vmap`` over folds and grid points and ``shard_map``
  the batch over the device mesh (SURVEY §2.10 north star);
* :class:`PredictorEstimator` / :class:`PredictorModel` are the stage-level
  wrappers: Estimator(RealNN label, OPVector features) → Prediction, the
  same contract as ``OpPredictorWrapper``
  (``core/.../sparkwrappers/specific/OpPredictorWrapper.scala:88-106``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columns import (Column, ColumnStore, NumericColumn, PredictionColumn,
                       VectorColumn)
from ..stages.base import (AllowLabelAsInput, Estimator, FittedModel,
                           FixedArity, InputSpec)
from ..types.feature_types import OPVector, Prediction, RealNN

__all__ = ["PredictorEstimator", "PredictorModel", "ModelFamily",
           "extract_xy"]


def pull_f64(out) -> Tuple[np.ndarray, ...]:
    """ONE batched device→host transfer of a prediction tuple, then f64.

    Per-array ``np.asarray`` pulls each pay the device link's full
    round-trip (~200ms on a network-tunnelled TPU); ``jax.device_get`` of
    the whole pytree ships everything in a single fetch."""
    import jax
    return tuple(np.asarray(o, dtype=np.float64)
                 for o in jax.device_get(out))


#: content-keyed device uploads of feature matrices: (shape, dtype,
#: blake2b-128) → f32 device array. A 2M×20 matrix is ~150 MB on a
#: tunnelled link; validate → refit → final transform → repeat scoring
#: touch the same CONTENT through different host objects (boolean-index
#: copies, per-run re-extracts), so identity is not part of the key and
#: entries deliberately OUTLIVE their host arrays — a content-hash match
#: is a content match, whoever holds the bytes now. Bounded FIFO caps
#: device memory (6 × a big matrix ≲ 4 GB HBM on a 16 GB v5e).
_DEVICE_PUT_CACHE: dict = {}


def _content_tag(X: np.ndarray) -> bytes:
    """Full-buffer content fingerprint: blake2b, 128-bit digest. A
    strided sample misses most small in-place edits (ADVICE r4), an
    id-based key misses content-equal re-uploads, and the previous
    crc32+adler32 pair (64 bits of non-cryptographic checksum) left a
    real collision budget for a cache whose hits skip a device upload —
    blake2b-128 makes accidental collision astronomically unlikely at
    the same ~ms full-buffer pass (it is the fast keyed BLAKE2 path in
    hashlib, no allocation beyond the 16-byte digest)."""
    import hashlib
    try:
        view = memoryview(X).cast("B")      # zero-copy when contiguous
    except (TypeError, ValueError, BufferError):
        view = X.tobytes()
    return hashlib.blake2b(view, digest_size=16).digest()


def device_put_f32(X: np.ndarray):
    """``jnp.asarray(X)`` with a content-keyed FIFO cache. The dtype
    follows jax's default conversion (f32 under x64-off — the production
    setting; the f64 CPU test path stays exact)."""
    import jax.numpy as jnp
    key = (getattr(X, "shape", None), str(getattr(X, "dtype", "")),
           _content_tag(X))
    hit = _DEVICE_PUT_CACHE.get(key)
    if hit is not None:
        return hit
    dev = jnp.asarray(X)
    while len(_DEVICE_PUT_CACHE) >= 6:
        _DEVICE_PUT_CACHE.pop(next(iter(_DEVICE_PUT_CACHE)))
    _DEVICE_PUT_CACHE[key] = dev
    return dev


def extract_xy(store: ColumnStore, label_name: str, features_name: str
               ) -> Tuple[np.ndarray, np.ndarray]:
    import jax
    ycol = store[label_name]
    xcol = store[features_name]
    assert isinstance(xcol, VectorColumn), f"{features_name} must be a vector"
    y = np.asarray(ycol.values, dtype=np.float64)
    # under x64 (CPU test path) fits run in f64 — cast the stored f32
    # matrix up (exact embedding). With x64 off (production TPU) the
    # device converts to f32 anyway; skipping the cast avoids a full f64
    # copy of the feature matrix per fit.
    if jax.config.jax_enable_x64:
        X = np.asarray(xcol.values, dtype=np.float64)
    else:
        X = np.asarray(xcol.values)
    return X, y


class PredictorModel(FittedModel, AllowLabelAsInput):
    """Fitted predictor: OPVector → Prediction struct column.

    Keeps the estimator's (label, features) input slots — the label is only
    read by holdout evaluation, never by transform."""

    output_type = Prediction

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, OPVector)

    def predict_device(self, Xd):
        """Device-side (prediction, raw, prob) triple as jax arrays — the
        contract the serving export (serving.py) and SelectedModel's
        device path build on. Implement this in subclasses whose math is
        pure jax; predict_arrays then comes for free."""
        raise NotImplementedError(
            f"{type(self).__name__} implements neither predict_device nor "
            "predict_arrays")

    def predict_arrays(self, X: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(prediction [n], raw [n,k], prob [n,k]) as host float64 — ONE
        batched device pull around predict_device by default (upload
        content-cached: scoring + evaluating the same store must not
        re-ship the feature matrix over the link).

        Models exposing ``predict_host`` (cheap matvec heads: linear/
        logistic/NB) run it instead when the matrix is big and the
        measured link is slow — on a tunnelled TPU the [n, d] upload
        costs tens of seconds for a prediction the host computes in
        milliseconds (same bandwidth gate as the layer-fusion decision)."""
        import logging
        import time

        import jax
        host = getattr(self, "predict_host", None)
        if host is not None and getattr(X, "size", 0) >= 2e6:
            from ..workflow import (FUSE_MIN_BANDWIDTH_MBPS,
                                    device_roundtrip_mbps)
            if device_roundtrip_mbps() < FUSE_MIN_BANDWIDTH_MBPS:
                return host(X)
        log = logging.getLogger(__name__)
        if log.isEnabledFor(logging.INFO) and getattr(X, "size", 0) > 1e6:
            t0 = time.perf_counter()
            Xd = device_put_f32(X)
            jax.block_until_ready(Xd)
            t1 = time.perf_counter()
            dev = self.predict_device(Xd)
            jax.block_until_ready(dev)
            t2 = time.perf_counter()
            out = pull_f64(dev)
            log.info("predict_arrays n=%d: upload %.2fs compute %.2fs "
                     "pull %.2fs", X.shape[0], t1 - t0, t2 - t1,
                     time.perf_counter() - t2)
            return out
        return pull_f64(self.predict_device(device_put_f32(X)))

    def transform_columns(self, store: ColumnStore) -> Column:
        xcol = store[self.input_features[1].name]
        assert isinstance(xcol, VectorColumn)
        # pass the stored (f32) matrix straight through: device_put
        # converts to the device dtype anyway, and a f64 round-trip here
        # copied the full matrix twice per scoring pass
        pred, raw, prob = self.predict_arrays(np.asarray(xcol.values))
        return PredictionColumn(np.asarray(pred, dtype=np.float64),
                                np.asarray(raw, dtype=np.float64),
                                np.asarray(prob, dtype=np.float64))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        x = np.asarray(row[self.input_features[1].name], dtype=np.float64)
        pred, raw, prob = self.predict_arrays(x[None, :])
        out = {"prediction": float(pred[0])}
        for i in range(raw.shape[1]):
            out[f"rawPrediction_{i}"] = float(raw[0, i])
        for i in range(prob.shape[1]):
            out[f"probability_{i}"] = float(prob[0, i])
        return out


class PredictorEstimator(Estimator, AllowLabelAsInput):
    """Estimator(label: RealNN, features: OPVector) → Prediction."""

    output_type = Prediction

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, OPVector)

    @property
    def label_name(self) -> str:
        return self.input_features[0].name

    @property
    def features_name(self) -> str:
        return self.input_features[1].name


class ModelFamily:
    """Batched pure-JAX fit/predict over a hyperparameter grid.

    Static-shape contract (everything vmappable):
      * ``stack_grid(grid)`` → pytree of arrays with leading dim G
      * ``fit_batch(X, y, w, stacked)`` → params pytree, leading dims [..., G]
        (callers vmap over the sample-weight axis for folds)
      * ``predict_batch(params, X)`` → scores for metric computation
      * ``realize(params_i, hparams_i, est)`` → a FittedModel stage
    """

    name: str = "family"
    #: hyperparameter grid: list of dicts
    default_grid: List[Dict[str, Any]] = []

    def __init__(self, grid: Optional[List[Dict[str, Any]]] = None, **fixed):
        self.grid = list(grid) if grid is not None else list(self.default_grid)
        self.fixed = fixed
        if not self.grid:
            self.grid = [{}]

    def grid_size(self) -> int:
        return len(self.grid)

    # -- jax side ----------------------------------------------------------
    def stack_grid(self) -> Dict[str, np.ndarray]:
        keys = sorted({k for g in self.grid for k in g})
        out = {}
        for k in keys:
            out[k] = np.asarray([g.get(k, self._grid_default(k))
                                 for g in self.grid])
        return out

    def _grid_default(self, key: str):
        defaults = self.param_defaults()
        return defaults[key]

    def param_defaults(self) -> Dict[str, Any]:
        return {}

    def fit_batch(self, X, y, w, stacked_grid):
        raise NotImplementedError

    def predict_batch(self, params, X, on_train: bool = False):
        """→ (prediction, raw, prob) with grid-leading batch dims.

        ``on_train=True`` asserts X is the exact matrix ``fit_batch`` saw,
        allowing families to answer from fit-time caches (tree families
        skip routing entirely); it must never be set for fresh data."""
        raise NotImplementedError

    def realize(self, params, hparams: Dict[str, Any]) -> PredictorModel:
        raise NotImplementedError

    def trace_signature(self) -> Tuple:
        """Hashable digest of everything that shapes this family's traced
        program besides the runtime array arguments — the CV engine caches
        compiled (fit+predict+metric) executables across validate() calls
        keyed on this, so repeated sweeps (benchmarks, warm services,
        workflow-level CV folds) skip tracing entirely."""
        items = []
        for k, v in sorted(self.__dict__.items()):
            if k in ("_max_instances", "_tree_chunk_cap"):
                # budget bookkeeping that does NOT shape the traced
                # program (only the finalized _tree_chunk_auto does) —
                # keying it would recompile byte-identical executables
                # whenever the HBM budget constant moves
                continue
            if k == "grid":
                items.append((k, tuple(tuple(sorted(g.items()))
                                       for g in self.grid)))
            elif isinstance(v, np.ndarray):
                items.append((k, (v.shape, str(v.dtype),
                                  hash(v.tobytes()))))
            elif isinstance(v, (int, float, str, bool, type(None))):
                items.append((k, v))
            elif isinstance(v, dict):
                items.append((k, tuple(sorted(
                    (kk, repr(vv)) for kk, vv in v.items()))))
            else:
                items.append((k, repr(v)))
        items.extend(self._trace_extras())
        return (type(self).__module__, type(self).__name__, tuple(items))

    def _trace_extras(self) -> Tuple:
        """Extra (key, value) pairs for trace_signature — trace-time
        environment toggles that change the family's emitted program must
        key the executable cache too, or flipping them mid-process
        silently reuses the old compiled path. Base families have none;
        tree families key the Pallas histogram gate."""
        return ()

    def clone_single(self, hparams: Dict[str, Any]) -> "ModelFamily":
        """Same family configured with a one-point grid (final refit).

        Copies every instance attribute except the grid, so subclass
        configuration (n_classes, task, seeds, …) survives the clone."""
        new = type(self)(grid=[dict(hparams)])
        new.__dict__.update({k: v for k, v in self.__dict__.items()
                             if k != "grid"})
        return new

    def __repr__(self) -> str:
        return f"{type(self).__name__}(grid={len(self.grid)})"
