"""Linear model stages: logistic regression, linear regression, naive Bayes.

Parity: ``OpLogisticRegression`` (``core/.../impl/classification/
OpLogisticRegression.scala``), ``OpLinearRegression``, ``OpNaiveBayes`` —
but fit natively in JAX (models/_jaxfit.py) instead of wrapping MLlib.
Each estimator also exposes a :class:`ModelFamily` so ModelSelector can
vmap its hyperparameter grid.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import register_stage
from . import _jaxfit as JF
from .base import (ModelFamily, PredictorEstimator, PredictorModel,
                   extract_xy, pull_f64)

__all__ = [
    "OpLogisticRegression", "LogisticRegressionModel", "LogisticRegressionFamily",
    "OpLinearRegression", "LinearRegressionModel", "LinearRegressionFamily",
    "OpNaiveBayes", "NaiveBayesModel", "NaiveBayesFamily",
]


def _f(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float64)


# ---------------------------------------------------------------------------
# Logistic regression
# ---------------------------------------------------------------------------

@register_stage
class LogisticRegressionModel(PredictorModel):
    operation_name = "logreg"

    def __init__(self, coefficients=None, intercept=None, n_classes: int = 2,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = _f(coefficients) if coefficients is not None else None
        self.intercept = _f(intercept) if intercept is not None else None
        self.n_classes = int(n_classes)

    def predict_device(self, X):
        """Device-side Prediction triple (pure jax; export/serving path)."""
        if self.n_classes == 2 and self.coefficients.ndim == 1:
            return JF.predict_binary_logistic(
                jnp.asarray(self.coefficients), jnp.asarray(self.intercept),
                X)
        return JF.predict_multinomial_logistic(
            jnp.asarray(self.coefficients), jnp.asarray(self.intercept), X)

    def predict_host(self, X):
        """numpy mirror of predict_device — the slow-link fast path (see
        PredictorModel.predict_arrays): X @ coef is milliseconds on the
        host vs tens of seconds to ship X over a tunnelled device link."""
        Xf = np.asarray(X, dtype=np.float64)
        if self.n_classes == 2 and self.coefficients.ndim == 1:
            margin = Xf @ np.asarray(self.coefficients, np.float64) \
                + float(np.asarray(self.intercept))
            with np.errstate(over="ignore"):
                p1 = np.where(margin >= 0, 1.0 / (1.0 + np.exp(-margin)),
                              np.exp(np.minimum(margin, 0.0))
                              / (1.0 + np.exp(np.minimum(margin, 0.0))))
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-margin, margin], axis=1)
            return (p1 > 0.5).astype(np.float64), raw, prob
        W = np.asarray(self.coefficients, np.float64)
        b = np.asarray(self.intercept, np.float64)
        logits = Xf @ W.T + b
        z = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(z)
        prob = e / e.sum(axis=1, keepdims=True)
        pred = np.argmax(prob, axis=1).astype(np.float64)
        return pred, logits, prob

    def get_model_state(self):
        return {"coefficients": self.coefficients, "intercept": self.intercept,
                "n_classes": self.n_classes}

    def summary(self):
        return {"model": "LogisticRegression", "numClasses": self.n_classes,
                "numFeatures": int(np.atleast_2d(self.coefficients).shape[-1])}


@register_stage
class OpLogisticRegression(PredictorEstimator):
    """LogisticRegression estimator (binomial or multinomial by label arity)."""

    operation_name = "logreg"

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 128, family: str = "auto",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.family = family

    def fit_columns(self, store) -> LogisticRegressionModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        n_classes = int(y.max()) + 1 if len(y) else 2
        n_classes = max(n_classes, 2)
        w = jnp.ones((X.shape[0],))
        if n_classes == 2 and self.family != "multinomial":
            coef, b = JF.fit_binary_logistic(
                jnp.asarray(X), jnp.asarray(y), w,
                self.reg_param, self.elastic_net_param, max_iter=self.max_iter)
            return LogisticRegressionModel(coef, b, 2)
        coef, b = JF.fit_multinomial_logistic(
            jnp.asarray(X), jnp.asarray(y), w,
            self.reg_param, self.elastic_net_param,
            n_classes=n_classes, max_iter=self.max_iter)
        return LogisticRegressionModel(coef, b, n_classes)


class LogisticRegressionFamily(ModelFamily):
    """Batched LR grid (DefaultSelectorParams.scala:35-60: reg × elasticNet)."""

    name = "OpLogisticRegression"
    default_grid = [
        {"regParam": r, "elasticNetParam": e}
        for r in (0.001, 0.01, 0.1, 0.2) for e in (0.1, 0.5)
    ]

    def __init__(self, grid=None, n_classes: int = 2, max_iter: int = 128,
                 **fixed):
        super().__init__(grid, **fixed)
        self.n_classes = n_classes
        self.max_iter = max_iter

    def param_defaults(self):
        return {"regParam": 0.0, "elasticNetParam": 0.0}

    def fit_batch(self, X, y, w, stacked):
        reg = jnp.asarray(stacked["regParam"], dtype=X.dtype)
        enet = jnp.asarray(stacked["elasticNetParam"], dtype=X.dtype)
        if self.n_classes == 2:
            fit = lambda r, e: JF.fit_binary_logistic(
                X, y, w, r, e, max_iter=self.max_iter)
        else:
            fit = lambda r, e: JF.fit_multinomial_logistic(
                X, y, w, r, e, n_classes=self.n_classes,
                max_iter=self.max_iter)
        return jax.vmap(fit)(reg, enet)

    def predict_batch(self, params, X, on_train: bool = False):
        coef, intercept = params
        if self.n_classes == 2:
            return jax.vmap(JF.predict_binary_logistic,
                            in_axes=(0, 0, None))(coef, intercept, X)
        return jax.vmap(JF.predict_multinomial_logistic,
                        in_axes=(0, 0, None))(coef, intercept, X)

    def realize(self, params, hparams) -> LogisticRegressionModel:
        coef, intercept = params
        return LogisticRegressionModel(coef, intercept, self.n_classes)


# ---------------------------------------------------------------------------
# Linear regression
# ---------------------------------------------------------------------------

@register_stage
class LinearRegressionModel(PredictorModel):
    operation_name = "linreg"

    def __init__(self, coefficients=None, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = _f(coefficients) if coefficients is not None else None
        self.intercept = float(intercept) if intercept is not None else 0.0

    def predict_device(self, X):
        return JF.predict_linear(
            jnp.asarray(self.coefficients), self.intercept, X)

    def predict_host(self, X):
        """numpy mirror of predict_device (slow-link fast path)."""
        pred = np.asarray(X, np.float64) @ np.asarray(
            self.coefficients, np.float64) + self.intercept
        empty = np.zeros((pred.shape[0], 0))
        return pred, empty, empty

    def get_model_state(self):
        return {"coefficients": self.coefficients, "intercept": self.intercept}

    def summary(self):
        return {"model": "LinearRegression",
                "numFeatures": int(self.coefficients.shape[0])}


@register_stage
class OpLinearRegression(PredictorEstimator):
    operation_name = "linreg"

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 128, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter

    def fit_columns(self, store) -> LinearRegressionModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        w = jnp.ones((X.shape[0],))
        coef, b = JF.fit_linear(jnp.asarray(X), jnp.asarray(y), w,
                                self.reg_param, self.elastic_net_param,
                                max_iter=self.max_iter)
        return LinearRegressionModel(coef, float(b))


class LinearRegressionFamily(ModelFamily):
    name = "OpLinearRegression"
    default_grid = [
        {"regParam": r, "elasticNetParam": e}
        for r in (0.001, 0.01, 0.1, 0.2) for e in (0.1, 0.5)
    ]

    def __init__(self, grid=None, max_iter: int = 128, **fixed):
        super().__init__(grid, **fixed)
        self.max_iter = max_iter

    def param_defaults(self):
        return {"regParam": 0.0, "elasticNetParam": 0.0}

    def fit_batch(self, X, y, w, stacked):
        reg = jnp.asarray(stacked["regParam"], dtype=X.dtype)
        enet = jnp.asarray(stacked["elasticNetParam"], dtype=X.dtype)
        return jax.vmap(lambda r, e: JF.fit_linear(
            X, y, w, r, e, max_iter=self.max_iter))(reg, enet)

    def predict_batch(self, params, X, on_train: bool = False):
        coef, intercept = params
        return jax.vmap(JF.predict_linear, in_axes=(0, 0, None))(
            coef, intercept, X)

    def realize(self, params, hparams) -> LinearRegressionModel:
        coef, intercept = params
        return LinearRegressionModel(coef, float(intercept))


# ---------------------------------------------------------------------------
# Naive Bayes
# ---------------------------------------------------------------------------

@register_stage
class NaiveBayesModel(PredictorModel):
    operation_name = "naiveBayes"

    def __init__(self, log_prior=None, log_likelihood=None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.log_prior = _f(log_prior) if log_prior is not None else None
        self.log_likelihood = (_f(log_likelihood)
                               if log_likelihood is not None else None)

    def predict_device(self, X):
        return JF.predict_naive_bayes(
            jnp.asarray(self.log_prior), jnp.asarray(self.log_likelihood), X)

    def predict_host(self, X):
        """numpy mirror of predict_device (slow-link fast path)."""
        logits = np.maximum(np.asarray(X, np.float64), 0.0) \
            @ np.asarray(self.log_likelihood, np.float64).T \
            + np.asarray(self.log_prior, np.float64)
        z = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(z)
        prob = e / e.sum(axis=1, keepdims=True)
        return logits.argmax(axis=1).astype(np.float64), logits, prob

    def get_model_state(self):
        return {"log_prior": self.log_prior,
                "log_likelihood": self.log_likelihood}

    def summary(self):
        return {"model": "NaiveBayes",
                "numClasses": int(self.log_prior.shape[0])}


@register_stage
class OpNaiveBayes(PredictorEstimator):
    """Multinomial NB with Laplace smoothing (OpNaiveBayes.scala)."""

    operation_name = "naiveBayes"

    def __init__(self, smoothing: float = 1.0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.smoothing = smoothing

    def fit_columns(self, store) -> NaiveBayesModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        n_classes = max(int(y.max()) + 1 if len(y) else 2, 2)
        w = jnp.ones((X.shape[0],))
        lp, ll = JF.fit_naive_bayes(jnp.asarray(X), jnp.asarray(y), w,
                                    self.smoothing, n_classes=n_classes)
        return NaiveBayesModel(lp, ll)


class NaiveBayesFamily(ModelFamily):
    name = "OpNaiveBayes"
    default_grid = [{"smoothing": s} for s in (1.0,)]

    def __init__(self, grid=None, n_classes: int = 2, **fixed):
        super().__init__(grid, **fixed)
        self.n_classes = n_classes

    def param_defaults(self):
        return {"smoothing": 1.0}

    def fit_batch(self, X, y, w, stacked):
        sm = jnp.asarray(stacked["smoothing"], dtype=X.dtype)
        return jax.vmap(lambda s: JF.fit_naive_bayes(
            X, y, w, s, n_classes=self.n_classes))(sm)

    def predict_batch(self, params, X, on_train: bool = False):
        lp, ll = params
        return jax.vmap(JF.predict_naive_bayes, in_axes=(0, 0, None))(
            lp, ll, X)

    def realize(self, params, hparams) -> NaiveBayesModel:
        lp, ll = params
        return NaiveBayesModel(lp, ll)
