"""Generalized linear models — IRLS on device.

Parity: ``core/.../impl/regression/OpGeneralizedLinearRegression.scala``
(Spark ``GeneralizedLinearRegression``); selector grid uses
``DefaultSelectorParams.DistFamily = gaussian, poisson``
(``DefaultSelectorParams.scala:56``).

TPU re-design: one IRLS loop whose family-specific link/variance terms are
selected branchlessly by a traced family id, so the whole (family × reg)
grid fits as a single ``vmap`` — no per-family recompilation. Families:
gaussian (identity link), poisson (log), gamma (log), binomial (logit).
(Spark's default gamma link is inverse; we use log for numerical stability
under jit — Spark supports gamma/log as well.)
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import register_stage
from .base import ModelFamily, PredictorEstimator, PredictorModel, extract_xy, pull_f64

__all__ = ["OpGeneralizedLinearRegression", "GLMRegressionModel",
           "GLMRegressionFamily", "FAMILY_IDS"]

FAMILY_IDS = {"gaussian": 0, "poisson": 1, "gamma": 2, "binomial": 3}
_EPS = 1e-9


def _inv_link(eta, fam):
    """μ = g⁻¹(η), branchless on the traced family id."""
    eta_c = jnp.clip(eta, -30.0, 30.0)
    mu_log = jnp.exp(eta_c)
    mu_logit = jax.nn.sigmoid(eta_c)
    return jnp.where(fam == 0, eta,
                     jnp.where(fam == 3, mu_logit, mu_log))


def _irls_terms(eta, mu, fam):
    """(dμ/dη, Var(μ)) per family, branchless."""
    dmu = jnp.where(fam == 0, 1.0,
                    jnp.where(fam == 3, mu * (1.0 - mu), mu))
    var = jnp.where(fam == 0, 1.0,
                    jnp.where(fam == 1, mu,
                              jnp.where(fam == 2, mu * mu,
                                        mu * (1.0 - mu))))
    return dmu, jnp.maximum(var, _EPS)


@functools.partial(jax.jit, static_argnames=("max_iter",))
def fit_glm(X, y, w, fam, reg_param, max_iter: int = 25):
    """IRLS with ridge regularization → (coef [d], intercept).

    ``fam`` is a traced scalar family id; ``reg_param`` a traced scalar.
    Each iteration solves the weighted normal equations — a d×d solve, tiny
    next to the XᵀWX matmul that feeds the MXU.
    """
    n, d = X.shape
    Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    # poisson/gamma need positive working response to start
    y_safe = jnp.where(fam == 0, y, jnp.maximum(y, 0.1))
    eta0 = jnp.where(fam == 0, y,
                     jnp.where(fam == 3,
                               jnp.log((y_safe + 0.5) /
                                       jnp.maximum(1.5 - y_safe, 0.5)),
                               jnp.log(y_safe)))
    beta0 = jnp.zeros((d + 1,), X.dtype)
    beta0 = beta0.at[d].set(jnp.sum(w * eta0) / jnp.maximum(jnp.sum(w), 1.0))

    reg = reg_param * n
    ridge = jnp.concatenate([jnp.ones((d,), X.dtype),
                             jnp.zeros((1,), X.dtype)])  # no intercept penalty

    def step(_, beta):
        eta = Xa @ beta
        mu = _inv_link(eta, fam)
        dmu, var = _irls_terms(eta, mu, fam)
        W = w * dmu * dmu / var
        z = eta + (y - mu) / jnp.where(jnp.abs(dmu) > _EPS, dmu, _EPS)
        XtW = Xa.T * W[None, :]
        A = XtW @ Xa + reg * jnp.diag(ridge)
        b = XtW @ z
        return jnp.linalg.solve(A, b)

    beta = jax.lax.fori_loop(0, max_iter, step, beta0)
    return beta[:d], beta[d]


def predict_glm(coef, intercept, X, fam):
    mu = _inv_link(X @ coef + intercept, fam)
    return mu, jnp.zeros((X.shape[0], 0)), jnp.zeros((X.shape[0], 0))


@register_stage
class GLMRegressionModel(PredictorModel):
    """Fitted GLM: prediction = g⁻¹(Xβ + β₀)."""

    operation_name = "glm"

    def __init__(self, coefficients=None, intercept: float = 0.0,
                 family: str = "gaussian", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = (np.asarray(coefficients, dtype=np.float64)
                             if coefficients is not None else None)
        self.intercept = float(intercept) if intercept is not None else 0.0
        self.family = family

    def predict_device(self, X):
        return predict_glm(jnp.asarray(self.coefficients), self.intercept,
                           X, jnp.asarray(FAMILY_IDS[self.family]))

    def predict_arrays(self, X):
        return pull_f64(self.predict_device(jnp.asarray(X)))

    def get_model_state(self):
        return {"coefficients": self.coefficients,
                "intercept": self.intercept, "family": self.family}

    def summary(self):
        return {"model": "GeneralizedLinearRegression",
                "family": self.family,
                "numFeatures": int(self.coefficients.shape[0])}


@register_stage
class OpGeneralizedLinearRegression(PredictorEstimator):
    """Estimator(label, features) → GLM prediction."""

    operation_name = "glm"

    def __init__(self, family: str = "gaussian", reg_param: float = 0.0,
                 max_iter: int = 25, uid: Optional[str] = None):
        super().__init__(uid=uid)
        if family not in FAMILY_IDS:
            raise ValueError(f"Unknown GLM family {family!r}; "
                             f"one of {sorted(FAMILY_IDS)}")
        self.family = family
        self.reg_param = reg_param
        self.max_iter = max_iter

    def fit_columns(self, store) -> GLMRegressionModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        w = jnp.ones_like(jnp.asarray(y))
        coef, b = fit_glm(jnp.asarray(X), jnp.asarray(y), w,
                          jnp.asarray(FAMILY_IDS[self.family]),
                          jnp.asarray(self.reg_param),
                          max_iter=self.max_iter)
        return GLMRegressionModel(np.asarray(coef), float(b), self.family)


class GLMRegressionFamily(ModelFamily):
    """(family × regParam) grid, one vmapped IRLS fit."""

    name = "OpGeneralizedLinearRegression"
    default_grid = [
        {"family": f, "regParam": r}
        for f in ("gaussian", "poisson")            # DefaultSelectorParams:56
        for r in (0.001, 0.01, 0.1, 0.2)            # .Regularization
    ]

    def __init__(self, grid=None, max_iter: int = 25, **fixed):
        super().__init__(grid, **fixed)
        self.max_iter = max_iter

    def param_defaults(self) -> Dict[str, Any]:
        return {"family": "gaussian", "regParam": 0.0}

    def stack_grid(self) -> Dict[str, np.ndarray]:
        out = {"regParam": np.asarray(
            [g.get("regParam", 0.0) for g in self.grid], dtype=np.float64)}
        out["familyId"] = np.asarray(
            [FAMILY_IDS[g.get("family", "gaussian")] for g in self.grid],
            dtype=np.int32)
        return out

    def fit_batch(self, X, y, w, stacked):
        def fit_one(fam, reg):
            return fit_glm(X, y, w, fam, reg, max_iter=self.max_iter)
        return jax.vmap(fit_one)(stacked["familyId"], stacked["regParam"])

    def predict_batch(self, params, X, on_train: bool = False):
        coef, intercept = params
        G = coef.shape[0]
        fams = jnp.asarray([FAMILY_IDS[g.get("family", "gaussian")]
                            for g in self.grid], dtype=jnp.int32)
        if fams.shape[0] != G:     # cloned single grid
            fams = jnp.broadcast_to(fams[:1], (G,))
        return jax.vmap(lambda c, b, f: predict_glm(c, b, X, f))(
            coef, intercept, fams)

    def realize(self, params, hparams) -> GLMRegressionModel:
        coef, intercept = params
        return GLMRegressionModel(np.asarray(coef), float(intercept),
                                  hparams.get("family", "gaussian"))
