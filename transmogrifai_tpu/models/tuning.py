"""Tuning: splitters, balancing, and the batched cross-validation engine.

Parity: ``core/.../impl/tuning/*`` — ``Splitter``/``DataSplitter``/
``DataBalancer``/``DataCutter`` (:30-178) and ``OpCrossValidation``/
``OpTrainValidationSplit``.

TPU re-design highlights:

* **Folds are masks, not copies.** ``OpCrossValidation`` materializes k
  train/val datasets (``MLUtils.kFold``); here a fold is a 0/1 weight
  vector, so all k folds share one device-resident (X, y) and one compiled
  program evaluates every fold.
* **The grid is one batched computation.** The reference fans out
  ``estimator.fit`` calls on an 8-thread pool (``OpValidator.scala:318-326``);
  here ``vmap(fold) ∘ vmap(grid)`` over a ModelFamily's pure-JAX fit gives
  XLA the whole sweep at once, and a mesh shards the batch across chips.
* **Balancing is deterministic reweighting.** ``DataBalancer`` up/down-samples
  rows stochastically (``DataBalancer.scala:84-178``); resampling breaks
  static shapes, so we hit the same target positive fraction with per-row
  weights — equivalent in expectation for every weighted-loss model here.
"""
from __future__ import annotations

import functools
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from ..columns import ColumnStore
from ..evaluators import metrics as M
from .base import ModelFamily

__all__ = ["Splitter", "DataSplitter", "DataBalancer", "DataCutter",
           "CrossValidation", "TrainValidationSplit", "ValidationResult",
           "ValidatorSummary"]


# ---------------------------------------------------------------------------
# Splitters (impl/tuning/DataSplitter.scala, DataBalancer.scala, DataCutter.scala)
# ---------------------------------------------------------------------------

class Splitter:
    """Base: holdout reservation + per-task train preparation."""

    def __init__(self, seed: int = 42, reserve_test_fraction: float = 0.0):
        self.seed = seed
        self.reserve_test_fraction = reserve_test_fraction
        self.summary: Dict[str, Any] = {}

    def reserve_split(self, store: ColumnStore
                      ) -> Tuple[ColumnStore, Optional[ColumnStore]]:
        if self.reserve_test_fraction <= 0.0:
            return store, None
        rng = np.random.default_rng(self.seed)
        n = store.n_rows
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        test_idx, train_idx = perm[:n_test], perm[n_test:]
        return store.take(np.sort(train_idx)), store.take(np.sort(test_idx))

    def pre_validation_prepare(self, y: np.ndarray) -> None:
        """Estimate preparation parameters (DataBalancer.estimate)."""

    def sample_weights(self, y: np.ndarray) -> np.ndarray:
        """Per-row training weights implementing the preparation."""
        return np.ones_like(y, dtype=np.float64)

    def keep_mask(self, y: np.ndarray) -> np.ndarray:
        """Rows admitted to training at all (DataCutter label dropping)."""
        return np.ones_like(y, dtype=bool)

    def physical_sample(self, y: np.ndarray, w: np.ndarray
                        ) -> Tuple[Optional[np.ndarray], np.ndarray]:
        """(keep mask | None, kept weights): physically drop rows whose
        sampling fraction is below 1 — what the reference's splitters DO
        (``DataBalancer.scala rebalance`` Bernoulli-samples the majority
        class; ``maxTrainingSample`` caps the physical training size).

        The round-1..4 design kept every row and carried the fraction as
        a weight — statistically the exact expectation of the reference's
        sample and fully static-shaped, but at the 10M BASELINE config it
        histograms 10× the rows Spark trains on (maxTrainingSample=1M).
        Physically sampling once, host-side, BEFORE the sweep keeps
        shapes static per validate() call and deterministic per seed.
        Rows with fraction ≥ 1 (minority up-weighting) keep their weight.
        Default: no sampling (weights already uniform)."""
        return None, w

    def relabel(self, y: np.ndarray) -> np.ndarray:
        """Map kept labels to contiguous model classes (DataCutter only)."""
        return y

    def original_labels(self):
        """new class id → original label value, or None (identity)."""
        return None


class DataSplitter(Splitter):
    """Plain splitter — regression (DataSplitter.scala:30-100)."""


class DataBalancer(Splitter):
    """Binary-label balancer with the reference's exact sampling fractions
    (``DataBalancer.scala:84-131`` getProportions, ``:208-253`` estimate).

    TPU-first mechanism: instead of physically up-/down-sampling rows (the
    reference's ``rebalance``), each class carries its sampling fraction as
    a per-row TRAINING WEIGHT — identical expected class mass, but static
    shapes so the whole (fold × grid) sweep stays one compiled program.
    """

    def __init__(self, sample_fraction: float = 0.1, seed: int = 42,
                 reserve_test_fraction: float = 0.0,
                 max_training_sample: int = 1_000_000):
        super().__init__(seed=seed, reserve_test_fraction=reserve_test_fraction)
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample
        self._pos_weight = 1.0
        self._neg_weight = 1.0

    @staticmethod
    def get_proportions(small: float, big: float, sample_f: float,
                        max_training_sample: int) -> Tuple[float, float]:
        """(downSample for majority, upSample for minority) — exact port of
        ``DataBalancer.getProportions`` (:84-115)."""
        def check_up(mult: float) -> bool:
            return (mult * small * (1 - sample_f) < sample_f * big
                    and max_training_sample * sample_f > small * mult)

        if small < max_training_sample * sample_f:
            up = next((float(m) for m in (100, 50, 10, 5, 4, 3, 2)
                       if check_up(m)), 1.0)
            down = (small * up / sample_f - small * up) / big
            return down, up
        # data too big: downsample both classes
        up = (max_training_sample * sample_f) / small
        return (1 - sample_f) * max_training_sample / big, up

    def pre_validation_prepare(self, y: np.ndarray) -> None:
        n = len(y)
        n_pos = float((y == 1).sum())
        n_neg = float(n - n_pos)
        f = self.sample_fraction
        mts = self.max_training_sample
        is_pos_small = n_pos < n_neg
        small, big = (n_pos, n_neg) if is_pos_small else (n_neg, n_pos)

        if small == 0 or small / max(n, 1) >= f:
            # already balanced; uniformly downsample only when too big
            frac = mts / n if mts < n else 1.0
            self._pos_weight = self._neg_weight = frac
            self.summary = {
                "positiveLabels": n_pos, "negativeLabels": n_neg,
                "desiredFraction": f, "upSamplingFraction": 0.0,
                "downSamplingFraction": frac}
            return
        down, up = self.get_proportions(small, big, f, mts)
        if is_pos_small:
            self._pos_weight, self._neg_weight = up, down
        else:
            self._pos_weight, self._neg_weight = down, up
        self.summary = {
            "positiveLabels": n_pos, "negativeLabels": n_neg,
            "desiredFraction": f, "upSamplingFraction": up,
            "downSamplingFraction": down}

    def sample_weights(self, y: np.ndarray) -> np.ndarray:
        return np.where(y == 1, self._pos_weight, self._neg_weight).astype(
            np.float64)

    def physical_sample(self, y: np.ndarray, w: np.ndarray
                        ) -> Tuple[Optional[np.ndarray], np.ndarray]:
        """EXACT-count per-class row sampling for fractions < 1 (the
        reference's ``rebalance``/``maxTrainingSample`` sampling);
        up-weights (> 1) stay as weights.

        Exact counts (not Bernoulli draws) make the sampled row count a
        deterministic function of the class fractions: every config in
        the uniform-downsample branch lands on EXACTLY
        ``round(Σ fraction) ≈ maxTrainingSample`` rows, so a 2M-row and
        a 10M-row sweep share identical array shapes — and therefore
        every compiled (fold × grid) executable. That turned the 10M
        BASELINE config's fresh ~250 s compile bill into cache hits."""
        frac = np.minimum(w, 1.0)
        if bool((frac >= 1.0 - 1e-12).all()):
            return None, w
        rng = np.random.default_rng(self.seed + 0x5EED)
        keep = np.zeros(len(w), dtype=bool)
        target = int(round(float(frac.sum())))
        classes = [c for c in (0.0, 1.0) if (y == c).any()] or [None]
        remaining = target
        for ci, cls in enumerate(classes):
            idx = (np.nonzero(y == cls)[0] if cls is not None
                   else np.arange(len(y)))
            f = float(frac[idx[0]])
            if f >= 1.0 - 1e-12:
                keep[idx] = True
                remaining -= len(idx)
                continue
            k = (int(round(f * len(idx))) if ci < len(classes) - 1
                 else remaining)            # last class absorbs rounding
            k = int(np.clip(k, 0, len(idx)))
            sel = rng.choice(len(idx), size=k, replace=False)
            keep[idx[sel]] = True
            remaining -= k
        return keep, np.maximum(w, 1.0)[keep]


class DataCutter(Splitter):
    """Multiclass label cutter (DataCutter.scala:30-120): drop labels beyond
    ``max_label_categories`` or below ``min_label_fraction``."""

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0, seed: int = 42,
                 reserve_test_fraction: float = 0.0):
        super().__init__(seed=seed, reserve_test_fraction=reserve_test_fraction)
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction
        self._kept_labels: Optional[np.ndarray] = None

    def pre_validation_prepare(self, y: np.ndarray) -> None:
        labels, counts = np.unique(y, return_counts=True)
        frac = counts / max(len(y), 1)
        order = np.argsort(-counts, kind="stable")
        kept = [labels[i] for i in order[:self.max_label_categories]
                if frac[i] >= self.min_label_fraction]
        self._kept_labels = np.asarray(sorted(kept))
        self.summary = {"labelsKept": self._kept_labels.tolist(),
                        "labelsDropped": sorted(
                            set(labels.tolist()) - set(kept))}

    def keep_mask(self, y: np.ndarray) -> np.ndarray:
        if self._kept_labels is None:
            return np.ones_like(y, dtype=bool)
        return np.isin(y, self._kept_labels)

    def relabel(self, y: np.ndarray) -> np.ndarray:
        """Kept labels → contiguous 0..k-1 model classes: the reference
        re-indexes and fixes the NominalAttribute metadata
        (DataCutter.scala:30-120); here the SelectedModel carries the
        inverse mapping and translates predictions back."""
        if self._kept_labels is None:
            return y
        return np.searchsorted(self._kept_labels, y,
                               side="left").astype(np.float64)

    def original_labels(self):
        if self._kept_labels is None:
            return None
        # identity mapping needs no translation
        if np.array_equal(self._kept_labels,
                          np.arange(len(self._kept_labels))):
            return None
        return [float(v) for v in self._kept_labels]


class RandomParamBuilder:
    """Random hyperparameter grids (RandomParamBuilder.scala:1): declare a
    distribution per param, then ``build(n)`` samples n grid points to feed
    a ModelFamily's ``grid``.

    ``uniform`` — linear range; ``exponential`` — log-uniform (the
    reference's choice for regularization params); ``choice`` — discrete.
    """

    def __init__(self, seed: int = 42):
        self.seed = seed
        self._dists: List[Tuple[str, str, Any]] = []

    def uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        self._dists.append((name, "uniform", (float(lo), float(hi))))
        return self

    def exponential(self, name: str, lo: float, hi: float
                    ) -> "RandomParamBuilder":
        if lo <= 0 or hi <= 0:
            raise ValueError("exponential bounds must be positive")
        self._dists.append((name, "exponential", (float(lo), float(hi))))
        return self

    def choice(self, name: str, values: Sequence[Any]) -> "RandomParamBuilder":
        if not values:
            raise ValueError(f"choice({name!r}) needs at least one value")
        self._dists.append((name, "choice", list(values)))
        return self

    def build(self, n: int) -> List[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        grid = []
        for _ in range(n):
            point: Dict[str, Any] = {}
            for name, kind, spec in self._dists:
                if kind == "uniform":
                    lo, hi = spec
                    point[name] = float(rng.uniform(lo, hi))
                elif kind == "exponential":
                    lo, hi = spec
                    point[name] = float(np.exp(
                        rng.uniform(np.log(lo), np.log(hi))))
                else:
                    point[name] = spec[int(rng.integers(len(spec)))]
            grid.append(point)
        return grid


# ---------------------------------------------------------------------------
# Validators (OpCrossValidation / OpTrainValidationSplit)
# ---------------------------------------------------------------------------

@dataclass
class ValidationResult:
    family_name: str
    hparams: Dict[str, Any]
    grid_index: int
    metric_values: List[float]          # per fold/split
    mean_metric: float


@dataclass
class ValidatorSummary:
    validation_type: str
    evaluation_metric: str
    results: List[ValidationResult] = field(default_factory=list)
    best: Optional[ValidationResult] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "validationType": self.validation_type,
            "evaluationMetric": self.evaluation_metric,
            "bestModelName": self.best.family_name if self.best else None,
            "bestModelParams": self.best.hparams if self.best else None,
            "results": [
                {"model": r.family_name, "params": r.hparams,
                 "metricPerFold": r.metric_values, "mean": r.mean_metric}
                for r in self.results],
        }


def _metric_value(metric_name: str, task: str, y: np.ndarray,
                  pred: np.ndarray, prob: np.ndarray) -> float:
    if task == "binary":
        scores = prob[:, 1] if prob.ndim == 2 and prob.shape[1] >= 2 else pred
        m = M.binary_metrics(y, pred, scores)
    elif task == "multiclass":
        m = M.multiclass_metrics(y, pred)
    else:
        m = M.regression_metrics(y, pred)
    return m[metric_name]


_LARGER_BETTER = frozenset({"AuROC", "AuPR", "Precision", "Recall", "F1", "R2"})

#: compiled (fit+predict+metric) executables, keyed by (family trace
#: signature, task, metric, mesh, arg shapes) — see validate()
_FUSED_EXE_CACHE: Dict[Any, Any] = {}


#: HBM transient budget for one family's in-flight (fold × grid) instances.
#: v5e has 16G; the data + fold weights + compiled program take the rest.
CHUNK_MEM_BUDGET_BYTES = 6e9


def _auto_chunks(family, n_rows: int, n_shards: int, n_folds: int,
                 n_features: int = 0) -> Optional[int]:
    """Bound the (fold × grid) product so tree-engine transients fit HBM.

    Each in-flight tree-grid instance materializes, per level,
    ~3 × [rows_per_shard, max_active_nodes] f32 routing tensors (one-hot
    slot matmul in _treefit.grow_tree) PLUS — on the XLA histogram path —
    the bf16 matmul operands NS [rows, A·C] and Bc [rows, bins·F] that
    _level_cumhist materializes in HBM (the Pallas kernel builds these in
    VMEM, but the budget must cover the fallback: at 1M rows × F=20 these
    operands alone are ~2 GB/instance and undercounting them crashed the
    TPU worker). At small n the whole (fold × grid) sweep runs as one
    vmap (fastest); as n grows we first serialize folds, then grid points
    within a fold — the caller (validate's chunk_plan) turns both into
    HOST-level chunk re-dispatches of one compiled executable. Non-tree
    families are cheap — never chunked. Returns fold_chunk (None = no
    fold chunking); sets family.grid_chunk as a side effect (consumed and
    reset by chunk_plan).
    """
    A = getattr(family, "max_active_nodes", None)
    if not A:
        return None
    rows = n_rows / max(n_shards, 1)
    A = max(A, 64)
    n_bins = getattr(family, "n_bins", 32)
    C_est = max(getattr(family, "n_classes", 2) + 1, 4)
    from ._pallas_hist import pallas_histograms_enabled
    cache_bpr = 0
    try:
        cache_bpr = int(family._cache_bytes_per_row())
    except (AttributeError, TypeError, ValueError):
        pass                    # families without a cache estimate
    if pallas_histograms_enabled():
        # prebinned + fused-kernel path (round 4): the [n, A] routing
        # tensors and the NS/Bc matmul operands never hit HBM, so an
        # in-flight instance carries only its [n] slot/g/margin vectors,
        # [n, C] stats, the per-chunk bootstrap draw, its fit-time
        # prediction caches (RF: [T, n] train-node routing — 1.9 GB per
        # fold at 9M rows, undercounting it OOMed the 10M config), and
        # the K-major train-predict gather chunk (~64 MB cap)
        per_instance = rows * (24 + 4 * C_est + cache_bpr) + 96e6
    else:
        per_instance = rows * A * 4 * 3 \
            + rows * (A * C_est + n_bins * max(n_features, 1)) * 2 \
            + rows * cache_bpr
    max_instances = max(int(CHUNK_MEM_BUDGET_BYTES // per_instance), 1)
    g = family.grid_size()
    if getattr(family, "tree_chunk", 1) is None:
        # auto tree-chunking (RF/DT bootstrap batching) is finalized by
        # the caller once the in-flight (fold × grid) chunk sizes are
        # known — record the budget and the row-count gate here. Only
        # engaged at large PER-SHARD row counts (per-device step work is
        # what must amortize the widened level tensors): measured
        # single-chip, a 200k-row RF sweep gains 28% from chunking while
        # Titanic scale (~900 rows) loses ~20%; the crossover gate is
        # per-shard by construction.
        family._max_instances = max_instances
        # TMOG_TREE_CHUNK_CAP overrides the bootstrap batch cap for
        # perf experiments (HBM budget still bounds the realized chunk)
        _cap_env = os.environ.get("TMOG_TREE_CHUNK_CAP")
        if _cap_env:
            try:
                family._tree_chunk_cap = max(1, int(_cap_env))
            except ValueError:
                raise ValueError(
                    f"TMOG_TREE_CHUNK_CAP must be an integer, "
                    f"got {_cap_env!r}") from None
        else:
            family._tree_chunk_cap = 1 if rows < 32_768 else 4
        family._tree_chunk_auto = 1
    if max_instances >= g * n_folds:
        family.grid_chunk = None
        return None
    if max_instances >= g:
        family.grid_chunk = None
        return max(max_instances // g, 1)
    family.grid_chunk = max_instances
    return 1


def _best_chunk(total: int, cmax: int) -> int:
    """Largest chunk size ≤ cmax that DIVIDES total: zero padded work
    (a padded fold/grid slot costs a whole wasted fit at large n, which
    dominates the ~100ms saved per avoided dispatch), fewest dispatches
    among the zero-padding options."""
    cmax = max(1, min(cmax, total))
    return max(c for c in range(1, cmax + 1) if total % c == 0)


def _chunk_sizes(total: int, cmax: int) -> List[int]:
    """Chunk-size schedule ≤ cmax covering total.

    Prefers ONE uniform divisor size (single executable, zero padding).
    When the best divisor sits far below the budget — e.g. a prime
    7-point grid with budget 6 would degrade to seven 1-wide dispatches —
    it instead emits a ragged schedule ``[cmax]*q + [r]`` (ADVICE r2):
    one extra compile for the remainder shape beats multiplying dispatch
    count and collapsing the vmap batch width."""
    cmax = max(1, min(cmax, total))
    d = _best_chunk(total, cmax)
    if 2 * d > cmax:          # divisor uses >half the budget: good enough
        return [d] * (total // d)
    q, r = divmod(total, cmax)
    return [cmax] * q + ([r] if r else [])


def _grid_chunks(family, sizes: List[int]):
    """Split the family's stacked hyperparameter grid into device-ready
    chunks following the ``sizes`` schedule (shared by validate and
    validate_per_fold so the chunking logic cannot drift)."""
    stacked = family.stack_grid()
    chunks, j0 = [], 0
    for gc in sizes:
        chunks.append({k2: jnp.asarray(v[j0:j0 + gc])
                       for k2, v in stacked.items()})
        j0 += gc
    return chunks


def _finalize_tree_chunk(family, in_flight: int) -> None:
    """Spend HBM slack left after (fold × grid) chunking on batching
    bootstrap trees per scan step (see _auto_chunks, which records the
    budget and the row-count gate)."""
    if getattr(family, "tree_chunk", 1) is None:
        family._tree_chunk_auto = int(np.clip(
            getattr(family, "_max_instances", 1) // max(in_flight, 1),
            1, getattr(family, "_tree_chunk_cap", 1)))


#: executed-FLOP accounting for MFU reporting (bench.py): every compiled
#: CV executable's XLA cost-analysis FLOPs accumulate here per DISPATCH.
#: Covers the sweep executables (where the device math is); single-model
#: refits and transforms are excluded, so this is a lower bound.
DEVICE_FLOPS = {"total": 0.0}
#: id(exe) → flops. Keys can outlive evicted executables (bounded by the
#: 64-entry FIFO cache, a few floats) — id() reuse is harmless because a
#: new executable re-registers its own flops before any dispatch.
_EXE_FLOPS: Dict[int, float] = {}


def _register_exe_flops(exe) -> None:
    try:
        ca = exe.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        _EXE_FLOPS[id(exe)] = float(d.get("flops", 0.0))
    # cost analysis is best-effort (backend-dep)
    except Exception:  # lint: broad-except — cost analysis is best-effort (backend-dep)
        _EXE_FLOPS[id(exe)] = 0.0


def _count_dispatch(exe, extra_flops: float = 0.0) -> None:
    """Accumulate one dispatch's FLOPs: XLA cost analysis of the
    executable PLUS ``extra_flops`` — the analytic estimate of work
    inside Pallas custom calls, which cost analysis cannot see (without
    it the round-4 kernel migration made the MFU numerator collapse).
    Mirrors into the process-wide device-cost ledger
    (``telemetry.record_device_work``) under the ``tuning`` phase —
    FLOPs only, no per-dispatch timing here (the sweep executables run
    under thread-pool overlap, so a wall timer would double-count)."""
    f = _EXE_FLOPS.get(id(exe))
    if f is None:
        _register_exe_flops(exe)
        f = _EXE_FLOPS[id(exe)]
    DEVICE_FLOPS["total"] += f + extra_flops
    from .. import telemetry
    telemetry.record_device_work("tuning", flops=f + extra_flops)


def _pallas_on() -> bool:
    from ._pallas_hist import pallas_histograms_enabled
    return pallas_histograms_enabled()


_NO_CHUNK_ATTR = object()


def _snapshot_grid_chunks(families):
    return [(f, getattr(f, "grid_chunk", _NO_CHUNK_ATTR)) for f in families]


def _restore_grid_chunks(snaps) -> None:
    for f, gc in snaps:
        if gc is not _NO_CHUNK_ATTR:
            f.grid_chunk = gc


class _ValidatorBase:
    """Shared fold-mask validation engine."""

    validation_type = "validator"

    def __init__(self, metric_name: str, task: str, seed: int = 42,
                 stratify: bool = False, max_iter_folds: int = 0):
        self.metric_name = metric_name
        self.task = task
        self.seed = seed
        self.stratify = stratify
        self.is_larger_better = metric_name in _LARGER_BETTER

    def _splits(self, y: np.ndarray) -> List[Tuple[np.ndarray, np.ndarray]]:
        """(train_mask, val_mask) pairs as 0/1 float arrays."""
        raise NotImplementedError

    def validate(self, families: Sequence[ModelFamily], X: np.ndarray,
                 y: np.ndarray, base_weights: Optional[np.ndarray] = None,
                 mesh=None) -> Tuple[ModelFamily, Dict[str, Any], ValidatorSummary]:
        """Run the full (family × grid × fold) sweep; return winner.

        The per-family computation is ONE jitted program — fit, predict and
        the selection metric fused, folds vmapped on the outer axis, grid
        points inner — returning only a [folds, grid] metric matrix, so
        predictions never leave the device. With a mesh, X/y are device_put
        with a row sharding so XLA partitions the batch over chips (GSPMD).
        Metrics without a device kernel fall back to host numpy.

        Wrapped in the Pallas fit-level fallback: a Mosaic failure at
        production shapes disables the kernel and re-runs the sweep on the
        XLA path (families re-key via ``trace_signature``). chunk_plan
        consumes each family's ``grid_chunk``, so the retry restores the
        pre-attempt values — otherwise the degraded-hardware pass would
        dispatch the full grid unchunked.
        """
        from ._pallas_hist import with_pallas_fallback
        from ._treefit import tree_mesh_scope
        snaps = _snapshot_grid_chunks(families)

        def attempt():
            _restore_grid_chunks(snaps)
            # the tree engine's kernel dispatches shard over this mesh
            # (shard_map partial histograms + psum); the scope spans
            # binning, compile AND dispatch so every traced program
            # agrees on the row padding and the sharded kernels
            with tree_mesh_scope(mesh):
                return self._validate_impl(families, X, y, base_weights,
                                           mesh)
        return with_pallas_fallback(attempt)

    def _validate_impl(self, families, X, y, base_weights=None, mesh=None):
        from ..evaluators.device_metrics import device_metric_fn
        from ..parallel.mesh import mesh_if_multi

        # a degenerate (1×1) mesh routes onto the exact single-device
        # path — content-cached uploads, unsharded executables — so the
        # pre-mesh behavior is the mesh's special case, not a fork
        mesh = mesh_if_multi(mesh)
        splits = self._splits(y)
        base_w = (np.ones_like(y, dtype=np.float64)
                  if base_weights is None else base_weights)
        train_w = np.stack([m * base_w for m, _ in splits])   # [K, n]
        val_w = np.stack([v for _, v in splits])              # [K, n] 0/1
        val_masks = val_w.astype(bool)

        n_orig = len(y)
        if mesh is not None:
            from ..parallel.mesh import shard_cv_inputs
            Xd, yd, wd, vwd, n_orig = shard_cv_inputs(mesh, X, y, train_w,
                                                      extra=val_w)
        else:
            from .base import device_put_f32
            Xd, yd = device_put_f32(X), jnp.asarray(y)
            wd = jnp.asarray(train_w)
            vwd = jnp.asarray(val_w)

        summary = ValidatorSummary(self.validation_type, self.metric_name)
        best: Optional[ValidationResult] = None
        best_family: Optional[ModelFamily] = None
        sign = 1.0 if self.is_larger_better else -1.0

        # Phase 1: compile every family's fused fit+predict+metric program
        # CONCURRENTLY — XLA compilation is C++ and releases the GIL, so the
        # cold-start cost is max(compile) across families, not the sum.
        # Compiled executables are cached across validate() calls keyed by
        # (family trace signature, metric, arg shapes): data, fold weights
        # and the stacked hyperparameter grid are jit ARGUMENTS, so repeat
        # sweeps skip tracing AND compilation entirely.
        #
        # Memory-bounded chunking is HOST-level: when _auto_chunks bounds
        # the in-flight (fold × grid) product, the fold/grid axes are cut
        # into equal chunks and ONE executable (compiled for the chunk
        # shape) is re-dispatched per chunk. An earlier design serialized
        # chunks with lax.map INSIDE the program; that compiled a second,
        # markedly slower program (a 1M-row RF fit ran ~4× slower under
        # the map than standalone) and concentrated the whole sweep's
        # transients into one device program, which crashed the TPU
        # worker at 1M rows. Host chunk calls reuse the executable, queue
        # async back-to-back, and bound peak memory to one chunk.
        def make_fit_eval(family, metric_fn, static_depth=None):
            def fit_eval(X, y, w_folds, v_folds, stacked):
                if isinstance(X, dict):
                    # device_prep may ROW_ALIGN-pad the binned matrix;
                    # follow with zero-weighted label/mask rows so the
                    # pads stay out of every histogram and metric
                    from .trees import _tree_rows, pad_rows_to
                    n_pad = _tree_rows(X)
                    if n_pad != y.shape[0]:
                        (y,) = pad_rows_to(n_pad, y)
                        w_folds, v_folds = [
                            jnp.concatenate(
                                [a, jnp.zeros((a.shape[0],
                                               n_pad - a.shape[1]),
                                              a.dtype)], axis=1)
                            for a in (w_folds, v_folds)]

                def per_fold(w, v):
                    if static_depth is not None:
                        params = family.fit_batch(
                            X, y, w, stacked, static_depth=static_depth)
                    else:
                        params = family.fit_batch(X, y, w, stacked)
                    pred, _raw, prob = family.predict_batch(params, X,
                                                            on_train=True)
                    return jax.vmap(
                        lambda pg, prg: metric_fn(y, pg, prg, v)
                    )(pred, prob)
                return jax.vmap(per_fold)(w_folds, v_folds)
            return fit_eval

        mesh_key = tuple(sorted(mesh.shape.items())) if mesh is not None \
            else None

        def shapes_of(tree):
            return tuple((tuple(a.shape), str(jnp.asarray(a).dtype))
                         for a in jax.tree_util.tree_leaves(tree))

        n_shards = (mesh.shape.get("data", 1) if mesh is not None else 1)
        k_folds = len(splits)

        def chunk_plan(family):
            """(fc, chunks): fold chunk size (a divisor of k_folds) and
            the grid chunks as (grid-index array, device-ready stacked
            slice, static_depth|None) triples.

            Tree families at ≥ UNROLL_MIN_ROWS rows are grouped by
            ``maxDepth`` first: each group compiles a STATIC-depth
            unrolled program (per-level slot growth, no dead levels for
            shallow grid points), which is where the round-4 histogram
            FLOP cut comes from. Below that, one traced-depth program
            serves the whole grid (compile time dominates at small n).
            """
            fold_chunk = _auto_chunks(family, len(y), n_shards, k_folds,
                                      n_features=X.shape[1])
            gc = getattr(family, "grid_chunk", None) or family.grid_size()
            if hasattr(family, "grid_chunk"):
                family.grid_chunk = None    # chunking happens here, not
            fc = fold_chunk or k_folds      # in fit_batch's lax.map
            fc = _best_chunk(k_folds, fc)
            groups = []                     # (index list, static depth)
            if getattr(family, "supports_static_depth", False):
                from .trees import UNROLL_MIN_ROWS
                if len(y) >= UNROLL_MIN_ROWS:
                    dflt = family.param_defaults().get("maxDepth", 0)
                    by_depth: Dict[int, list] = {}
                    for i, gpt in enumerate(family.grid):
                        by_depth.setdefault(
                            int(gpt.get("maxDepth", dflt)), []).append(i)
                    for dpt, idxs in sorted(by_depth.items()):
                        j0 = 0
                        for sz in _chunk_sizes(len(idxs), gc):
                            groups.append((idxs[j0:j0 + sz], dpt))
                            j0 += sz
            if not groups:
                idxs = list(range(family.grid_size()))
                j0 = 0
                for sz in _chunk_sizes(family.grid_size(), gc):
                    groups.append((idxs[j0:j0 + sz], None))
                    j0 += sz
            stacked = family.stack_grid()
            chunks = [(np.asarray(ix),
                       {k2: jnp.asarray(np.asarray(v)[np.asarray(ix)])
                        for k2, v in stacked.items()}, sd)
                      for ix, sd in groups]
            _finalize_tree_chunk(family,
                                 fc * max(len(ix) for ix, _ in groups))
            logger.info(
                "chunk plan %s: fold_chunk=%d/%d grid_chunks=%s%s",
                family.name, fc, k_folds,
                [(len(ix), sd) for ix, sd in groups],
                f" tree_chunk={family._tree_chunk_auto}"
                if getattr(family, "_tree_chunk_auto", None) else "")
            return fc, chunks

        # one executable per (family, grid-chunk width, static depth)
        fused: Dict[int, Dict[Any, Any]] = {}
        plans: Dict[int, Any] = {}
        xargs: Dict[int, Any] = {}
        to_compile = []
        for fi, family in enumerate(families):
            metric_fn = device_metric_fn(
                self.task, self.metric_name,
                n_classes=getattr(family, "n_classes", 2))
            if metric_fn is None:
                continue
            # bin the data once per family config (cached across families
            # sharing the same device array + binning config)
            xargs[fi] = (family.device_prep(Xd)
                         if hasattr(family, "device_prep") else Xd)
            plan = chunk_plan(family)
            plans[fi] = plan
            fc, chunks = plan
            exes: Dict[Any, Any] = {}
            jfs: Dict[Any, Any] = {}
            for ix, st, sd in chunks:
                ek = (len(ix), sd)
                if ek in exes:
                    continue
                key = (family.trace_signature(), self.task, self.metric_name,
                       mesh_key, ("chunk", fc, ek),
                       shapes_of((xargs[fi], yd, wd[:fc], vwd[:fc], st)))
                exe = _FUSED_EXE_CACHE.get(key)
                if exe is not None:
                    exes[ek] = exe
                else:
                    if sd not in jfs:
                        jfs[sd] = jax.jit(
                            make_fit_eval(family, metric_fn, sd))
                    exes[ek] = None
                    to_compile.append((fi, ek, key, jfs[sd], st))
            fused[fi] = exes

        if to_compile:
            import concurrent.futures as cf
            import time as _time
            tc0 = _time.perf_counter()
            # concurrency shrinks with row count: at 10M-row shapes, 8
            # parallel compiles crashed the (remote) compile service
            workers = max(1, min(len(to_compile),
                                 int(24_000_000 // max(len(y), 1)) or 1))
            logger.info("compiling %d fused fit+predict+metric program(s), "
                        "%d concurrent", len(to_compile), workers)

            def compile_one(jf, x, w, v, st):
                try:
                    return jf.lower(x, yd, w, v, st).compile()
                except Exception as e:  # lint: broad-except — compile-service retry filter inspects the error
                    # one retry for transient compile-SERVICE failures
                    # only — deterministic XLA errors routinely mention
                    # while-"body" computations, so match the service's
                    # specific signatures, not loose substrings
                    txt = repr(e).lower()
                    if not any(s in txt for s in
                               ("remote_compile", "response body closed",
                                "http 5", "connection reset",
                                "connection refused")):
                        raise
                    logger.warning("compile failed (%r); retrying once",
                                   str(e)[:200])
                    _time.sleep(5.0)
                    return jf.lower(x, yd, w, v, st).compile()
            with cf.ThreadPoolExecutor(workers) as ex:
                futs = []
                for fi, ek, key, jf, st in to_compile:
                    fc, chunks = plans[fi]
                    futs.append((fi, ek, key, ex.submit(
                        compile_one, jf, xargs[fi], wd[:fc], vwd[:fc], st)))
                for fi, ek, key, fut in futs:
                    exe = fut.result()
                    fused[fi][ek] = exe
                    while len(_FUSED_EXE_CACHE) > 64:
                        _FUSED_EXE_CACHE.pop(
                            next(iter(_FUSED_EXE_CACHE)))   # FIFO evict
                    _FUSED_EXE_CACHE[key] = exe
            logger.info("compile phase done in %.2fs",
                        _time.perf_counter() - tc0)

        # dispatch every chunk of every family FIRST (async — the device
        # queues them back-to-back), then ONE batched metrics pull: per-
        # chunk synchronous pulls would pay a full link round-trip each
        # AND serialize device execution against host latency
        import time as _time
        td0 = _time.perf_counter()
        fused_out: Dict[int, Any] = {}
        for fi in fused:
            fc, chunks = plans[fi]
            fam = families[fi]
            outs = []
            for i0 in range(0, k_folds, fc):
                for ix, st, sd in chunks:
                    exe = fused[fi][(len(ix), sd)]
                    kflops = 0.0
                    if hasattr(fam, "analytic_flops") \
                            and isinstance(xargs[fi], dict) \
                            and _pallas_on():
                        # kernel path only: histogram dots live inside
                        # custom calls (invisible to cost analysis); on
                        # the XLA path they ARE counted — adding the
                        # analytic term there would double-count
                        kflops = fc * len(ix) * fam.analytic_flops(
                            len(y), X.shape[1], sd)
                    _count_dispatch(exe, kflops)
                    outs.append(exe(xargs[fi], yd, wd[i0:i0 + fc],
                                    vwd[i0:i0 + fc], st))
            fused_out[fi] = outs
        fused_np = jax.device_get(fused_out)
        logger.info("sweep dispatch+execute+pull: %.2fs",
                    _time.perf_counter() - td0)

        for fi, family in enumerate(families):
            k, g = len(splits), family.grid_size()

            if fi in fused:
                fc, chunks = plans[fi]
                full = np.zeros((k, g))
                ci = 0
                for i0 in range(0, k, fc):
                    for ix, st, sd in chunks:
                        full[i0:i0 + fc, ix] = np.asarray(fused_np[fi][ci])
                        ci += 1
                per_grid_metrics = full.T                       # [G, K]
            else:
                stacked = family.stack_grid()
                def fit_all(w_folds):
                    return jax.vmap(
                        lambda w: family.fit_batch(Xd, yd, w, stacked)
                    )(w_folds)

                params = jax.jit(fit_all)(wd)    # leading dims [K, G, ...]

                def predict_all(p):
                    return jax.vmap(
                        lambda pk: family.predict_batch(pk, Xd))(p)

                pred, _raw, prob = jax.jit(predict_all)(params)
                # slice off any zero-weight sharding padding rows
                pred = np.asarray(pred)[..., :n_orig]
                prob = np.asarray(prob)[:, :, :n_orig] \
                    if np.asarray(prob).ndim == 4 else np.asarray(prob)

                per_grid_metrics = np.zeros((g, k))
                for gi in range(g):
                    for ki in range(k):
                        vm = val_masks[ki]
                        per_grid_metrics[gi, ki] = _metric_value(
                            self.metric_name, self.task, y[vm],
                            pred[ki, gi][vm],
                            prob[ki, gi][vm] if prob.ndim == 4
                            else prob[ki, gi])
            means = per_grid_metrics.mean(axis=1)
            for gi in range(g):
                r = ValidationResult(
                    family_name=family.name, hparams=family.grid[gi],
                    grid_index=gi,
                    metric_values=per_grid_metrics[gi].tolist(),
                    mean_metric=float(means[gi]))
                summary.results.append(r)
                if best is None or sign * r.mean_metric > sign * best.mean_metric:
                    best = r
                    best_family = family
        summary.best = best
        assert best is not None and best_family is not None
        return best_family, best.hparams, summary

    def validate_per_fold(self, families: Sequence[ModelFamily],
                          fold_data: Sequence[Tuple[np.ndarray, np.ndarray,
                                                    np.ndarray, np.ndarray]],
                          mesh=None
                          ) -> Tuple[ModelFamily, Dict[str, Any],
                                     ValidatorSummary]:
        """Workflow-level CV engine: each fold carries its OWN feature
        matrix (in-fold feature engineering — cutDAG's *during* stages are
        re-fit per fold upstream of this call), so the fold axis cannot be
        vmapped over one X. Folds run sequentially; within a fold the
        (grid × metric) computation is the same fused jitted program as
        :meth:`validate` (cache-keyed per fold shape — folds of equal
        width share one compile).

        ``fold_data``: per fold ``(X, y, w_train, w_val)`` full-length
        arrays, optionally ``(…, binary_mask)`` with the fold matrix's
        one-hot column mask (each fold's engineered X has its own).
        Ref: ``OpCrossValidation.scala:89-116`` (per-fold dagCopy).
        """
        from ._pallas_hist import with_pallas_fallback
        from ._treefit import tree_mesh_scope
        snaps = _snapshot_grid_chunks(families)

        def attempt():
            _restore_grid_chunks(snaps)
            with tree_mesh_scope(mesh):
                return self._validate_per_fold_impl(families, fold_data,
                                                    mesh)
        return with_pallas_fallback(attempt)

    def _validate_per_fold_impl(self, families, fold_data, mesh=None):
        from ..evaluators.device_metrics import device_metric_fn
        from ..parallel.mesh import mesh_if_multi

        mesh = mesh_if_multi(mesh)   # degenerate 1×1 = single-device path
        summary = ValidatorSummary("WorkflowCV:" + self.validation_type,
                                   self.metric_name)
        best: Optional[ValidationResult] = None
        best_family: Optional[ModelFamily] = None
        sign = 1.0 if self.is_larger_better else -1.0
        mesh_key = tuple(sorted(mesh.shape.items())) if mesh is not None \
            else None
        n_shards = (mesh.shape.get("data", 1) if mesh is not None else 1)

        for family in families:
            metric_fn = device_metric_fn(
                self.task, self.metric_name,
                n_classes=getattr(family, "n_classes", 2))
            g = family.grid_size()
            per_grid = np.zeros((g, len(fold_data)))
            fit_host = None   # compiled once per family (host-metric path)
            for ki, fold in enumerate(fold_data):
                X, y, w_tr, w_val = fold[:4]
                if len(fold) > 4 and hasattr(family, "binary_mask"):
                    family.binary_mask = fold[4]
                if mesh is not None:
                    from ..parallel.mesh import shard_cv_inputs
                    Xd, yd, wd, vwd, _n0 = shard_cv_inputs(
                        mesh, X, y, w_tr[None, :], extra=w_val[None, :])
                else:
                    Xd, yd = jnp.asarray(X), jnp.asarray(y)
                    wd = jnp.asarray(w_tr[None, :])
                    vwd = jnp.asarray(w_val[None, :])
                if metric_fn is None:   # host-metric fallback
                    stacked = {k2: jnp.asarray(v) for k2, v in
                               family.stack_grid().items()}
                    if fit_host is None:
                        def fit_host(Xa, ya, wa, st, _f=family):
                            return _f.fit_batch(Xa, ya, wa, st)
                        fit_host = jax.jit(fit_host)
                    params = fit_host(Xd, yd, wd[0], stacked)
                    pred, _raw, prob = family.predict_batch(params, Xd)
                    pred, prob = np.asarray(pred), np.asarray(prob)
                    vm = w_val > 0
                    for gi in range(g):
                        per_grid[gi, ki] = _metric_value(
                            self.metric_name, self.task, y[vm],
                            pred[gi][:len(y)][vm],
                            prob[gi][:len(y)][vm] if prob.ndim == 3
                            else prob[gi])
                    continue
                _auto_chunks(family, len(y), n_shards, 1,
                             n_features=X.shape[1])
                # grid chunking at HOST level, one executable re-dispatched
                # per chunk (same rationale as validate's chunk_plan: the
                # in-program lax.map alternative compiles a slower program
                # and concentrates transients)
                gc = getattr(family, "grid_chunk", None) or g
                if hasattr(family, "grid_chunk"):
                    family.grid_chunk = None
                g_sizes = _chunk_sizes(g, gc)
                _finalize_tree_chunk(family, max(g_sizes))  # one fold live
                st_chunks = _grid_chunks(family, g_sizes)
                # bin each fold's engineered matrix once for all chunks
                Xarg = (family.device_prep(Xd)
                        if hasattr(family, "device_prep") else Xd)

                def fit_eval(X, y, w_folds, v_folds, stacked):
                    if isinstance(X, dict):
                        from .trees import _tree_rows, pad_rows_to
                        n_pad = _tree_rows(X)
                        if n_pad != y.shape[0]:
                            (y,) = pad_rows_to(n_pad, y)
                            w_folds, v_folds = [
                                jnp.concatenate(
                                    [a, jnp.zeros((a.shape[0],
                                                   n_pad - a.shape[1]),
                                                  a.dtype)], axis=1)
                                for a in (w_folds, v_folds)]

                    def per_fold(w, v):
                        params = family.fit_batch(X, y, w, stacked)
                        pred, _raw, prob = family.predict_batch(
                            params, X, on_train=True)
                        return jax.vmap(
                            lambda pg, prg: metric_fn(y, pg, prg, v)
                        )(pred, prob)
                    return jax.vmap(per_fold)(w_folds, v_folds)

                exe_by_width: Dict[int, Any] = {}
                for gw, st in zip(g_sizes, st_chunks):
                    if gw in exe_by_width:
                        continue
                    key = (family.trace_signature(), self.task,
                           self.metric_name, mesh_key, ("per_fold", gw),
                           tuple((tuple(a.shape), str(a.dtype)) for a in
                                 jax.tree_util.tree_leaves(
                                     (Xarg, yd, wd, vwd))))
                    exe = _FUSED_EXE_CACHE.get(key)
                    if exe is None:
                        exe = jax.jit(fit_eval).lower(
                            Xarg, yd, wd, vwd, st).compile()
                        while len(_FUSED_EXE_CACHE) > 64:
                            _FUSED_EXE_CACHE.pop(next(iter(_FUSED_EXE_CACHE)))
                        _FUSED_EXE_CACHE[key] = exe
                    exe_by_width[gw] = exe
                for gw, _st in zip(g_sizes, st_chunks):
                    kflops = (gw * family.analytic_flops(len(y), X.shape[1])
                              if hasattr(family, "analytic_flops")
                              and isinstance(Xarg, dict)
                              and _pallas_on() else 0.0)
                    _count_dispatch(exe_by_width[gw], kflops)
                outs = [exe_by_width[gw](Xarg, yd, wd, vwd, st)
                        for gw, st in zip(g_sizes, st_chunks)]
                per_grid[:, ki] = np.concatenate(
                    [np.asarray(o)[0] for o in outs])
            means = per_grid.mean(axis=1)
            for gi in range(g):
                r = ValidationResult(
                    family_name=family.name, hparams=family.grid[gi],
                    grid_index=gi, metric_values=per_grid[gi].tolist(),
                    mean_metric=float(means[gi]))
                summary.results.append(r)
                if best is None or sign * r.mean_metric > sign * best.mean_metric:
                    best = r
                    best_family = family
        summary.best = best
        assert best is not None and best_family is not None
        return best_family, best.hparams, summary


class CrossValidation(_ValidatorBase):
    """k-fold CV over fold masks (OpCrossValidation.scala)."""

    validation_type = "CrossValidation"

    def __init__(self, num_folds: int = 3, metric_name: str = "AuROC",
                 task: str = "binary", seed: int = 42, stratify: bool = False):
        super().__init__(metric_name, task, seed, stratify)
        self.num_folds = num_folds

    def _splits(self, y: np.ndarray):
        n = len(y)
        rng = np.random.default_rng(self.seed)
        fold_of = np.zeros(n, dtype=np.int64)
        if self.stratify and self.task in ("binary", "multiclass"):
            for c in np.unique(y):
                idx = np.nonzero(y == c)[0]
                idx = rng.permutation(idx)
                fold_of[idx] = np.arange(len(idx)) % self.num_folds
        else:
            fold_of = rng.permutation(n) % self.num_folds
        out = []
        for kf in range(self.num_folds):
            val = (fold_of == kf)
            out.append(((~val).astype(np.float64), val.astype(np.float64)))
        return out


class TrainValidationSplit(_ValidatorBase):
    """Single split (OpTrainValidationSplit.scala)."""

    validation_type = "TrainValidationSplit"

    def __init__(self, train_ratio: float = 0.75, metric_name: str = "AuROC",
                 task: str = "binary", seed: int = 42, stratify: bool = False):
        super().__init__(metric_name, task, seed, stratify)
        self.train_ratio = train_ratio

    def _splits(self, y: np.ndarray):
        n = len(y)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_train = int(round(n * self.train_ratio))
        train = np.zeros(n, dtype=np.float64)
        train[perm[:n_train]] = 1.0
        return [(train, 1.0 - train)]
