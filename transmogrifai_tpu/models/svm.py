"""Linear SVC + multilayer perceptron classifier stages.

Parity: ``OpLinearSVC`` (``core/.../impl/classification/OpLinearSVC.scala``,
166 LoC) and ``OpMultilayerPerceptronClassifier`` (149 LoC) — fit natively
in JAX instead of wrapping MLlib.

LinearSVC uses the squared hinge (smooth; Spark's OWLQN hinge differs only
in the loss corner) with L2 regularization, solved by accelerated gradient
descent on standardized features. Like Spark's LinearSVC the model has no
probability column; ``prob`` is a monotone sigmoid of the margin so
threshold metrics (AuROC/AuPR) are still well-defined.

The MLP trains full-batch Adam on cross-entropy; hidden ``layers`` are
structural (part of the compiled shape), so families group grid points by
layer spec the same way trees group by depth.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import register_stage
from ._jaxfit import _fista, _power_iter_sq_norm, standardize_stats
from .base import (ModelFamily, PredictorEstimator, PredictorModel,
                   extract_xy, pull_f64)

__all__ = ["OpLinearSVC", "LinearSVCModel", "LinearSVCFamily",
           "OpMultilayerPerceptronClassifier", "MLPModel", "MLPFamily"]


def _f(x):
    return np.asarray(x, dtype=np.float64)


# ---------------------------------------------------------------------------
# Linear SVC
# ---------------------------------------------------------------------------

def fit_linear_svc(X, y, w, reg_param, max_iter: int = 64):
    """Squared-hinge L2 SVM → (coef [d], intercept). y ∈ {0, 1}."""
    mean, std = standardize_stats(X, w)
    Xs = (X - mean) / std
    ypm = 2.0 * y - 1.0
    wsum = jnp.maximum(w.sum(), 1e-12)
    d = X.shape[1]

    def grad(params):
        beta, b = params[:d], params[d]
        m = Xs @ beta + b
        slack = jnp.maximum(1.0 - ypm * m, 0.0)
        g_m = w * (-2.0 * ypm * slack) / wsum
        g_beta = Xs.T @ g_m + reg_param * beta
        return jnp.concatenate([g_beta, g_m.sum()[None]])

    lip = 2.0 * _power_iter_sq_norm(Xs, w) + reg_param + 1.0
    params0 = jnp.zeros((d + 1,), X.dtype)
    params = _fista(grad, lambda p, s: p, params0, 1.0 / lip, max_iter)
    coef = params[:d] / std
    intercept = params[d] - (coef * mean).sum()
    return coef, intercept


def predict_linear_svc(coef, intercept, X):
    m = X @ coef + intercept
    raw = jnp.stack([-m, m], axis=1)
    p1 = jax.nn.sigmoid(m)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    pred = (m > 0.0).astype(X.dtype)
    return pred, raw, prob


@register_stage
class LinearSVCModel(PredictorModel):
    operation_name = "linearSVC"

    def __init__(self, coefficients=None, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = (_f(coefficients)
                             if coefficients is not None else None)
        self.intercept = float(intercept) if intercept is not None else 0.0

    def predict_device(self, X):
        return predict_linear_svc(jnp.asarray(self.coefficients),
                                  self.intercept, X)

    def predict_arrays(self, X):
        return pull_f64(self.predict_device(jnp.asarray(X)))

    def get_model_state(self):
        return {"coefficients": self.coefficients,
                "intercept": self.intercept}

    def summary(self):
        return {"model": "LinearSVC",
                "numFeatures": int(self.coefficients.shape[0])}


@register_stage
class OpLinearSVC(PredictorEstimator):
    operation_name = "linearSVC"

    def __init__(self, reg_param: float = 0.0, max_iter: int = 64,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.max_iter = max_iter

    def fit_columns(self, store) -> LinearSVCModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        coef, b = fit_linear_svc(jnp.asarray(X), jnp.asarray(y),
                                 jnp.ones((X.shape[0],)),
                                 self.reg_param, self.max_iter)
        return LinearSVCModel(coef, float(b))


class LinearSVCFamily(ModelFamily):
    """Grid = Regularization (DefaultSelectorParams.Regularization)."""

    name = "OpLinearSVC"
    default_grid = [{"regParam": r} for r in (0.001, 0.01, 0.1, 0.2)]

    def __init__(self, grid=None, max_iter: int = 64, n_classes: int = 2,
                 **fixed):
        super().__init__(grid, **fixed)
        self.max_iter = max_iter
        self.n_classes = n_classes   # binary only; kept for selector protocol

    def param_defaults(self):
        return {"regParam": 0.0}

    def fit_batch(self, X, y, w, stacked):
        reg = jnp.asarray(stacked["regParam"], dtype=X.dtype)
        return jax.vmap(lambda r: fit_linear_svc(
            X, y, w, r, self.max_iter))(reg)

    def predict_batch(self, params, X, on_train: bool = False):
        coef, b = params
        return jax.vmap(predict_linear_svc, in_axes=(0, 0, None))(coef, b, X)

    def realize(self, params, hparams) -> LinearSVCModel:
        coef, b = params
        return LinearSVCModel(coef, float(b))


# ---------------------------------------------------------------------------
# Multilayer perceptron
# ---------------------------------------------------------------------------

def _mlp_init(key, sizes: Tuple[int, ...], dtype):
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        scale = jnp.sqrt(2.0 / fan_in).astype(dtype)
        params.append((jax.random.normal(k, (fan_in, fan_out), dtype) * scale,
                       jnp.zeros((fan_out,), dtype)))
    return params


def _mlp_logits(params, X):
    h = X
    for i, (W, b) in enumerate(params):
        h = h @ W + b
        if i < len(params) - 1:
            h = jnp.tanh(h)       # Spark MLP uses sigmoid-ish; tanh trains better
    return h


def fit_mlp(X, y, w, sizes: Tuple[int, ...], step_size, max_iter: int,
            seed: int = 3):
    """Full-batch Adam on weighted cross-entropy → list[(W, b)]."""
    n_classes = sizes[-1]
    params = _mlp_init(jax.random.PRNGKey(seed), sizes, X.dtype)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=X.dtype)
    wsum = jnp.maximum(w.sum(), 1e-12)

    def loss(p):
        logp = jax.nn.log_softmax(_mlp_logits(p, X))
        return -(w * (onehot * logp).sum(-1)).sum() / wsum

    grad_fn = jax.grad(loss)
    b1, b2, eps = 0.9, 0.999, 1e-8
    m0 = jax.tree_util.tree_map(jnp.zeros_like, params)
    v0 = jax.tree_util.tree_map(jnp.zeros_like, params)

    def body(i, state):
        p, m, v = state
        g = grad_fn(p)
        m = jax.tree_util.tree_map(lambda a, b_: b1 * a + (1 - b1) * b_, m, g)
        v = jax.tree_util.tree_map(lambda a, b_: b2 * a + (1 - b2) * b_ ** 2,
                                   v, g)
        t = i.astype(X.dtype) + 1.0
        mhat = jax.tree_util.tree_map(lambda a: a / (1 - b1 ** t), m)
        vhat = jax.tree_util.tree_map(lambda a: a / (1 - b2 ** t), v)
        p = jax.tree_util.tree_map(
            lambda a, mh, vh: a - step_size * mh / (jnp.sqrt(vh) + eps),
            p, mhat, vhat)
        return p, m, v
    params, _, _ = jax.lax.fori_loop(0, max_iter, body, (params, m0, v0))
    return params


def predict_mlp(params, X):
    logits = _mlp_logits(params, X)
    prob = jax.nn.softmax(logits, axis=-1)
    pred = jnp.argmax(logits, axis=-1).astype(X.dtype)
    return pred, logits, prob


@register_stage
class MLPModel(PredictorModel):
    operation_name = "mlp"

    def __init__(self, layers: Optional[List[int]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.layers = list(layers or [])
        self.weights: List[Tuple[np.ndarray, np.ndarray]] = []

    def predict_device(self, X):
        params = [(jnp.asarray(W), jnp.asarray(b)) for W, b in self.weights]
        return predict_mlp(params, X)

    def predict_arrays(self, X):
        return pull_f64(self.predict_device(jnp.asarray(X)))

    def get_model_state(self):
        state: Dict[str, Any] = {"layers": np.asarray(self.layers)}
        for i, (W, b) in enumerate(self.weights):
            state[f"W_{i}"] = _f(W)
            state[f"b_{i}"] = _f(b)
        return state

    def apply_model_state(self, state) -> None:
        self.layers = [int(v) for v in np.asarray(state["layers"])]
        self.weights = []
        i = 0
        while f"W_{i}" in state:
            self.weights.append((np.asarray(state[f"W_{i}"]),
                                 np.asarray(state[f"b_{i}"])))
            i += 1

    def summary(self):
        return {"model": "MultilayerPerceptron", "layers": self.layers}


@register_stage
class OpMultilayerPerceptronClassifier(PredictorEstimator):
    operation_name = "mlp"

    def __init__(self, hidden_layers: Optional[List[int]] = None,
                 step_size: float = 0.03, max_iter: int = 100,
                 seed: int = 3, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.hidden_layers = list(hidden_layers or [10])
        self.step_size = step_size
        self.max_iter = max_iter
        self.seed = seed

    def fit_columns(self, store) -> MLPModel:
        X, y = extract_xy(store, self.label_name, self.features_name)
        n_classes = max(int(y.max()) + 1 if len(y) else 2, 2)
        sizes = (X.shape[1], *self.hidden_layers, n_classes)
        params = jax.jit(lambda X, y, w: fit_mlp(
            X, y, w, sizes, self.step_size, self.max_iter, self.seed))(
            jnp.asarray(X), jnp.asarray(y), jnp.ones((X.shape[0],)))
        model = MLPModel(layers=list(sizes))
        model.weights = [(_f(W), _f(b)) for W, b in params]
        return model


class MLPFamily(ModelFamily):
    """Grid over stepSize/maxIter (traced); hidden ``layers`` structural —
    grid points grouped by layer spec like trees group by depth."""

    name = "OpMultilayerPerceptronClassifier"
    default_grid = [{"stepSize": s, "layers": (10,)} for s in (0.01, 0.03)]

    def __init__(self, grid=None, n_classes: int = 2, max_iter: int = 100,
                 seed: int = 3, **fixed):
        super().__init__(grid, **fixed)
        self.n_classes = n_classes
        self.max_iter = max_iter
        self.seed = seed

    def param_defaults(self):
        return {"stepSize": 0.03, "layers": (10,)}

    def fit_batch(self, X, y, w, stacked):
        layer_specs = [tuple(g.get("layers", (10,))) for g in self.grid]
        steps = np.asarray([g.get("stepSize", 0.03) for g in self.grid])
        order: List[int] = []
        outs = []
        for spec in sorted(set(layer_specs)):
            idxs = [i for i, s in enumerate(layer_specs) if s == spec]
            order += idxs
            sizes = (X.shape[1], *spec, self.n_classes)
            st = jnp.asarray(steps[idxs], X.dtype)
            outs.append(jax.vmap(lambda s, _sz=sizes: fit_mlp(
                X, y, w, _sz, s, self.max_iter, self.seed))(st))
        if len(outs) == 1:
            cat = outs[0]
        else:
            # heterogenous layer shapes can't concat — restrict to one spec
            raise ValueError(
                "MLPFamily grid must use a single hidden-layer spec per "
                "family; split specs into separate families")
        inv = jnp.argsort(jnp.asarray(order))
        return jax.tree_util.tree_map(lambda a: jnp.take(a, inv, axis=0), cat)

    def predict_batch(self, params, X, on_train: bool = False):
        return jax.vmap(lambda p: predict_mlp(p, X))(params)

    def realize(self, params, hparams) -> MLPModel:
        spec = tuple(hparams.get("layers", (10,)))
        weights = [(np.asarray(W), np.asarray(b)) for W, b in params]
        sizes = (weights[0][0].shape[0], *spec, self.n_classes)
        model = MLPModel(layers=list(sizes))
        model.weights = weights
        return model
