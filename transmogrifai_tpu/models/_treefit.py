"""Histogram-based decision-tree learning in pure JAX — the TPU-native
replacement for Spark MLlib trees and xgboost4j's C++/JNI core
(reference: ``OpRandomForestClassifier.scala``, ``OpGBTClassifier.scala``,
``OpXGBoostClassifier.scala:46``; Rabit allreduce ``:74-90``).

Design (SURVEY §7 step 8): **static shapes everywhere** so the whole
(fold × hyperparameter) grid vmaps onto the mesh.

* Features are quantile-binned once per fit (``n_bins=32``, Spark's
  ``maxBins`` default) — binning depends only on X, so under a fold-vmap
  XLA computes it once.
* A tree is grown **level-wise** to a static ``max_depth``: every sample
  carries a node index in [0, 2^d); per level one ``segment_sum`` builds the
  [nodes, features, bins, channels] histogram (Rabit's allreduce becomes a
  ``psum`` when the batch axis is sharded), a cumulative sum over bins
  scores every (feature, threshold) candidate, and an argmax picks the
  split. Nodes that stop splitting route all samples left via a dummy
  (+inf threshold) split, so the fixed-depth routing stays correct.
* Hyperparameters that only gate values (minInstancesPerNode, minInfoGain,
  eta, minChildWeight, numTrees/numRound, subsample rate) are *traced*
  scalars → they can vary inside one vmapped grid. Only ``maxDepth`` is
  structural; families group grid points by it (models/trees.py).
* Ensembles run under ``lax.scan`` (bounded memory; XLA pipelines the
  per-tree work); RF bootstraps with Poisson(subsample) weights.

Tree layout: level-order arrays ``feat``/``thr`` of length 2^D − 1 and
``leaf`` of shape [2^D, K]; routing is ``node = 2*node + (x[feat] > thr)``.
"""
from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_EPS = 1e-12
_NEG = -1e30


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

def quantile_bin_edges(X: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Per-feature interior quantile edges → [F, n_bins - 1]."""
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T


def binarize(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """bin[i, f] = #{edges[f] < x[i, f]} ∈ [0, n_bins-1]; bin ≤ t ⟺
    x ≤ edges[f, t], matching the stored split threshold."""
    def per_feature(col, e):
        return jnp.searchsorted(e, col, side="left")
    return jax.vmap(per_feature, in_axes=(1, 0), out_axes=1)(
        X, edges).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Split criteria: (total, left, right) [-1 channel is raw count] → gain
# ---------------------------------------------------------------------------

def variance_split(total, left, right):
    """Spark Variance impurity gain: imp(P) − wL/W·imp(L) − wR/W·imp(R).
    Channels: (w, w·y, w·y², count)."""
    def imp(s):
        w = jnp.maximum(s[..., 0], _EPS)
        return s[..., 2] / w - (s[..., 1] / w) ** 2
    W = jnp.maximum(total[..., 0], _EPS)
    return imp(total) - (left[..., 0] / W) * imp(left) \
        - (right[..., 0] / W) * imp(right)


def variance_leaf(s):
    """Weighted mean target → [1]."""
    return (s[..., 1] / jnp.maximum(s[..., 0], _EPS))[..., None]


def gini_split(total, left, right):
    """Spark Gini gain. Channels: (per-class weight … , count)."""
    def imp(s):
        cls = s[..., :-1]
        w = jnp.maximum(cls.sum(-1), _EPS)
        p = cls / w[..., None]
        return 1.0 - (p * p).sum(-1)
    W = jnp.maximum(total[..., :-1].sum(-1), _EPS)
    wl = left[..., :-1].sum(-1)
    wr = right[..., :-1].sum(-1)
    return imp(total) - (wl / W) * imp(left) - (wr / W) * imp(right)


def gini_leaf(s):
    """Per-class probabilities → [C]."""
    cls = s[..., :-1]
    return cls / jnp.maximum(cls.sum(-1, keepdims=True), _EPS)


def make_xgb_split(lam, min_child_weight):
    """XGBoost gain: ½(G_L²/(H_L+λ) + G_R²/(H_R+λ) − G²/(H+λ)).
    Channels: (g, h, count). min_child_weight masks on hessian mass."""
    def split(total, left, right):
        def score(s):
            return s[..., 0] ** 2 / (s[..., 1] + lam + _EPS)
        gain = 0.5 * (score(left) + score(right) - score(total))
        ok = (left[..., 1] >= min_child_weight) & \
             (right[..., 1] >= min_child_weight)
        return jnp.where(ok, gain, _NEG)
    return split


def make_xgb_leaf(lam):
    def leaf(s):
        return (-s[..., 0] / (s[..., 1] + lam + _EPS))[..., None]
    return leaf


# ---------------------------------------------------------------------------
# Level-wise tree growing
# ---------------------------------------------------------------------------

def _level_hist(stats, node, Xb, n_nodes, n_bins, feature_chunk: int = 128):
    """[n, C] sample stats → [n_nodes, F, n_bins, C] histograms.

    hist[s,f,b,c] = Σ_i 1[node_i=s]·1[Xb_if=b]·stats_ic, computed as one
    MXU matmul per feature chunk: (one_hot(node) ⊗ stats)ᵀ @ one_hot(bins).
    A vmapped segment_sum here would materialize the full [F, n, S] one-hot
    scatter in HBM (28 GB at Titanic scale under the fold×grid vmaps);
    chunking bounds the peak at n·chunk·B floats, and the chunk loop is a
    lax.map, which stays sequential under outer vmaps.
    """
    n, F = Xb.shape
    C = stats.shape[1]
    NS = (jax.nn.one_hot(node, n_nodes, dtype=stats.dtype)[:, :, None]
          * stats[:, None, :]).reshape(n, n_nodes * C)
    Fc = min(feature_chunk, F)
    n_chunks = -(-F // Fc)
    pad = n_chunks * Fc - F
    Xp = jnp.pad(Xb, ((0, 0), (0, pad)))
    chunks = Xp.reshape(n, n_chunks, Fc).transpose(1, 0, 2)   # [nc, n, Fc]

    def chunk_hist(Xc):
        Bh = jax.nn.one_hot(Xc, n_bins,
                            dtype=stats.dtype).reshape(n, Fc * n_bins)
        h = NS.T @ Bh                                  # [nodes*C, Fc*B]
        return h.reshape(n_nodes, C, Fc, n_bins).transpose(0, 2, 3, 1)

    hist = jax.lax.map(chunk_hist, chunks)             # [nc, nodes, Fc, B, C]
    hist = hist.transpose(1, 0, 2, 3, 4).reshape(
        n_nodes, n_chunks * Fc, n_bins, C)
    return hist[:, :F]


def grow_tree(Xb: jnp.ndarray, edges: jnp.ndarray, stats: jnp.ndarray,
              split_fn: Callable, leaf_fn: Callable, max_depth: int,
              n_bins: int, min_instances, min_info_gain,
              feat_mask=None, max_active_nodes: int = 128
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Grow one tree level-wise; returns (feat [2^D−1], thr [2^D−1],
    leaf [2^D, K], node [n] final sample→leaf assignment).

    ``min_instances`` / ``min_info_gain`` may be traced scalars.
    ``feat_mask`` [F] bool restricts candidate features (RF column
    subsampling).

    Active-node compaction: a dense level-wise build would need a
    [2^d, F, B, C] histogram per level — 1.5 GB per grid instance at depth
    12 — even though most of those nodes are empty. Instead each level keeps
    at most ``max_active_nodes`` live nodes in a compact slot space (ranked
    by parent split gain; the histogram/gain tensors stay [A, F, B, C]
    regardless of depth). With min-instances ≥ n/A this is exact; beyond
    that the lowest-gain subtrees are truncated, which matches leaf-wise
    growers' behavior under a node budget.
    """
    n, F = Xb.shape
    B = n_bins
    g = jnp.zeros((n,), jnp.int32)          # per-level node id ∈ [0, 2^d)
    slot = jnp.zeros((n,), jnp.int32)       # compact active slot; ==A → idle
    gpos = jnp.zeros((1,), jnp.int32)       # slot → per-level node id
    alive = jnp.ones((1,), bool)
    feats, thrs = [], []
    for d in range(max_depth):
        W = 1 << d                          # dense level width
        A = min(W, max_active_nodes)        # compact slot count
        # histogram over slots; idle samples (slot ≥ A) one-hot to zero
        hist = _level_hist(stats, slot, Xb, A, B)     # [A, F, B, C]
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, :, -1, :][:, :, None, :]
        left = cum[:, :, :-1, :]                      # split: bins ≤ t
        right = total - left
        gain = split_fn(total, left, right)           # [A, F, B-1]
        ok = (left[..., -1] >= min_instances) & \
             (right[..., -1] >= min_instances)
        if feat_mask is not None:
            ok = ok & feat_mask[None, :, None]
        gain = jnp.where(ok, gain, _NEG)
        flat = gain.reshape(A, F * (B - 1))
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        do_split = alive & (best_gain >= jnp.maximum(min_info_gain, 1e-10))
        f_idx = jnp.where(do_split, best // (B - 1), 0).astype(jnp.int32)
        t_idx = jnp.where(do_split, best % (B - 1), 0).astype(jnp.int32)
        thr = jnp.where(do_split, edges[f_idx, t_idx], jnp.inf)

        # record into the dense level arrays (idle node ids scatter-drop)
        pos = jnp.where(alive, gpos, W)
        feat_lvl = jnp.zeros((W,), jnp.int32).at[pos].set(f_idx, mode="drop")
        thr_lvl = jnp.full((W,), jnp.inf).at[pos].set(thr, mode="drop")
        feats.append(feat_lvl)
        thrs.append(thr_lvl)

        # route samples (idle samples keep going left: thr = +inf)
        slot_c = jnp.minimum(slot, A)                 # clamp for gathers
        f_s = jnp.concatenate([f_idx, jnp.zeros((1,), jnp.int32)])[slot_c]
        t_s = jnp.concatenate([t_idx, jnp.zeros((1,), jnp.int32)])[slot_c]
        s_s = jnp.concatenate([do_split, jnp.zeros((1,), bool)])[slot_c]
        xb = jnp.take_along_axis(Xb, f_s[:, None], axis=1)[:, 0]
        go_right = jnp.where(s_s, xb > t_s, False)
        g = 2 * g + go_right.astype(jnp.int32)

        # next level: rank splitting slots by gain, allocate child slots
        A2 = min(2 * W, max_active_nodes)
        rank = jnp.argsort(jnp.where(do_split, -best_gain, jnp.inf))
        inv = jnp.zeros((A,), jnp.int32).at[rank].set(
            jnp.arange(A, dtype=jnp.int32))
        parent_ok = do_split & (inv < A2 // 2)
        lchild = jnp.where(parent_ok, 2 * inv, A2)
        child_slot = jnp.concatenate(
            [jnp.stack([lchild, lchild + 1], axis=1),
             jnp.full((1, 2), A2, jnp.int32)])        # idle row
        slot = child_slot[slot_c, go_right.astype(jnp.int32)]
        gpos = (jnp.full((A2,), 0, jnp.int32)
                .at[lchild].set(2 * gpos, mode="drop")
                .at[jnp.where(parent_ok, lchild + 1, A2)]
                .set(2 * gpos + 1, mode="drop"))
        alive = (jnp.zeros((A2,), bool)
                 .at[lchild].set(parent_ok, mode="drop")
                 .at[jnp.where(parent_ok, lchild + 1, A2)]
                 .set(parent_ok, mode="drop"))

    # leaf values: one MXU matmul instead of a vmapped scatter
    onehot_leaf = jax.nn.one_hot(g, 1 << max_depth, dtype=stats.dtype)
    leaf_stats = onehot_leaf.T @ stats
    leaf = leaf_fn(leaf_stats)
    return jnp.concatenate(feats), jnp.concatenate(thrs), leaf, g


def predict_tree(feat, thr, leaf, X, max_depth: int) -> jnp.ndarray:
    """Route [n, F] rows through one tree → [n, K] leaf values."""
    n = X.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    off = 0
    for d in range(max_depth):
        f = feat[off + node]
        t = thr[off + node]
        x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        node = 2 * node + (x > t).astype(jnp.int32)
        off += 1 << d
    return leaf[node]


def predict_ensemble(feat, thr, leaf, tree_w, X, max_depth: int
                     ) -> jnp.ndarray:
    """Weighted sum over [T, …] stacked trees → [n, K]."""
    def body(acc, tree):
        f, t, l, w = tree
        return acc + w * predict_tree(f, t, l, X, max_depth), None
    init = jnp.zeros((X.shape[0], leaf.shape[-1]), leaf.dtype)
    out, _ = lax.scan(body, init, (feat, thr, leaf, tree_w))
    return out


# ---------------------------------------------------------------------------
# Random forest
# ---------------------------------------------------------------------------

def _feature_masks(key, n_trees: int, n_feat: int, k: int) -> jnp.ndarray:
    """[T, F] bool, exactly-k random features per tree (featureSubsetStrategy
    'auto' — per-tree rather than Spark's per-node, same spirit)."""
    if k >= n_feat:
        return jnp.ones((n_trees, n_feat), bool)
    u = jax.random.uniform(key, (n_trees, n_feat))
    kth = jnp.sort(u, axis=1)[:, k - 1][:, None]
    return u <= kth


def fit_forest(X, y, w, *, task: str, n_classes: int, n_trees: int,
               max_depth: int, n_bins: int, min_instances, min_info_gain,
               num_trees_used, subsample_rate, seed: int = 7):
    """Random forest via scanned bootstrap trees.

    Traced: min_instances, min_info_gain, num_trees_used (≤ n_trees,
    masks extra trees), subsample_rate. Returns params dict."""
    key = jax.random.PRNGKey(seed)
    k_boot, k_feat = jax.random.split(key)
    n, F = X.shape
    edges = quantile_bin_edges(X, n_bins)
    Xb = binarize(X, edges)
    boot = jax.random.poisson(
        k_boot, jnp.broadcast_to(jnp.asarray(subsample_rate, jnp.float32),
                                 ()), (n_trees, n)).astype(X.dtype)
    if n_trees == 1:
        boot = jnp.ones((1, n), X.dtype)          # single DT: no bootstrap
        fmask = jnp.ones((1, F), bool)
    else:
        k = max(1, int(round(np.sqrt(F))) if task == "classification"
                else max(1, F // 3))
        fmask = _feature_masks(k_feat, n_trees, F, k)

    if task == "classification":
        onehot = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=X.dtype)
        def make_stats(wt):
            return jnp.concatenate(
                [onehot * wt[:, None], (wt > 0).astype(X.dtype)[:, None]], 1)
        split_fn, leaf_fn = gini_split, gini_leaf
    else:
        def make_stats(wt):
            return jnp.stack(
                [wt, wt * y, wt * y * y, (wt > 0).astype(X.dtype)], axis=1)
        split_fn, leaf_fn = variance_split, variance_leaf

    def body(_, per_tree):
        bw, fm = per_tree
        wt = w * bw
        feat, thr, leaf, _node = grow_tree(
            Xb, edges, make_stats(wt), split_fn, leaf_fn, max_depth,
            n_bins, min_instances, min_info_gain, feat_mask=fm)
        return None, (feat, thr, leaf)
    _, (feat, thr, leaf) = lax.scan(body, None, (boot, fmask))
    tree_w = (jnp.arange(n_trees) < num_trees_used).astype(X.dtype)
    tree_w = tree_w / jnp.maximum(tree_w.sum(), 1.0)
    return {"feat": feat, "thr": thr, "leaf": leaf, "tree_w": tree_w}


# ---------------------------------------------------------------------------
# Gradient boosting (Spark GBT: first-order, variance splits on residuals)
# ---------------------------------------------------------------------------

def fit_gbt(X, y, w, *, task: str, n_rounds: int, max_depth: int,
            n_bins: int, min_instances, min_info_gain, step_size,
            num_rounds_used):
    """Spark-style GBT: each round fits a weighted regression tree to the
    pseudo-residuals; classification uses logloss on y' ∈ {−1,+1} with
    margin F, prob = σ(2F) (GBTClassificationModel semantics)."""
    edges = quantile_bin_edges(X, n_bins)
    Xb = binarize(X, edges)
    n = X.shape[0]
    ypm = 2.0 * y - 1.0

    def residual(Fm):
        if task == "classification":
            return 2.0 * ypm / (1.0 + jnp.exp(2.0 * ypm * Fm))
        return y - Fm

    def body(Fm, t):
        r = residual(Fm)
        stats = jnp.stack([w, w * r, w * r * r,
                           (w > 0).astype(X.dtype)], axis=1)
        feat, thr, leaf, node = grow_tree(
            Xb, edges, stats, variance_split, variance_leaf, max_depth,
            n_bins, min_instances, min_info_gain)
        use = (t < num_rounds_used).astype(X.dtype)
        scale = use * step_size
        Fm = Fm + scale * leaf[node][:, 0]
        return Fm, (feat, thr, leaf * scale)
    F0 = jnp.zeros((n,), X.dtype)
    _, (feat, thr, leaf) = lax.scan(body, F0, jnp.arange(n_rounds))
    return {"feat": feat, "thr": thr, "leaf": leaf,
            "tree_w": jnp.ones((n_rounds,), X.dtype)}


# ---------------------------------------------------------------------------
# XGBoost-equivalent (second-order, L2 leaf regularization)
# ---------------------------------------------------------------------------

def fit_xgb(X, y, w, *, task: str, n_rounds: int, max_depth: int,
            n_bins: int, eta, lam, min_child_weight, num_rounds_used):
    """Second-order boosting: g/h from logistic (classification) or squared
    (regression) loss; leaf = −G/(H+λ) (xgboost4j replacement — Rabit's
    histogram allreduce becomes psum under a sharded batch axis)."""
    edges = quantile_bin_edges(X, n_bins)
    Xb = binarize(X, edges)
    n = X.shape[0]
    split_fn = make_xgb_split(lam, min_child_weight)
    leaf_fn = make_xgb_leaf(lam)

    def grads(Fm):
        if task == "classification":
            p = jax.nn.sigmoid(Fm)
            return w * (p - y), w * jnp.maximum(p * (1.0 - p), 1e-6)
        return w * (Fm - y), w

    def body(Fm, t):
        g, h = grads(Fm)
        stats = jnp.stack([g, h, (w > 0).astype(X.dtype)], axis=1)
        feat, thr, leaf, node = grow_tree(
            Xb, edges, stats, split_fn, leaf_fn, max_depth, n_bins,
            jnp.asarray(0.0, X.dtype), jnp.asarray(-1e29, X.dtype))
        use = (t < num_rounds_used).astype(X.dtype)
        scale = use * eta
        Fm = Fm + scale * leaf[node][:, 0]
        return Fm, (feat, thr, leaf * scale)
    F0 = jnp.zeros((n,), X.dtype)
    _, (feat, thr, leaf) = lax.scan(body, F0, jnp.arange(n_rounds))
    return {"feat": feat, "thr": thr, "leaf": leaf,
            "tree_w": jnp.ones((n_rounds,), X.dtype)}


# ---------------------------------------------------------------------------
# Ensemble → Prediction triple (pred, raw, prob)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_depth", "n_classes"))
def predict_rf_classification(params, X, max_depth: int, n_classes: int):
    probs = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                             params["tree_w"], X, max_depth)
    probs = probs / jnp.maximum(probs.sum(-1, keepdims=True), _EPS)
    pred = jnp.argmax(probs, axis=-1).astype(X.dtype)
    return pred, probs, probs


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_rf_regression(params, X, max_depth: int):
    out = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                           params["tree_w"], X, max_depth)[:, 0]
    empty = jnp.zeros((X.shape[0], 0), X.dtype)
    return out, empty, empty


@functools.partial(jax.jit, static_argnames=("max_depth", "margin_scale"))
def predict_margin_classification(params, X, max_depth: int,
                                  margin_scale: float = 1.0):
    """GBT (margin_scale=2: prob = σ(2F)) and XGB (=1) binary heads."""
    m = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                         params["tree_w"], X, max_depth)[:, 0]
    p1 = jax.nn.sigmoid(margin_scale * m)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-m, m], axis=1)
    pred = (p1 > 0.5).astype(X.dtype)
    return pred, raw, prob


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_margin_regression(params, X, max_depth: int):
    out = predict_ensemble(params["feat"], params["thr"], params["leaf"],
                           params["tree_w"], X, max_depth)[:, 0]
    empty = jnp.zeros((X.shape[0], 0), X.dtype)
    return out, empty, empty
